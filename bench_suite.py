#!/usr/bin/env python
"""Per-config benchmark suite: one JSON line per BASELINE.json config.

`bench.py` is the driver-facing headline (sustained NVMe→HBM streaming);
this suite covers the full config list so every capability row has a
number:

  1 raw     — raw sequential engine read, payload discarded (ssd2gpu_test
              analogue, SURVEY.md §3.4)
  2 arrow   — Arrow column file → single-chip device columns
  3 loader  — WebDataset shards → sharded dataloader → device batches
  4 weights — safetensors shards → lazy sharded HBM param load
  5 sql     — Parquet row-group scan → on-device GROUP BY aggregate
  6 decode  — autoregressive generation, tokens/sec (compute row)
  7 train   — train-step model-FLOPs utilisation (compute row)
  8 multi   — N concurrent streams through one engine vs serial (the
              striped-raid0 scaling story's engine-side requirement)
  9 ckpt    — checkpoint save bandwidth, durable GiB/s (inverse path;
              no read-derived ceiling → vs_baseline null)
 10 kvoff   — SSD-backed decode, tokens/sec with most KV history on
              NVMe (models/kv_offload.py; deliberately storage-bound —
              the capability is decode BEYOND HBM, its cost is the
              stream → vs_baseline null)
 11 serve   — continuous-batching aggregate throughput, tokens/sec
              across mixed-length requests on fixed slots
              (models/serving.py; compute row → vs_baseline null)
 12 zstd    — zstd-compressed Parquet scan, direct path vs pyarrow on
              the same file (compressed spans ride O_DIRECT, host
              decompress, device decode → vs_baseline null; the
              speedup-vs-pyarrow tag is the claim)
 13 dict    — dictionary-encoded Parquet scan with the on-device
              bit-unpack; the bounce_vs_idx_raw tag is the claim (host
              touches only the raw index stream, never expanded rows)

Usage: python bench_suite.py [--config N ... | --all]
(stdout is already JSON-only — one line per config; logs go to stderr)

I/O rows (1–5, 8): {"metric", "value" (GiB/s payload→device), "unit",
"vs_baseline" (value / 0.9·min(raw SSD, host→device link) — the
BASELINE.json north star; ≥1.0 means target met)}.  Discipline per the
round-1 verdict: run 0 warms jit/IPC caches and is DISCARDED, the page
cache is evicted before every timed run (cold = NVMe, not DRAM), and the
reported value is the MEDIAN of the timed runs, never best-of.

The tunnel link flaps 10-30x within an up-window (0.02-1.4 GiB/s), so a
step-start link ceiling is stale by the time a config's passes run —
window 7 ledgered the probe's own pure stream at 0.16 GiB/s minutes
after bench rode the identical link at 0.95x of 1.35.  On a live device
every _steady pass is therefore PAIRED with a link burst measured
seconds before it, and vs_baseline is the median of PER-PASS ratios
against 0.9·min(raw, that pass's link) — bench.py's interleaved
same-minute discipline, applied per pass (raw is local NVMe and does
not flap; one step-start measure suffices).

Compute rows (6–7) have no BASELINE.json target (the reference is a
storage engine, SURVEY.md §1) → vs_baseline is always null; they exist so
the framework's perf claims cover compute, not just I/O.

Env: STROM_SUITE_BYTES (per-config payload, default 256 MiB),
STROM_BENCH_DIR (scratch dir, default repo root),
STROM_KVOFF_QUANT=int8 / STROM_KVOFF_HOSTCACHE=N (config-10 variants),
STROM_SERVE_PAGED=1 (config 11 through the block-pool paged server),
STROM_SERVE_SHARED_PREFIX=N (config-11 variant: every request shares an
N-token system prompt — the paged server's prefix caching prefills it
once; gauges in the tag).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import os
import shutil
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench  # noqa: E402  (shared helpers: probe_device, make_file, ...)

_log = bench._log

#: timed runs per I/O config AFTER the discarded jit-warmup run(s)
_RUNS = 3
#: discarded warmup calls at the head of every _steady loop — shared
#: with consumers that record side data from inside timed_fn and must
#: drop the same prefix (bench_sql's per-pass phase pairing); ONE
#: constant, so the run structure and the slicing cannot drift apart
_STEADY_WARMUPS = 1

#: same-run raw-SSD and host->device link rates (GiB/s), set by run()
#: before any config executes — the normalization base for rows whose
#: number is medium-bound (config 14's moment stream)
_CEILINGS: dict = {}

#: per-pass link pairing for io_row ratios (module header ¶3):
#: "probe" is a quick host→device burst installed by run() on a live
#: device; "last" holds the most recent _steady call's
#: [(pass_rate, link_gibs), ...] for the config result assembly
_PASS_LINK: dict = {"probe": None, "last": None}


class _SuiteWatchdog:
    """Convert a mid-suite hang into a self-diagnosing row instead of a
    silent timeout-burn.

    The axon tunnel HANGS rather than errors when it dies under a
    device op (ledger 2026-07-31T08:50: suite_15 finished all four topk
    scans in ~3.5s each, then sat wedged in a device transfer until the
    watcher's 900s kill — the round-3 verdict's weak #3).  Python can't
    interrupt a hung ``block_until_ready``, so the only honest move is:
    print WHERE we were wedged as a harvestable JSON line, flush, and
    ``os._exit`` so the step ends at its budget instead of the watcher's
    grace-period later.

    Two modes:
      * ``arm(budget_s)`` — fires while configs still run → rc=3
        ("HUNG" row names the phase; work was incomplete, the watcher
        retries the step);
      * ``teardown(grace_s)`` — armed after every result line has been
        printed; engine close / JAX runtime teardown hanging must not
        cost the window anything → rc=0 (the results already landed).
    """

    def __init__(self) -> None:
        self._phase = "startup"
        self._t_phase = time.monotonic()
        self._timer = None

    def phase(self, name: str) -> None:
        self._phase = name
        self._t_phase = time.monotonic()

    def _cancel(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def arm(self, budget_s: float) -> None:
        import threading
        self._cancel()
        self._timer = threading.Timer(budget_s, self._fire_hung,
                                      args=(budget_s,))
        self._timer.daemon = True
        self._timer.start()

    def teardown(self, grace_s: float = 90.0) -> None:
        import threading
        self._cancel()
        self.phase("teardown")
        self._timer = threading.Timer(grace_s, self._fire_teardown,
                                      args=(grace_s,))
        self._timer.daemon = True
        self._timer.start()

    def _fire_hung(self, budget_s: float) -> None:
        stuck_s = round(time.monotonic() - self._t_phase, 1)
        print(json.dumps({
            "metric": f"WATCHDOG-HUNG in {self._phase} "
                      f"(stuck {stuck_s}s, budget {budget_s:.0f}s)",
            "value": stuck_s, "unit": "s", "vs_baseline": None,
        }), flush=True)
        _log(f"suite: WATCHDOG — hung in {self._phase} for {stuck_s}s; "
             "hard-exiting (rc=3) so the step ends at its budget")
        sys.stderr.flush()
        os._exit(3)

    def _fire_teardown(self, grace_s: float) -> None:
        _log(f"suite: WATCHDOG — teardown hung >{grace_s:.0f}s after all "
             "results printed; hard-exiting rc=0 (results already landed)")
        sys.stderr.flush()
        os._exit(0)


_WATCHDOG = _SuiteWatchdog()


def _steady(evict_paths, timed_fn) -> float:
    """Warmup + _RUNS cold timed runs → median rate.

    ``timed_fn()`` performs one full pass and returns its rate;
    ``evict_paths`` are dropped from the page cache before every run so
    each pass reads the NVMe, not DRAM (freshly generated bench data is
    100% cache-resident otherwise, and the residency planner would —
    correctly — serve it from memory).

    When run() installed a link probe (live device), each timed pass is
    preceded by one quick host→device burst and the (rate, link) pairs
    land in ``_PASS_LINK["last"]`` — the flap-proof per-pass ceilings
    the result assembly ratios against (module header ¶3).

    CONTRACT: exactly _STEADY_WARMUPS discarded warmup call(s), then
    _RUNS timed calls.  Consumers that record side data from inside
    ``timed_fn`` (bench_sql's per-pass phase pairing) slice off the
    same ``_STEADY_WARMUPS`` prefix — the shared constant is the
    coupling, not a comment."""
    probe = _PASS_LINK["probe"]
    rates, pairs = [], []
    for i in range(_RUNS + _STEADY_WARMUPS):
        for p in evict_paths:
            bench.evict_file(p)
        timed = i >= _STEADY_WARMUPS   # head runs warm jit/IPC caches
        link = probe() if (probe is not None and timed) else 0.0
        r = timed_fn()
        if timed:
            rates.append(r)
            if link > 0:
                pairs.append((r, link))
    if probe is not None:
        _PASS_LINK["last"] = pairs
    return statistics.median(rates)


def _paired_passes(path, direct_fn, fallback_fn) -> list:
    """Per-pass PAIRED comparison: evict → direct → evict → fallback,
    back to back within each pass so a link flap between the two
    measurements cancels out of the per-pass ratio (the window-9
    config-12 row read 0.61x while its own phase tag showed direct 4x
    faster — the two _steady runs had sampled the flapping link
    minutes apart).  Both fns receive ``timed`` (False during the
    _STEADY_WARMUPS prefix — same contract as _steady) so they can
    bracket side data for timed passes only.  Returns the timed
    (t_direct, t_fallback) pairs."""
    pairs = []
    for i in range(_RUNS + _STEADY_WARMUPS):
        timed = i >= _STEADY_WARMUPS
        bench.evict_file(path)
        td = direct_fn(timed)
        bench.evict_file(path)
        tp = fallback_fn(timed)
        if timed:
            pairs.append((td, tp))
    return pairs


def _scratch_dir() -> str:
    d = os.environ.get("STROM_BENCH_DIR",
                       os.path.dirname(os.path.abspath(__file__)))
    sub = os.path.join(d, ".bench_suite")
    os.makedirs(sub, exist_ok=True)
    return sub


def _suite_bytes() -> int:
    return int(os.environ.get("STROM_SUITE_BYTES", 256 << 20))


def _needs_regen(tag: str, nbytes: int, gen: int = 1) -> bool:
    """Size- and generation-aware scratch cache: True if data tagged
    `tag` must be (re)generated.  The .meta sentinel records the size a
    previous run FINISHED generating (written by _mark_generated after
    success), so changing STROM_SUITE_BYTES — or an interrupted
    generation — regenerates instead of silently benchmarking stale or
    truncated data.  ``gen`` is bumped when a generator's OUTPUT format
    changes (e.g. parquet switching to non-dictionary PLAIN), so an old
    scratch file can't silently bench the wrong code path."""
    meta = os.path.join(_scratch_dir(), f".{tag}.meta")
    try:
        return open(meta).read().strip() != f"{nbytes}/g{gen}"
    except OSError:
        return True


def _mark_generated(tag: str, nbytes: int, gen: int = 1) -> None:
    with open(os.path.join(_scratch_dir(), f".{tag}.meta"), "w") as f:
        f.write(f"{nbytes}/g{gen}")


# --------------------------- data generators ---------------------------

def make_arrow_file(path: str, nbytes: int) -> int:
    """Multi-batch Arrow IPC file of float32/int32 columns; returns size."""
    import numpy as np
    import pyarrow as pa
    if not _needs_regen("arrow", nbytes) and os.path.exists(path):
        return os.path.getsize(path)
    rows_total = max(1024, nbytes // 12)     # 3 cols × 4 bytes
    per_batch = max(1024, rows_total // 16)
    rng = np.random.default_rng(0)
    schema = pa.schema([("a", pa.float32()), ("b", pa.float32()),
                        ("k", pa.int32())])
    with pa.OSFile(path, "wb") as f, pa.ipc.new_file(f, schema) as w:
        left = rows_total
        while left > 0:
            n = min(per_batch, left)
            w.write_batch(pa.record_batch(
                [pa.array(rng.standard_normal(n, dtype=np.float32)),
                 pa.array(rng.standard_normal(n, dtype=np.float32)),
                 pa.array(rng.integers(0, 64, n, dtype=np.int32))],
                schema=schema))
            left -= n
    _mark_generated("arrow", nbytes)
    return os.path.getsize(path)


def make_wds_shards(dirpath: str, nbytes: int, n_shards: int = 4,
                    item_bytes: int = 1 << 20) -> list:
    """Tar shards of fixed-size .bin samples; returns shard paths."""
    import io as _io
    import tarfile
    import numpy as np
    os.makedirs(dirpath, exist_ok=True)
    per_shard = max(2, nbytes // n_shards // item_bytes)
    rng = np.random.default_rng(0)
    # sentinel keyed per DATASET DIR: config 3 and config 17 both build
    # wds shards with different sizes — one shared "wds" tag made each
    # run invalidate the other's cache and regenerate every cycle
    tag = "wds-" + os.path.basename(os.path.normpath(dirpath))
    regen = _needs_regen(tag, nbytes)
    paths = []
    for s in range(n_shards):
        p = os.path.join(dirpath, f"shard-{s:04d}.tar")
        paths.append(p)
        if os.path.exists(p) and not regen:
            continue
        with tarfile.open(p, "w") as tf:
            for i in range(per_shard):
                payload = rng.integers(0, 256, item_bytes,
                                       dtype=np.uint8).tobytes()
                ti = tarfile.TarInfo(f"{s:04d}{i:05d}.bin")
                ti.size = item_bytes
                tf.addfile(ti, _io.BytesIO(payload))
    _mark_generated(tag, nbytes)
    return paths


def make_safetensors_shards(dirpath: str, nbytes: int,
                            n_shards: int = 2) -> list:
    import numpy as np
    from nvme_strom_tpu.formats import write_safetensors
    os.makedirs(dirpath, exist_ok=True)
    per_shard = nbytes // n_shards
    n_tensors = 4
    rows = max(64, per_shard // n_tensors // (1024 * 4))
    rng = np.random.default_rng(0)
    regen = _needs_regen("st", nbytes)
    paths = []
    for s in range(n_shards):
        p = os.path.join(dirpath,
                         f"model-{s + 1:05d}-of-{n_shards:05d}.safetensors")
        paths.append(p)
        if os.path.exists(p) and not regen:
            continue
        write_safetensors(p, {
            f"w{s}_{i}": rng.standard_normal(
                (rows, 1024), dtype=np.float32)
            for i in range(n_tensors)})
    _mark_generated("st", nbytes)
    return paths


def make_parquet_file(path: str, nbytes: int, num_groups: int = 64,
                      compression: str = "none") -> int:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    tag = "parquet" if compression == "none" else f"parquet_{compression}"
    if not _needs_regen(tag, nbytes, gen=2) and os.path.exists(path):
        return os.path.getsize(path)
    rows = max(4096, nbytes // 8)            # int32 key + float32 value
    rng = np.random.default_rng(0)
    tbl = pa.table({
        "k": pa.array(rng.integers(0, num_groups, rows, dtype=np.int32)),
        "v": pa.array(rng.standard_normal(rows, dtype=np.float32))})
    # PLAIN pages: the shape PG-Strom-style on-device decode handles
    # (sql/pq_direct.py) — config 5 measures the uncompressed direct
    # scan, config 12 the compressed one (engine-read compressed spans,
    # host decompress, device decode).
    pq.write_table(tbl, path, row_group_size=max(4096, rows // 16),
                   compression=compression, use_dictionary=False)
    _mark_generated(tag, nbytes, gen=2)
    return os.path.getsize(path)


def make_topk_parquet(path: str, nbytes: int) -> int:
    """Table for config 15: a random float column (ORDER BY must scan
    everything) plus a monotonically increasing int64 "ts" column whose
    tight per-row-group statistics make LIMIT elimination provable."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    if not _needs_regen("parquet_topk", nbytes) and os.path.exists(path):
        return os.path.getsize(path)
    rows = max(4096, nbytes // 12)           # float32 v + int64 ts
    rng = np.random.default_rng(1)
    tbl = pa.table({
        "v": pa.array(rng.standard_normal(rows, dtype=np.float32)),
        "ts": pa.array(np.arange(rows, dtype=np.int64))})
    pq.write_table(tbl, path, row_group_size=max(4096, rows // 16),
                   compression="none", use_dictionary=False)
    _mark_generated("parquet_topk", nbytes)
    return os.path.getsize(path)


def make_sql_scan_parquet(path: str, nbytes: int,
                          num_groups: int = 64) -> int:
    """Table for config 23: a key column, three float32 payload
    columns, and a monotonically increasing int32 "ts" column (int32,
    not int64, so the direct page walk stays eligible under x32 JAX)
    with tight per-row-group AND per-page statistics.  The layout is
    the zone-map worst case the paper motivates pushdown with: TWO
    large row groups, so a predicate band straddling their boundary
    defeats row-group pruning outright — the pre-PR scan reads the
    whole table — while the late-materializing scan fetches the filter
    column plus just the 256 KiB payload pages the band touches.  The
    wide fact-table payload (16 value columns — TPC-DS store_sales
    width) keeps the filter column a small fraction of the bytes
    pushdown must still read in full."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    if not _needs_regen("parquet_scan", nbytes, gen=4) \
            and os.path.exists(path):
        return os.path.getsize(path)
    rows = max(8192, nbytes // 72)   # k,ts int32 + v0..v15 float32
    rng = np.random.default_rng(2)
    data = {"k": pa.array(rng.integers(0, num_groups, rows,
                                       dtype=np.int32))}
    for i in range(16):
        data[f"v{i}"] = pa.array(
            rng.standard_normal(rows, dtype=np.float32))
    data["ts"] = pa.array(np.arange(rows, dtype=np.int32))
    pq.write_table(pa.table(data), path, row_group_size=(rows + 1) // 2,
                   compression="none", use_dictionary=False,
                   data_page_size=256 << 10)
    _mark_generated("parquet_scan", nbytes, gen=4)
    return os.path.getsize(path)


# ------------------------------ benches --------------------------------

def bench_arrow(engine, nbytes: int, device=None) -> tuple[float, int]:
    path = os.path.join(_scratch_dir(), "cols.arrow")
    size = make_arrow_file(path, nbytes)
    from nvme_strom_tpu.formats.arrow import ArrowFileReader
    reader = ArrowFileReader(path)

    def one_pass() -> float:
        t0 = time.monotonic()
        cols = reader.read_columns_to_device(engine, device=device)
        for v in cols.values():
            v.block_until_ready()
        dt = time.monotonic() - t0
        return sum(int(v.nbytes) for v in cols.values()) / (1 << 30) / dt

    return _steady([path], one_pass), size


def bench_loader(engine, nbytes: int, batch: int = 8) -> tuple[float, str]:
    """Config 3: WebDataset shards → device batches.  Headline is the
    wds_raw batch-coalesced zero-copy path (round-2 verdict #6 — raw
    members go staging→device with no host copy, so on an accelerator
    the epoch's bounce is 0); the standard decode path's rate rides in
    the tag for comparison."""
    import jax
    import numpy as np
    from jax.sharding import Mesh
    from nvme_strom_tpu.data.loader import ShardedLoader
    paths = make_wds_shards(os.path.join(_scratch_dir(), "wds"), nbytes)
    mesh = Mesh(np.array(jax.local_devices()[:1]).reshape(1), ("dp",))

    def epoch_rate(fmt) -> float:
        with ShardedLoader(paths, mesh, global_batch=batch, fmt=fmt,
                           engine=engine) as loader:
            def one_epoch() -> float:
                n = 0
                t0 = time.monotonic()
                for arr in loader:
                    arr.block_until_ready()
                    n += int(arr.nbytes)
                return n / (1 << 30) / (time.monotonic() - t0)
            return _steady(paths, one_epoch)

    engine.sync_stats()
    pre = engine.stats.snapshot()["bounce_bytes"]
    raw_rate = epoch_rate("wds_raw")
    raw_pairs = _PASS_LINK["last"]   # headline pairing, not std's
    engine.sync_stats()
    # per-epoch, matching config 13's convention (_steady runs
    # _RUNS + 1 epochs including the discarded warmup)
    raw_bounce = (engine.stats.snapshot()["bounce_bytes"] - pre) \
        // (_RUNS + 1)
    std_rate = epoch_rate("wds")
    _PASS_LINK["last"] = raw_pairs
    _log(f"suite: loader wds_raw={raw_rate:.3f} GiB/s "
         f"(bounce/epoch={raw_bounce}) std={std_rate:.3f} GiB/s")
    return raw_rate, (f"wds_raw bounce/epoch={raw_bounce}, "
                      f"std_path={std_rate:.3f} GiB/s")


def bench_weights(engine, nbytes: int, device=None) -> tuple[float, int]:
    import jax
    from jax.sharding import SingleDeviceSharding
    from nvme_strom_tpu.parallel.weights import LazyCheckpoint
    paths = make_safetensors_shards(
        os.path.join(_scratch_dir(), "st"), nbytes)
    ckpt = LazyCheckpoint(paths)
    dev = device or jax.local_devices()[0]
    sh = SingleDeviceSharding(dev)
    payload = [0]

    def one_load() -> float:
        t0 = time.monotonic()
        params = ckpt.load_sharded(lambda name, shape: sh, engine=engine)
        for v in params.values():
            v.block_until_ready()
        dt = time.monotonic() - t0
        payload[0] = sum(int(v.nbytes) for v in params.values())
        del params
        return payload[0] / (1 << 30) / dt

    return _steady(paths, one_load), payload[0]


def bench_sql(engine, nbytes: int, num_groups: int = 64,
              device=None) -> tuple[float, str]:
    """Config 5: Parquet scan → on-device GROUP BY, with the round-3
    verdict's phase attribution: the tag decomposes the query into
    plan (footer+page walk, host), stream (pipelined spans→device,
    measured by a fold-free pass over the same cold file), and the
    fold's share (full time minus stream time) — so an on-silicon row
    that misses its ceiling names the phase that lost it."""
    import jax
    from nvme_strom_tpu.sql.parquet import ParquetScanner
    from nvme_strom_tpu.sql.groupby import (iter_device_columns,
                                            sql_groupby,
                                            sql_window_bytes)
    path = os.path.join(_scratch_dir(), "table.parquet")
    size = make_parquet_file(path, nbytes, num_groups)
    scanner = ParquetScanner(path, engine)
    rows = scanner.num_rows
    dev = device or jax.local_devices()[0]

    # phase 1: plan (pure host metadata walk, no payload I/O)
    from nvme_strom_tpu.sql import pq_direct
    t0 = time.monotonic()
    plans = pq_direct.plan_columns(scanner, ["k", "v"])
    t_plan = time.monotonic() - t0

    # phase 2: stream — the same columns, cold cache, NO aggregation;
    # the delta between this and the full query is the fold's cost.
    # (Blocking on the last group's arrays suffices: transfers retire
    # in submission order on a single device stream.)
    def stream_pass() -> float:
        t0 = time.monotonic()
        last = None
        for cols in iter_device_columns(scanner, ["k", "v"], dev,
                                        narrow_int32=("k",),
                                        plans=plans):
            last = cols
        for v in last.values():
            v.block_until_ready()
        return time.monotonic() - t0

    # Per-PASS phase pairing (window-7 diagnosis 1 applied to the phase
    # attribution, not just the ceiling): each timed scan subtracts a
    # stream pass run SECONDS after it, so a link flap between the two
    # phase measurements cancels instead of landing in fold_overhead —
    # window 8 ledgered fold 0.18→2.57 s across captures from exactly
    # this mispairing (the lone stream pass caught a 1.09 GiB/s moment,
    # the scans ~0.5 ones).  Order matters: the SCAN runs first, right
    # after _steady's link burst, so the (rate, link) ceiling pair
    # stays adjacent too; the stream pass follows the scan.  _steady's
    # discarded run 0 warms both paths' jit/dispatch caches.
    stream_ts, fold_ts = [], []

    # fold bisect knob: the v5 paired row put the fold at ~1.4 s on a
    # healthy link — method (matmul one-hot vs scatter segment-sum)
    # and window size are the two levers that split dispatch cost from
    # device-side fold cost.  Absent explicit env, the LEDGERED winner
    # of the bisect is adopted (utils/tuning.best_sql_fold — the
    # flash-tiling adoption pattern), so once suite_5_scatter/w256/
    # sw256 land their rows, every later config-5 run measures the
    # best known operating point by default.
    method = os.environ.get("STROM_SQL_METHOD")
    adopted_window = False
    if method is None and os.environ.get("STROM_SQL_WINDOW_BYTES") is None:
        # BOTH knobs unset = the plain contract row; a bisect step that
        # pins one knob must measure exactly what its label says, so
        # adoption never fills in its other knob
        from nvme_strom_tpu.utils.tuning import best_sql_fold
        tuned = best_sql_fold() or {}
        if tuned:
            _log(f"suite: sql fold adopting ledgered best {tuned}")
            method = tuned["method"]
            # sql_window_bytes() reads the env at each call — the
            # adoption rides the same knob the operator would set,
            # scoped to THIS config's scans (restored below: a --all
            # run's other configs must keep their own operating point)
            os.environ["STROM_SQL_WINDOW_BYTES"] = str(
                tuned["window_bytes"])
            adopted_window = True
    method = method or "matmul"

    def one_scan() -> float:
        t0 = time.monotonic()
        out = sql_groupby(scanner, "k", "v", num_groups,
                          aggs=("count", "sum", "mean"), method=method,
                          device=device)
        for v in out.values():
            v.block_until_ready()
        dt = time.monotonic() - t0
        bench.evict_file(path)   # the stream pass re-reads the NVMe too
        stream_ts.append(stream_pass())
        fold_ts.append(max(dt - stream_ts[-1], 0.0))
        _log(f"suite: sql scanned {rows} rows ({size >> 20} MiB) "
             f"in {dt:.3f}s = {rows / dt / 1e6:.1f} Mrows/s "
             f"(paired stream={stream_ts[-1]:.3f}s)")
        return size / (1 << 30) / dt

    try:
        rate = _steady([path], one_scan)
        # drop _steady's warmup-call prefix, same constant it runs by
        gib = size / (1 << 30)
        stream_rate = statistics.median(
            gib / t for t in (stream_ts[_STEADY_WARMUPS:] or stream_ts))
        fold_s = statistics.median(fold_ts[_STEADY_WARMUPS:] or fold_ts)
        tag = (f"rows={rows} plan={t_plan * 1e3:.0f}ms "
               f"stream={stream_rate:.3f} GiB/s "
               f"fold_overhead={fold_s:.3f}s paired=per-pass "
               f"method={method} window={sql_window_bytes() >> 20}MiB")
        _log(f"suite: sql phases: {tag}")
        return rate, tag
    finally:
        if adopted_window:
            os.environ.pop("STROM_SQL_WINDOW_BYTES", None)


def bench_sql_parallel(engine, nbytes: int, num_groups: int = 64,
                       device=None) -> tuple[float, str]:
    """Config 23: partition-parallel pushdown scan (sql/scan_plan.py)
    vs its own same-run serial arm — a ~10% selectivity range predicate
    on the monotone ts column whose band STRADDLES the two row groups'
    boundary, so zone-map pruning saves nothing and the whole win is
    page-level late materialization.  Three arms back to back on the
    same cold file: serial (workers=1, pushdown off — the exact pre-PR
    path), parallel (best workers, pushdown off), parallel+pushdown.
    The TIMED section is the scan stage (iter_scan_columns draining
    every column to the device) — the stage this engine owns; the
    group-by fold downstream of it is byte-for-byte the same work in
    every arm, and each arm's FULL query result is computed untimed
    and asserted bit-identical to serial every run, so a divergence
    fails the config loudly rather than benching a wrong answer.
    Headline is the parallel+pushdown effective table scan rate
    (surviving-row-group bytes over wall time); the tag stamps
    ``workers=N`` (utils/tuning.best_sql_workers adopts the ledgered
    winner as the STROM_SQL_WORKERS=0 auto width), the serial/parallel
    rates, speedups, rows/s, and the skip counters."""
    import numpy as np
    from nvme_strom_tpu.sql import scan_plan
    from nvme_strom_tpu.sql.groupby import sql_groupby
    from nvme_strom_tpu.sql.parquet import ParquetScanner
    path = os.path.join(_scratch_dir(), "scan.parquet")
    size = make_sql_scan_parquet(path, nbytes, num_groups)
    scanner = ParquetScanner(path, engine)
    rows = scanner.num_rows
    lo, hi = int(rows * 0.45), int(rows * 0.55) - 1    # ~10% survives
    wr = [("ts", lo, hi)]
    vcols = [f"v{i}" for i in range(16)]
    cols = ["k", *vcols, "ts"]
    window = 32 << 20          # fixed across arms: identical windowing
    knobs = ("STROM_SQL_WORKERS", "STROM_SQL_PUSHDOWN",
             "STROM_SQL_WINDOW_BYTES")
    saved = {k: os.environ.get(k) for k in knobs}

    def query():
        out = sql_groupby(scanner, "k", vcols, num_groups,
                          aggs=("count", "sum", "mean"), device=device,
                          where_ranges=wr)
        for v in out.values():
            v.block_until_ready()
        return {a: np.asarray(v) for a, v in out.items()}

    results = {}

    def arm(tag_, workers, pushdown):
        os.environ["STROM_SQL_WORKERS"] = str(workers)
        os.environ["STROM_SQL_PUSHDOWN"] = str(pushdown)
        rgs = (list(scan_plan.plan_scan(scanner, cols, wr).row_groups)
               if pushdown and scan_plan.pushdown_enabled()
               else scanner.prune_row_groups(wr))
        ts = []
        for i in range(_RUNS + _STEADY_WARMUPS):
            bench.evict_file(path)
            t0 = time.monotonic()
            for out in scan_plan.iter_scan_columns(
                    scanner, cols, device, row_groups=rgs,
                    where_ranges=wr, window_bytes=window):
                for v in out.values():
                    v.block_until_ready()
            if i >= _STEADY_WARMUPS:
                ts.append(time.monotonic() - t0)
        results[tag_] = query()        # untimed: fold bit-check
        dt = statistics.median(ts)
        _log(f"suite: sql-parallel arm {tag_}: {dt:.3f}s "
             f"({size / (1 << 30) / dt:.3f} GiB/s)")
        return dt

    try:
        os.environ["STROM_SQL_WINDOW_BYTES"] = str(window)
        env_w = int(saved["STROM_SQL_WORKERS"] or "0")
        widths = [env_w] if env_w > 1 else [2, 4]
        t_serial = arm("serial", 1, 0)
        t_par, best_w = None, widths[0]
        for w in widths:
            t = arm(f"par{w}", w, 0)
            if t_par is None or t < t_par:
                t_par, best_w = t, w
        snap0 = engine.stats.snapshot()
        t_push = arm("push", best_w, 1)
        snap1 = engine.stats.snapshot()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    base = results["serial"]
    for tag_, res in results.items():
        for a in base:
            if not np.array_equal(base[a], res[a], equal_nan=True):
                raise AssertionError(
                    f"config 23: arm {tag_} diverged from serial on "
                    f"{a!r} — scan correctness bug, not a perf number")
    rg_skip = (snap1.get("sql_rowgroups_skipped", 0)
               - snap0.get("sql_rowgroups_skipped", 0))
    # push arm: _RUNS + warmup timed scan passes plus the one untimed
    # bit-check query, each a late-materializing pass over the band
    by_skip = ((snap1.get("sql_bytes_skipped", 0)
                - snap0.get("sql_bytes_skipped", 0))
               // (_RUNS + _STEADY_WARMUPS + 1))
    gib = size / (1 << 30)
    rate = gib / t_push
    tag = (f"workers={best_w} rows={rows} sel=10% "
           f"serial={gib / t_serial:.3f} par={gib / t_par:.3f} "
           f"push={rate:.3f} GiB/s "
           f"speedup_par={t_serial / t_par:.2f}x "
           f"speedup_push={t_serial / t_push:.2f}x "
           f"mrows_s={rows / t_push / 1e6:.2f} "
           f"rg_skipped={rg_skip} bytes_skipped={by_skip}")
    _log(f"suite: sql-parallel: {tag}")
    return rate, tag


def bench_sql_zstd(engine, nbytes: int, num_groups: int = 64,
                   device=None) -> tuple[float, str]:
    """Config 12: zstd-compressed scan, direct path vs pyarrow fallback
    on the SAME file (round-2 verdict #4 — real tables are compressed).

    Direct path: compressed page spans ride O_DIRECT, host decompress,
    on-device bitcast + GROUP BY.  Fallback: pyarrow decodes the table
    on host.  Reports the direct rate (compressed GiB/s off the SSD)
    with the fallback rate and speedup in the tag."""
    from nvme_strom_tpu.sql.parquet import ParquetScanner
    from nvme_strom_tpu.sql.groupby import groupby_aggregate
    path = os.path.join(_scratch_dir(), "table_zstd.parquet")
    size = make_parquet_file(path, nbytes, num_groups,
                             compression="zstd")
    scanner = ParquetScanner(path, engine)
    rows = scanner.num_rows

    def scan(direct: str) -> float:
        t0 = time.monotonic()
        cols = scanner.read_columns_to_device(["k", "v"], direct=direct,
                                              device=device)
        out = groupby_aggregate(cols["k"], cols["v"], num_groups,
                                aggs=("count", "sum"))
        for v in out.values():
            v.block_until_ready()
        return time.monotonic() - t0

    # both paths ship the same decompressed bytes over the same link
    # moment, so the flap cancels out of the per-pass ratio
    from nvme_strom_tpu.sql import pq_direct
    ph: dict = {}

    def direct(timed):
        td = scan("always")
        if timed:
            ph.clear()
            ph.update(pq_direct.LAST_COMPRESSED_PHASES)
        return td

    pairs = _paired_passes(path, direct, lambda timed: scan("never"))
    d_times = [td for td, _ in pairs]
    p_times = [tp for _, tp in pairs]
    ratios = [tp / td for td, tp in pairs]
    dt_direct = 1.0 / statistics.median(d_times)
    dt_pyarrow = 1.0 / statistics.median(p_times)
    # host-decode-only pyarrow time: what the direct path's
    # stall+decomp phases race against — BOTH paths then ship the same
    # decompressed bytes over the same link, so the transfer term
    # cancels out of the comparison (round-3 verdict #5: the 0.24x
    # on-silicon row was uninterpretable without this split)
    import pyarrow.parquet as pq
    bench.evict_file(path)
    t0 = time.monotonic()
    pq.read_table(path, columns=["k", "v"])
    t_pa_host = time.monotonic() - t0
    rate = size / (1 << 30) * dt_direct          # dt_* are 1/seconds
    speedup = statistics.median(ratios)          # of per-pass ratios
    _log(f"suite: zstd scan {rows} rows ({size >> 20} MiB compressed): "
         f"direct={1 / dt_direct:.3f}s pyarrow={1 / dt_pyarrow:.3f}s "
         f"speedup={speedup:.2f}x (per-pass paired) phases={ph}")
    tag = (f"speedup_vs_pyarrow={speedup:.2f}x paired=per-pass; "
           f"direct phases: "
           f"stall={ph.get('read_stall_s', -1):.2f}s "
           f"decomp={ph.get('decomp_s', -1):.2f}s "
           f"put={ph.get('put_s', -1):.2f}s "
           f"({ph.get('decompressed_bytes', 0) >> 20}MiB to device); "
           f"pyarrow host decode={t_pa_host:.2f}s + same put")
    return rate, tag


def bench_topk(engine, nbytes: int, device=None) -> tuple[float, str]:
    """Config 15: ORDER BY ... LIMIT pushdown (sql/topk.py).

    Two queries on one table: ORDER BY a random float column (no usable
    statistics order → the streaming device top-k merge scans every row
    group; the reported GiB/s is that full scan) and ORDER BY a sorted
    int64 "ts" column (tight footer stats → the LIMIT elimination skips
    every row group but one; the tag carries skipped/total and the
    query's wall time — the scan-elimination claim as a measured row)."""
    from nvme_strom_tpu.sql.parquet import ParquetScanner
    from nvme_strom_tpu.sql.topk import sql_topk
    path = os.path.join(_scratch_dir(), "table_topk.parquet")
    size = make_topk_parquet(path, nbytes)
    scanner = ParquetScanner(path, engine)
    rows = scanner.num_rows
    nrg = scanner.num_row_groups

    def full_scan() -> float:
        t0 = time.monotonic()
        res = sql_topk(scanner, "v", columns=["ts"], k=10,
                       device=device)
        dt = time.monotonic() - t0
        assert len(res["v"]) == 10
        # HONEST rate: even a random column's stats eliminate some
        # groups once the carried k-th value is high; only bytes the
        # scan actually read may count toward the GiB/s row
        scanned = size * (nrg - res["_skipped_row_groups"]) / nrg
        _log(f"suite: topk scanned {rows} rows in {dt:.3f}s "
             f"({res['_skipped_row_groups']}/{nrg} rgs eliminated)")
        return scanned / (1 << 30) / dt

    rate = _steady([path], full_scan)
    bench.evict_file(path)
    t0 = time.monotonic()
    res = sql_topk(scanner, "ts", columns=["v"], k=10, device=device)
    dt_ts = time.monotonic() - t0
    skipped = res["_skipped_row_groups"]
    tag = (f"rows={rows} k=10; sorted-col elimination skipped "
           f"{skipped}/{nrg} rgs in {dt_ts * 1e3:.0f}ms")
    return rate, tag


def bench_dict_scan(engine, nbytes: int, cardinality: int = 4096,
                    device=None) -> tuple[float, str]:
    """Config 13: dictionary-encoded column scan with the on-device
    bit-unpack (round-2 verdict #5).  The tag reports host-touched
    payload (bounce) against the raw index-stream bytes — the claim is
    bounce ≈ raw stream (engine-read only), NOT 4 bytes/row of
    host-expanded indices — AND, per the round-4 verdict ("give
    config 13 a bar"), the per-pass-paired speedup over the pyarrow
    fallback shipping the same decoded column to the same device: the
    ×pyarrow bar config 12 already carries.  The direct path now runs
    the whole-column batched decode (one device program set + one sync
    for all row groups — the per-row-group walk priced the window-9
    row at 179 s of tunnel dispatches)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    from nvme_strom_tpu.sql.parquet import ParquetScanner
    from nvme_strom_tpu.sql import pq_direct
    path = os.path.join(_scratch_dir(), "table_dict.parquet")
    if _needs_regen("parquet_dict", nbytes) or not os.path.exists(path):
        rows = max(4096, nbytes // 4)
        rng = np.random.default_rng(0)
        pq.write_table(
            pa.table({"v": pa.array(
                rng.integers(0, cardinality, rows, dtype=np.int32))}),
            path, row_group_size=max(4096, rows // 8),
            compression="none", use_dictionary=True)
        _mark_generated("parquet_dict", nbytes)
    size = os.path.getsize(path)
    scanner = ParquetScanner(path, engine)
    plans = pq_direct.plan_columns(scanner, ["v"])
    idx_raw = sum(p.span[1] for plan in plans["v"]
                  for p in plan.parts if p.kind == "dict")
    stats = engine.stats

    def scan(direct: str) -> float:
        t0 = time.monotonic()
        out = scanner.read_columns_to_device(["v"], direct=direct,
                                             device=device)
        out["v"].block_until_ready()
        return time.monotonic() - t0

    # bounce accounting brackets only the DIRECT passes so the pyarrow
    # handoff can't pollute the bounce_vs_idx_raw claim
    bounce = [0]

    def direct(timed):
        engine.sync_stats()
        pre = stats.snapshot()["bounce_bytes"]
        td = scan("always")
        engine.sync_stats()
        if timed:
            bounce[0] += stats.snapshot()["bounce_bytes"] - pre
        return td

    pairs = _paired_passes(path, direct, lambda timed: scan("never"))
    d_times = [td for td, _ in pairs]
    p_times = [tp for _, tp in pairs]
    ratios = [tp / td for td, tp in pairs]
    rate = size / (1 << 30) / statistics.median(d_times)
    speedup = statistics.median(ratios)
    per_pass = bounce[0] / _RUNS
    _log(f"suite: dict scan rows={scanner.num_rows} idx_raw={idx_raw} "
         f"bounce/pass={per_pass:.0f} "
         f"({per_pass / max(idx_raw, 1):.2f}x of raw stream) "
         f"direct={statistics.median(d_times):.3f}s "
         f"pyarrow={statistics.median(p_times):.3f}s "
         f"speedup={speedup:.2f}x")
    return rate, (f"speedup_vs_pyarrow={speedup:.2f}x paired=per-pass; "
                  f"bounce_vs_idx_raw={per_pass / max(idx_raw, 1):.2f}x"
                  f", idx_raw={idx_raw}")


def bench_overlap(nbytes: int) -> tuple[float, str]:
    """Config 20: zero-copy overlap pipeline (docs/PERF.md §6) —
    overlapped streaming GiB/s through the double-buffered host→HBM
    stage, tagged with the speedup over the serialized arm and the
    SQPOLL submission-syscall reduction.  Delegates to
    ``bench.bench_overlap`` (pad-emulated hop on the CPU fallback,
    real paths on a TPU with the pad at 0); own engines, own file —
    like configs 6/11 no read-ceiling ratio applies (the serialized/
    SQPOLL-off arms in the tag are the claim)."""
    d = _scratch_dir()
    path = os.path.join(d, "overlap.bin")
    bench.make_file(path, max(nbytes, 16 << 20))
    out = bench.bench_overlap(path)
    tag = (f"serialized={out['serialized_gib_s']} GiB/s "
           f"({out['overlap_speedup_pct']:+.1f}%), "
           f"syscalls/GiB {out['sqpoll_off']['enters_per_gib']}"
           f"->{out['sqpoll_on']['enters_per_gib']} "
           f"({out['syscalls_per_gib_reduction_pct']:-.1f}%), "
           f"pad={out['pad_ms']}ms")
    return out["overlapped_gib_s"], tag


def bench_scatter(nbytes: int) -> tuple[float, str]:
    """Config 21: read-once/ICI-scatter restore (docs/PERF.md §7) —
    aggregate restore GiB/s when each virtual host reads 1/N off flash
    and the mesh exchanges shares, tagged with the read-all arm and the
    flash-byte reduction the ``ici_*`` counters prove.  Delegates to
    ``bench.bench_scatter`` (own engines, own file); a 1-device process
    grows the 8-host mesh in a throwaway subprocess.  Paired with its
    own same-run read-all arm — the N·T→T flash reduction in the tag is
    the claim, so no read-ceiling ratio applies."""
    import jax
    d = _scratch_dir()
    path = os.path.join(d, "scatter.bin")
    bench.make_file(path, max(nbytes, 16 << 20))
    if jax.device_count() >= 2:
        out = bench.bench_scatter(path)
    else:
        out = bench._bench_scatter_subprocess(path)
    if out is None:
        return 0.0, "scatter=unavailable (subprocess failed)"
    tag = (f"read_all={out['read_all_gib_s']} GiB/s, N={out['n_hosts']}"
           f", flash_bytes={out['n_hosts'] * out['payload_bytes']}"
           f"->{out['ici_bytes_read']}"
           + (", FELL BACK to read-all"
              if out["scatter_fell_back"] else ""))
    return out["scatter_gib_s"], tag


def bench_tenant_storm(nbytes: int) -> tuple[float, str]:
    """Config 22: multi-tenant isolation storm (docs/RESILIENCE.md
    "Multi-tenant isolation") — an open-loop victim + aggressor
    session trace served with tenancy off vs on, ALTERNATING storm
    trials with the median-p99 trial per arm (the bench_mixed
    discipline: clock drift hits both arms equally).  Delegates to
    ``bench.bench_tenants`` (own engines, own store file).  Headline
    is the isolation win — victim TTFT p99 tier-off / tier-on under
    the SAME storm; the tag carries the no-aggressor reference, both
    degradations, and the shed counters proving only the aggressor's
    tier paid."""
    d = _scratch_dir()
    path = os.path.join(d, "tenants.bin")
    bench.make_file(path, max(nbytes, 8 << 20))
    trials = 2 if _tiny_compute() else 3
    out = bench.bench_tenants(path, trials=trials)
    tag = (f"victim_p99={out['base']['victim_ttft_p99_ms']} ms alone"
           f", {out['tier_off']['victim_ttft_p99_ms']} tier-off"
           f", {out['tier_on']['victim_ttft_p99_ms']} tier-on "
           f"({out['victim_p99_degradation_on_pct']:+.1f}% vs alone), "
           f"sheds={out['tier_on']['tenant_sheds']}, "
           f"storm_dumps={out['tier_on']['tenant_storm_dumps']}, "
           f"trials={out['trials']}")
    return float(out["isolation_win"] or 0.0), tag


def bench_coldstart_suite(nbytes: int) -> tuple[float, str]:
    """Config 24: elastic cold-start (docs/RESILIENCE.md "Elastic
    cold-start") — time-to-first-token-from-boot, restore-then-serve
    vs serve-while-restoring, median over trials, with
    time-to-p99-steady and the token-identity verdict in the tag.
    Delegates to ``bench.bench_coldstart`` (own engines, own
    checkpoint + warm-payload files).  Headline is the TTFT-from-boot
    speedup (off/on); paired with its own same-run off arm, so no
    read-ceiling ratio applies."""
    d = _scratch_dir()
    path = os.path.join(d, "coldstart.bin")
    bench.make_file(path, max(nbytes, 64 << 20))
    trials = 2 if _tiny_compute() else 3
    out = bench.bench_coldstart(path, trials=trials)
    tag = (f"ttft_boot={out['off']['ttft_boot_s']}s off"
           f", {out['on']['ttft_boot_s']}s on; steady="
           f"{out['off']['steady_s']}s off"
           f", {out['on']['steady_s']}s on"
           f", faults={out['on']['coldstart_faults']}"
           f", bulk={out['on']['coldstart_bulk_tensors']}"
           f", tokens_identical={out['tokens_identical']}"
           f", pad={out['service_pad_ms']}ms"
           f", trials={out['trials']}")
    return float(out["ttft_boot_speedup"]), tag


def bench_handoff_suite(nbytes: int) -> tuple[float, str]:
    """Config 25: drain & warm handoff (docs/RESILIENCE.md "Drain &
    handoff") — rolling replica replacement, replacement
    TTFT-from-boot with vs without a shipped warm-state bundle,
    median over trials, with the zero-drop ledger and token-identity
    verdict in the tag.  Delegates to ``bench.bench_handoff`` (own
    engines, own checkpoint/store/bundle files).  Headline is the
    TTFT-from-boot speedup (off/on); paired with its own same-run off
    arm, so no read-ceiling ratio applies."""
    d = _scratch_dir()
    path = os.path.join(d, "handoff.bin")
    bench.make_file(path, max(nbytes, 64 << 20))
    trials = 2 if _tiny_compute() else 3
    out = bench.bench_handoff(path, trials=trials)
    tag = (f"ttft_boot={out['off']['ttft_boot_s']}s off"
           f", {out['on']['ttft_boot_s']}s on"
           f", exported={out['on']['sessions_exported']}"
           f", restored={out['on']['sessions_restored']}"
           f", dropped={out['dropped_requests']}"
           f", tokens_identical={out['tokens_identical']}"
           f", pad={out['service_pad_ms']}ms"
           f", trials={out['trials']}")
    return float(out["ttft_boot_speedup"]), tag


def bench_tar_index(engine, nbytes: int) -> tuple[float, str]:
    """Config 16: WebDataset shard-index rate (members/s), native C
    header walk vs Python tarfile — the first-epoch metadata cost of a
    many-shard dataset.  Cold-cache per pass like every I/O row; the
    member count scales with the suite budget (~4.5 KiB/member)."""
    import tarfile as _tarfile
    import io as _io
    from nvme_strom_tpu.io.engine import tar_index
    d = _scratch_dir()
    members = max(1000, nbytes // 4608)
    path = os.path.join(d, "tar_index.tar")
    tag = "tar_index"
    if _needs_regen(tag, members) or not os.path.exists(path):
        payload = b"x" * 4096
        tmp = path + ".tmp"
        with _tarfile.open(tmp, "w", format=_tarfile.GNU_FORMAT) as tf:
            for i in range(members):
                ti = _tarfile.TarInfo(f"train/{i:08d}.bin")
                ti.size = len(payload)
                tf.addfile(ti, _io.BytesIO(payload))
        os.replace(tmp, path)
        _mark_generated(tag, members)

    def native():
        t0 = time.monotonic()
        n = len(tar_index(path))
        dt = time.monotonic() - t0
        assert n == members, (n, members)
        return members / dt

    def python():
        t0 = time.monotonic()
        with _tarfile.open(path, "r:") as tf:
            n = sum(1 for m in tf if m.isfile())
        dt = time.monotonic() - t0
        assert n == members, (n, members)
        return members / dt

    r_native = _steady([path], native)
    r_py = _steady([path], python)
    return (r_native / 1e6,
            f"members={members} native={r_native / 1e3:.0f}k/s "
            f"tarfile={r_py / 1e3:.0f}k/s speedup={r_native / r_py:.1f}x")


def bench_checkpoint_write(engine, nbytes: int) -> tuple[float, str]:
    """Config 9: the inverse path — checkpoint save bandwidth.  Times
    CheckpointManager.save end to end (tile snapshot, engine writes,
    meta fsync, atomic rename) through the suite's shared engine, which
    is what a training run actually pays.  Every repeat writes a fresh
    step (no pruning inside the timed window); the tag says whether the
    payload actually went O_DIRECT (durable past the page cache) or the
    fs forced buffered writes — a page-cache memcpy number must not wear
    a 'durable' label.  The read side is config 4."""
    import shutil

    import numpy as np
    from nvme_strom_tpu.checkpoint.manager import CheckpointManager

    d = os.path.join(_scratch_dir(), "ckpt_bench")
    shutil.rmtree(d, ignore_errors=True)
    n_tensors = 8
    rows = max(1, nbytes // n_tensors // (1024 * 4))
    rng = np.random.default_rng(0)
    state = {f"w{i}": rng.standard_normal((rows, 1024), dtype=np.float32)
             for i in range(n_tensors)}
    payload = sum(v.nbytes for v in state.values())
    mgr = CheckpointManager(d, max_to_keep=None, engine=engine)

    # The row's own ceiling: the SAME payload through the engine's
    # aligned O_DIRECT streaming writer as ONE structureless tensor —
    # a write row without a write ceiling can't say whether 0.4 GiB/s
    # is the writer or the disk, and the delta to the full save prices
    # the checkpoint structure (tiles, manifest, durability flushes).
    # (A naive submit_write of unaligned user memory measures the page
    # cache, not the disk — 2.2 "GiB/s" on a 0.5 GiB/s device.)
    from nvme_strom_tpu.formats.safetensors import write_safetensors_engine
    raw_path = os.path.join(d, "raw_write.safetensors")
    blob = {"blob": np.concatenate([v.view(np.uint8).reshape(-1)
                                    for v in state.values()])}
    engine.sync_stats()
    pre_raw_direct = engine.stats.bytes_written_direct
    raw_rates = []
    for _ in range(2):
        t0 = time.monotonic()
        write_safetensors_engine(raw_path, blob, engine)
        raw_rates.append(payload / (1 << 30)
                         / (time.monotonic() - t0))
        os.unlink(raw_path)
    del blob            # don't hold a 2nd payload copy through the saves
    engine.sync_stats()
    # a buffered ceiling is a page-cache number, not a disk ceiling —
    # grade against it only when the bytes actually went O_DIRECT
    raw_is_direct = (engine.stats.bytes_written_direct - pre_raw_direct
                     >= payload * 2)
    raw_write = max(raw_rates)

    engine.sync_stats()
    pre_direct = engine.stats.bytes_written_direct
    rates = []
    for step in range(_RUNS + 1):
        t0 = time.monotonic()
        mgr.save(step, state)
        r = payload / (1 << 30) / (time.monotonic() - t0)
        if step > 0:           # step 0 warms jit/allocator paths
            rates.append(r)
    engine.sync_stats()
    direct_w = engine.stats.bytes_written_direct - pre_direct
    mode = ("durable O_DIRECT" if direct_w >= payload * _RUNS
            else "BUFFERED (unaligned spans or fs rejects O_DIRECT; "
                 "page-cache speed)")
    ph = getattr(mgr, "last_save_phases", {})
    shutil.rmtree(d, ignore_errors=True)
    rate = statistics.median(rates)
    ceiling = (f"raw_write={raw_write:.3f} GiB/s same-run "
               f"(save at {rate / raw_write:.0%} of it)"
               if raw_is_direct else
               f"raw_write=BUFFERED {raw_write:.3f} GiB/s "
               "(page-cache number, no disk ceiling on this fs)")
    return rate, (
        f"{payload >> 20}MiB/save, {mode}, {ceiling}, phases: "
        f"tiles={ph.get('tiles_s', -1):.3f}s "
        f"commit={ph.get('commit_s', -1):.3f}s (commit = manifest+"
        f"rename durability flushes; amortizes at real sizes)")


def bench_multistream(engine, nbytes: int,
                      n_streams: int = 4) -> tuple[float, str]:
    """Config 8: N concurrent file streams through ONE engine vs the same
    files read serially.  The reference's striped-raid0 story is multiple
    NVMe queues busy at once (BASELINE.md 6–10 GB/s over 3–4 SSDs); the
    engine-side requirement that story rests on is that concurrent
    streams share the queue without collapsing — scaling ≈1.0 on one SSD
    (both serial and concurrent saturate the device), >1 only on striped
    or multi-device rigs."""
    from concurrent.futures import ThreadPoolExecutor
    per = max(1 << 20, nbytes // n_streams) & ~4095
    paths = []
    for s in range(n_streams):
        p = os.path.join(_scratch_dir(), f"ms-{s}.bin")
        bench.make_file(p, per)
        paths.append(p)

    def read_one(path: str, depth: int) -> None:
        _pipelined_read(engine, path, depth)

    # Same TOTAL in-flight budget for both passes (the full queue depth):
    # serial runs one stream at full depth, concurrent N streams at
    # depth/N.  A throttled serial baseline would fake >1.0 scaling on a
    # single SSD, which is exactly the dishonesty this row must not have.
    full_depth = max(2, engine.config.queue_depth)
    per_stream_depth = max(2, engine.config.queue_depth // n_streams)

    def serial_pass() -> float:
        t0 = time.monotonic()
        for p in paths:
            read_one(p, full_depth)
        return n_streams * per / (1 << 30) / (time.monotonic() - t0)

    def concurrent_pass() -> float:
        t0 = time.monotonic()
        with ThreadPoolExecutor(n_streams) as ex:
            list(ex.map(lambda p: read_one(p, per_stream_depth), paths))
        return n_streams * per / (1 << 30) / (time.monotonic() - t0)

    serial = _steady(paths, serial_pass)
    conc = _steady(paths, concurrent_pass)
    scaling = conc / serial if serial > 0 else 0.0

    # Two-ENGINE aggregate at fixed per-stream depth (round-2 verdict
    # #8): the striped story's other half — independent engines (one per
    # member, each with its own ring/pool) must aggregate near-linearly
    # when the devices can take it.  Per-member attribution runs via the
    # simulated stripe geometry, so the accounting path the real-raid
    # rig would use is exercised and reported here.
    agg, agg_tag = _two_engine_aggregate(paths[:2])
    return conc, (f"streams={n_streams} scaling={scaling:.2f}x vs "
                  f"serial, {agg_tag}")


def _pipelined_read(eng, path: str, depth: int) -> int:
    """Whole-file depth-windowed engine read, payload discarded; the one
    read loop configs 1/8 (and the two-engine aggregate) share."""
    fh = eng.open(path)
    try:
        size = eng.file_size(fh)
        chunk = eng.config.chunk_bytes
        pend = []
        for off in range(0, size, chunk):
            pend.append(eng.submit_read(fh, off,
                                        min(chunk, size - off)))
            if len(pend) >= depth:
                p = pend.pop(0)
                p.wait()
                p.release()
        for p in pend:
            p.wait()
            p.release()
        return size
    finally:
        eng.close(fh)


def _two_engine_aggregate(paths) -> tuple[float, str]:
    from contextlib import ExitStack
    from concurrent.futures import ThreadPoolExecutor
    from nvme_strom_tpu.io.engine import StromEngine
    from nvme_strom_tpu.utils.config import EngineConfig
    from nvme_strom_tpu.utils.stats import StromStats

    saved = {k: os.environ.get(k)
             for k in ("STROM_STRIPE_ACCT", "STROM_STRIPE_SIM")}
    os.environ["STROM_STRIPE_ACCT"] = "1"
    os.environ.setdefault("STROM_STRIPE_SIM", "256:2")
    try:
        with ExitStack() as stack:
            stats = [StromStats(), StromStats()]
            engines = [StromEngine(EngineConfig(), stats=s)
                       for s in stats]
            for eng in engines:
                stack.callback(eng.close_all)
            depth = max(2, engines[0].config.queue_depth // 2)

            def single() -> float:
                t0 = time.monotonic()
                n = _pipelined_read(engines[0], paths[0], depth)
                return n / (1 << 30) / (time.monotonic() - t0)

            def both() -> float:
                t0 = time.monotonic()
                with ThreadPoolExecutor(2) as ex:
                    ns = list(ex.map(
                        lambda a: _pipelined_read(engines[a[0]], a[1],
                                                  depth),
                        enumerate(paths)))
                return sum(ns) / (1 << 30) / (time.monotonic() - t0)

            one = _steady(paths[:1], single)
            agg = _steady(paths, both)
            members: dict = {}
            for s in stats:
                for m, v in s.member_bytes.items():
                    members[m] = members.get(m, 0) + v
        total = max(1, sum(members.values()))
        dist = "/".join(f"{100 * v / total:.0f}%"
                        for _, v in sorted(members.items()))
        return agg, (f"2-engine agg={agg:.3f} GiB/s "
                     f"({agg / one:.2f}x of one, members {dist})")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# --------------------------- compute rows ------------------------------

#: per-chip dense bf16 peak FLOP/s (public spec sheets), matched by
#: substring against ``device_kind``.  MFU needs a denominator; on an
#: unrecognized device the suite reports achieved TFLOP/s with mfu=null
#: rather than inventing a peak.
_TPU_PEAK_BF16 = (("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12),
                  ("trillium", 918e12), ("v6", 918e12), ("v4", 275e12))


def _peak_flops(dev) -> float | None:
    kind = (getattr(dev, "device_kind", "") or "").lower()
    for key, val in _TPU_PEAK_BF16:
        if key in kind:
            return val
    return None


def _matmul_param_count(params) -> int:
    """Matmul-participating parameter count: every ≥2-d weight except the
    token embedding (a gather, not a matmul).  6·T·this is the standard
    fwd+bwd matmul-FLOPs estimate (PaLM appendix B convention)."""
    return sum(int(v.size) for k, v in params.items()
               if getattr(v, "ndim", 0) >= 2 and k != "tok_embed")


def _tiny_compute() -> bool:
    """STROM_SUITE_TINY_COMPUTE=1 shrinks the compute rows to CI scale
    (the CPU-pinned test suite can't push half a TFLOP per step)."""
    return os.environ.get("STROM_SUITE_TINY_COMPUTE") == "1"


def _bench_cfg(train_override: bool = False):
    """One config for both compute rows.  Sized by measurement on the
    v5e: MFU scales with matmul size (d=512 → 8.8%, d=1024 → 15.7%,
    d=2048 → 35.3% at b=8 s=1024), so the row uses d=2048 — large enough
    for real MXU tiles, small enough to compile in ~20 s.  remat stays
    off: it costs ~6 points of measured MFU here (recompute FLOPs are
    real but not model FLOPs) and HBM fits the activations at this
    size.

    ``train_override=True`` (the train/profile rows ONLY) honors
    STROM_TRAIN_CFG; decode/kv/serving rows ignore it — their ledger
    tags carry no shape, so an override there would produce rows
    indistinguishable from default-config ones."""
    from nvme_strom_tpu.models.transformer import TransformerConfig
    if _tiny_compute():
        if train_override and os.environ.get("STROM_TRAIN_CFG"):
            _log("suite: STROM_TRAIN_CFG ignored under "
                 "STROM_SUITE_TINY_COMPUTE=1 (tiny shape wins)")
        return TransformerConfig(vocab=256, d_model=64, n_layers=2,
                                 n_heads=4, n_kv_heads=2, d_ff=128,
                                 max_seq=256)
    cfg = TransformerConfig(vocab=16384, d_model=2048, n_layers=8,
                            n_heads=16, n_kv_heads=8, d_ff=5632,
                            max_seq=2048)
    # STROM_TRAIN_CFG="d=4096,L=2,ff=11008,heads=32,kv=8[,vocab=N]"
    # overrides the model shape — the MFU curve is matmul-size-bound
    # (still rising at d=2048), so the sweep needs points where the
    # per-layer matmuls are bigger than the default's.  A bad spec is
    # logged and ignored: one typo must not lose a scarce TPU window.
    spec = os.environ.get("STROM_TRAIN_CFG", "") if train_override else ""
    if spec:
        alias = {"d": "d_model", "L": "n_layers", "ff": "d_ff",
                 "heads": "n_heads", "kv": "n_kv_heads",
                 "vocab": "vocab", "xc": "xent_chunks",
                 "s": "max_seq"}
        try:
            kw = {}
            for part in spec.split(","):
                k, v = part.split("=")
                kw[alias[k.strip()]] = int(v)
            cfg = dataclasses.replace(cfg, **kw)
            _log(f"suite: train cfg override {kw}")
        except (ValueError, KeyError) as e:
            _log(f"suite: ignoring bad STROM_TRAIN_CFG {spec!r} ({e}); "
                 f"want 'd=4096,L=2,ff=11008,heads=32,kv=8'")
    return cfg


def bench_decode(device=None) -> tuple[float, str]:
    """Config 6: autoregressive decode throughput.  The whole generation
    is one jitted lax.scan (models/decode.py), so the number measures
    on-device steady-state decode, not per-token dispatch.

    Two regimes (measured on the v5e, d=2048, prefill-subtracted): short
    cache, where XLA's fused einsum wins (6726 vs 4916 tok/s at S≈160),
    and long cache, where the Pallas decode-attention kernel is ~1.7x
    faster (3066 vs 1813 tok/s at S≈1856) — each regime runs its winner;
    the short number is the headline value, the long-context one rides
    the metric tag."""
    import functools
    import jax
    import jax.numpy as jnp
    from nvme_strom_tpu.models.decode import generate
    from nvme_strom_tpu.models.transformer import init_params
    from nvme_strom_tpu.ops.decode_attention import make_decode_attn
    cfg = _bench_cfg()
    # tiny: 48 decode steps vs an 8-token prefill so the prefill-
    # subtracted decode time stays well clear of CPU timing noise
    batch, prompt_len, new = (2, 8, 48) if _tiny_compute() else (8, 32, 128)
    dev = device or jax.devices()[0]
    params = jax.device_put(init_params(jax.random.key(0), cfg), dev)

    def run_gen(plen: int, n_new: int, cache_attn) -> float:
        """Steady-state decode tok/s: the timed window of a full
        generate() includes the prompt prefill, so a prefill-only run
        (max_new_tokens=1) is measured too and subtracted — the rate is
        (n_new - 1) decode steps over decode-only time, not prefill
        amortized over the generated tokens."""
        prompt = jax.device_put(jax.random.randint(
            jax.random.key(1), (batch, plen), 0, cfg.vocab,
            dtype=jnp.int32), dev)

        def med_time(n_tok: int) -> float:
            gen = jax.jit(functools.partial(
                generate, cfg=cfg, max_new_tokens=n_tok,
                cache_attn=cache_attn))
            gen(params, prompt).block_until_ready()  # compile (discarded)
            ts = []
            for _ in range(_RUNS):
                t0 = time.monotonic()
                gen(params, prompt).block_until_ready()
                ts.append(time.monotonic() - t0)
            return statistics.median(ts)

        t_full = med_time(n_new)
        t_prefill = med_time(1)
        if t_full <= t_prefill * 1.02:
            # Timing noise swallowed the decode phase (tiny configs on a
            # loaded CPU).  0.0 is visibly invalid; a clamped division
            # would record an absurd tok/s as if it were real.
            _log(f"suite: WARNING decode timing invalid "
                 f"(t_full={t_full:.4f}s <= t_prefill={t_prefill:.4f}s) "
                 f"— reporting 0.0")
            return 0.0
        return batch * (n_new - 1) / (t_full - t_prefill)

    short = run_gen(prompt_len, new, None)
    tag = f"batch={batch} new={new}"
    # int8 weight-only leg: decode is weight-streaming bound, so the
    # halved weight bytes should show directly (models/quant.py); the
    # fp params are swapped out so both legs fit side by side
    from nvme_strom_tpu.models.quant import quantize_weights_int8
    qparams = jax.device_put(quantize_weights_int8(
        jax.device_get(params)), dev)
    fp_params, params = params, qparams
    int8_rate = run_gen(prompt_len, new, None)
    params = fp_params
    if short > 0 and int8_rate > 0:
        tag += f", int8={int8_rate:.0f}tok/s ({int8_rate / short:.2f}x)"
    else:   # the 0.0 timing-invalid sentinel must not fabricate a ratio
        tag += f", int8={int8_rate:.0f}tok/s (ratio n/a)"
    # Long-context leg: TPU only — off-TPU the Pallas kernel runs in the
    # interpreter, where a d=2048 S~1856 scan would take hours.
    if not _tiny_compute() and jax.default_backend() == "tpu":
        long_plen = cfg.max_seq - 256
        long_rate = run_gen(long_plen, 64, make_decode_attn())
        tag += (f", longctx={long_rate:.0f}tok/s"
                f"@S{long_plen + 64}(pallas)")
    return short, tag


def bench_kv_offload(engine, device=None) -> tuple[float, str]:
    """Config 10: decode throughput with the SSD-backed KV cache.

    The HBM window holds only a fraction of the attention history; the
    rest streams back from NVMe through the engine every step.  The
    tok/s is storage-bound BY DESIGN — the row prices the capability of
    decoding past HBM, and the tag reports the per-token streamed bytes
    so the number can be sanity-checked against raw bandwidth."""
    import jax
    import jax.numpy as jnp
    from nvme_strom_tpu.models import decode as _dec
    from nvme_strom_tpu.models.kv_offload import (
        OffloadConfig, PagedKVCache, offload_decode_step)
    from nvme_strom_tpu.models.transformer import init_params
    cfg = _bench_cfg()
    if _tiny_compute():
        batch, plen, steps, page_len, wpages = 2, 24, 8, 8, 1
    else:
        batch, plen, steps, page_len, wpages = 8, 1024, 16, 128, 2
    dev = device or jax.devices()[0]
    params = jax.device_put(init_params(jax.random.key(0), cfg), dev)
    prompt = jax.device_put(jax.random.randint(
        jax.random.key(1), (batch, plen), 0, cfg.vocab, dtype=jnp.int32),
        dev)
    dense = _dec.init_cache(cfg, batch, plen)
    logits, dense = _dec.prefill(params, prompt, cfg, dense)
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    quant = os.environ.get("STROM_KVOFF_QUANT") or None
    host_cache = int(os.environ.get("STROM_KVOFF_HOSTCACHE", "0") or 0)
    ocfg = OffloadConfig(
        path=os.path.join(_scratch_dir(), "kvoff.bin"),
        page_len=page_len, window_pages=wpages, quantize=quant,
        host_cache_pages=host_cache)
    stats = engine.stats
    with PagedKVCache(cfg, ocfg, engine, batch, device=dev) as cache:
        cache.append(dense["k"], dense["v"])
        del dense
        # first step compiles the per-layer segments — discard it
        logits = offload_decode_step(params, tok, cfg, cache)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        # Cold discipline (suite docstring): the pages were JUST
        # written, so without eviction a buffered-fs run would stream
        # them from DRAM and call it SSD bandwidth.  Mid-loop evictions
        # re-dirty the cache; the direct-read share in the tag is the
        # honest label for whatever the fs allowed.
        bench.evict_file(ocfg.path)
        engine.sync_stats()
        dev0, dir0 = stats.bytes_to_device, stats.bytes_direct
        rd0 = dir0 + stats.bytes_fallback
        ts = []
        for _ in range(steps):
            t0 = time.monotonic()
            logits = offload_decode_step(params, tok, cfg, cache)
            logits.block_until_ready()
            ts.append(time.monotonic() - t0)
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
        engine.sync_stats()
        streamed = (stats.bytes_to_device - dev0) / steps
        read_total = stats.bytes_direct + stats.bytes_fallback - rd0
        direct_share = ((stats.bytes_direct - dir0) / read_total
                        if read_total else 0.0)
        # measured AFTER the loop: the steps themselves evict pages
        cold_frac = 1 - cache.count / cache.pos
    rate = batch / statistics.median(ts)
    tag = (f"ctx={plen} window={ocfg.window} cold={cold_frac:.0%} "
           f"stream/tok={streamed / 2**20:.1f}MiB "
           f"direct={direct_share:.0%}")
    if quant:
        tag += f" quant={quant}"
    if host_cache:
        tag += f" hostcache={host_cache}p"
    return rate, tag


def bench_serving(device=None) -> tuple[float, str]:
    """Config 11: continuous-batching aggregate decode throughput.

    Mixed-length requests keep every slot busy (a freed slot admits the
    next request mid-flight); the number is total generated tokens over
    wall-clock from first step to drain, admission prefills included —
    the end-to-end serving rate, not a per-step best case."""
    import jax
    from nvme_strom_tpu.models.serving import (DecodeServer,
                                               PagedDecodeServer)
    from nvme_strom_tpu.models.transformer import init_params
    cfg = _bench_cfg()
    if _tiny_compute():
        slots, n_req, max_len = 2, 4, 64
        lens = [5, 9, 13, 7]
        news = [6, 8, 5, 7]
    else:
        slots, n_req, max_len = 8, 24, 1536
        lens = [128 + 61 * (i % 7) for i in range(n_req)]
        news = [64 + 17 * (i % 5) for i in range(n_req)]
    dev = device or jax.devices()[0]
    params = jax.device_put(init_params(jax.random.key(0), cfg), dev)
    paged = os.environ.get("STROM_SERVE_PAGED") == "1"
    block_len = 16 if _tiny_compute() else 128
    # pool sized for the live-token high-water mark: the `slots`
    # largest concurrent worst cases (the paged design point — far
    # below slots × max_len)
    shared_prefix = os.environ.get("STROM_SERVE_SHARED_PREFIX")
    shared = []
    if shared_prefix:
        # config-11 variant: every request shares a system prompt of N
        # tokens — the paged server's automatic prefix caching prefills
        # it once and reuses the blocks (tag reports the cache gauges)
        import numpy as np
        shared = np.random.default_rng(2).integers(
            0, cfg.vocab, int(shared_prefix)).tolist()
    worst = sorted((len(shared) + l + n for l, n in zip(lens, news)),
                   reverse=True)[:slots]
    total_blocks = sum(-(-w // block_len) for w in worst)

    def make():
        if paged:
            return PagedDecodeServer(params, cfg, max_batch=slots,
                                     max_len=max_len,
                                     total_blocks=total_blocks,
                                     block_len=block_len)
        return DecodeServer(params, cfg, max_batch=slots,
                            max_len=max_len)

    def submit_all(srv):
        import numpy as np
        rng = np.random.default_rng(1)
        for i in range(n_req):
            srv.submit(i, shared
                       + rng.integers(0, cfg.vocab, lens[i]).tolist(),
                       news[i])

    # decode sub-steps per host readback: the round-3 on-silicon row
    # (43.6 tok/s vs 6,826 decode) was one blocking readback per token
    # over a high-latency link; lookahead amortizes it (verdict #6)
    lookahead = int(os.environ.get("STROM_SERVE_LOOKAHEAD", "8"))

    # warmup run compiles the step + admission buckets (discarded)
    srv = make()
    submit_all(srv)
    srv.run(lookahead=lookahead)
    ts = []
    for _ in range(_RUNS):
        srv = make()
        submit_all(srv)
        t0 = time.monotonic()
        out = srv.run(lookahead=lookahead)
        ts.append(time.monotonic() - t0)
    total = sum(news)
    wall = statistics.median(ts)
    rate = total / wall
    # phase attribution from the LAST run (its wall time for scale):
    # admission+prefill, back-to-back dispatch, readback syncs, and
    # the host-scheduling remainder
    tm = srv.timings
    other = max(ts[-1] - tm["admit_s"] - tm["dispatch_s"]
                - tm["readback_s"], 0.0)
    tag = (f"slots={slots} reqs={n_req} tok/req~{total // n_req} "
           f"lookahead={lookahead}; phases(last run "
           f"{ts[-1]:.2f}s): admit={tm['admit_s']:.2f}s "
           f"dispatch={tm['dispatch_s']:.2f}s "
           f"readback={tm['readback_s']:.2f}s({tm['readbacks']}x) "
           f"sched={other:.2f}s, steps={tm['steps']}")
    if paged:
        tag += (f" paged={total_blocks}x{block_len} "
                f"({total_blocks * block_len * 100 // (slots * max_len)}"
                f"% of dense)")
        if shared:
            st = srv.stats()
            tag += (f", shared_prefix={len(shared)}tok "
                    f"hits={st['prefix_hits']} "
                    f"reused_blocks={st['prefix_shared_blocks']}")
    return rate, tag


def bench_kvserve(engine, device=None) -> tuple[float, str]:
    """Config 19: serving throughput with the content-addressed NVMe
    KV prefix store (models/kv_offload.py PrefixStore, docs/PERF.md
    §5).

    Mixed-length requests share a system prompt; the run measures the
    store-ON steady state (prefix pages restored from NVMe through the
    decode-class batched read path instead of re-prefilled) and pairs
    it with an identical store-OFF run in the same process — the tag
    carries both TTFT averages, the aggregate-rate ratio, and the
    store's dedupe/hit counters.  tok/s is the headline because the
    prefix win IS admission time: every re-prefilled shared token is
    wall-clock the batch spends not decoding."""
    import jax
    from nvme_strom_tpu.models.kv_offload import PrefixStore
    from nvme_strom_tpu.models.serving import DecodeServer
    from nvme_strom_tpu.models.transformer import init_params
    cfg = _bench_cfg()
    # the shared prefix must be LONG relative to a page: the win is
    # admission prefill skipped, and a too-short prefix costs as much
    # to restore as to recompute — so the tiny row keeps the tiny
    # WIDTH but serves real sequence lengths (the prefill cost being
    # skipped is attention-length-bound, dispatch included)
    if _tiny_compute():
        cfg = dataclasses.replace(cfg, max_seq=1024)
        slots, n_req, max_len, page_tokens, n_pages, max_new = \
            2, 6, 512, 32, 8, 6
    else:
        slots, n_req, max_len, page_tokens, n_pages, max_new = \
            8, 24, 1536, 64, 8, 48
    dev = device or jax.devices()[0]
    params = jax.device_put(init_params(jax.random.key(0), cfg), dev)
    import numpy as np
    rng = np.random.default_rng(5)
    shared = rng.integers(0, cfg.vocab, n_pages * page_tokens).tolist()
    reqs = [(i, shared + rng.integers(
        0, cfg.vocab, 2 + int(rng.integers(0, 5))).tolist(), max_new)
        for i in range(n_req)]
    lookahead = int(os.environ.get("STROM_SERVE_LOOKAHEAD", "8"))
    store_path = os.path.join(_scratch_dir(), "suite.kvstore")
    stats = engine.stats
    snap0 = stats.snapshot()

    def run(store) -> tuple[float, float]:
        srv = DecodeServer(params, cfg, max_batch=slots,
                           max_len=max_len, kv_store=store)
        for rid, p, m in reqs:
            srv.submit(rid, p, m)
        t0 = time.monotonic()
        srv.run(lookahead=lookahead)
        wall = time.monotonic() - t0
        ttft = (sum(v["ttft_ms"] for v in srv.request_metrics.values())
                / max(1, len(srv.request_metrics)))
        return sum(m for _r, _p, m in reqs) / wall, ttft

    run(None)                      # warm: compiles the store-off phases
    with PrefixStore(cfg, engine, store_path, page_tokens=page_tokens,
                     capacity_bytes=64 << 20) as store:
        run(store)                 # seed: writes the shared pages once
        #                            and compiles the restore phases
        # alternating trials + medians (the bench_mixed discipline):
        # host noise drifts within a suite step, and a single
        # off-then-on pair ratios one mode against the other's minute
        offs, ons = [], []
        for _ in range(3):
            offs.append(run(None))
            ons.append(run(store))
    rate_off, ttft_off = sorted(offs)[len(offs) // 2]
    rate_on, ttft_on = sorted(ons)[len(ons) // 2]
    snap1 = stats.snapshot()
    d = lambda k: int(snap1.get(k, 0)) - int(snap0.get(k, 0))  # noqa: E731
    hits, misses = d("kv_prefix_hits"), d("kv_prefix_misses")
    tag = (f"reqs={n_req} shared={n_pages * page_tokens}tok "
           f"page={page_tokens}tok; TTFT off={ttft_off:.1f}ms "
           f"on={ttft_on:.1f}ms ({100 * (ttft_off - ttft_on) / ttft_off:+.1f}% "
           f"off-rate={rate_off:.1f}tok/s ratio={rate_on / rate_off:.2f}); "
           f"hit_rate={hits / max(1, hits + misses):.3f} "
           f"deduped={d('kv_pages_deduped')} "
           f"saved={_human_int(d('kv_bytes_saved'))} "
           f"restored={d('kv_pages_restored')}")
    return rate_on, tag


def _human_int(n: int) -> str:
    from nvme_strom_tpu.utils.stats import human_bytes
    return human_bytes(float(n)).replace(" ", "")


def _train_setup(cfg, batch: int, seq: int, dev, attn: str = "dense"):
    """(params, opt_state, tokens, step, flops_step) shared by the
    synthetic (config 7) and NVMe-fed (config 17) train rows — ONE
    copy of the donated-step construction and the 6·T·P + attention
    model-FLOP formula, so the two TFLOP/s rows cannot diverge."""
    import jax
    import jax.numpy as jnp
    import optax
    from nvme_strom_tpu.models.transformer import (init_params,
                                                   make_train_step)
    attn_fn = None
    if attn == "flash":
        from nvme_strom_tpu.ops.flash_attention import make_flash_attn
        attn_fn = make_flash_attn()
    elif attn != "dense":
        raise ValueError(f"attn {attn!r}: expected dense|flash")
    params = jax.device_put(init_params(jax.random.key(0), cfg), dev)
    opt = optax.adamw(1e-3)
    opt_state = jax.device_put(opt.init(params), dev)
    tokens = jax.device_put(jax.random.randint(
        jax.random.key(1), (batch, seq), 0, cfg.vocab, dtype=jnp.int32),
        dev)
    n_matmul = _matmul_param_count(params)
    flops_step = (6 * batch * seq * n_matmul
                  + 12 * cfg.n_layers * batch * seq * seq * cfg.d_model)
    step = jax.jit(make_train_step(cfg, opt, attn_fn=attn_fn),
                   donate_argnums=(0, 1))
    return params, opt_state, tokens, step, flops_step


def _loss_sanity(vals: list) -> None:
    """A real Adam trajectory moves the loss every step and keeps it
    finite; anything else means the device did not actually run the
    program (the tunneled runtime has returned garbage instead of
    raising)."""
    if not all(math.isfinite(v) for v in vals) or len(set(vals)) <= 1:
        raise RuntimeError(f"loss sanity failed (runtime returned "
                           f"garbage without raising): losses={vals[:6]}")


def _train_variant(cfg, batch: int, seq: int, dev,
                   profile_dir: str | None = None,
                   attn: str = "dense") -> float:
    """Aggregate model-FLOP/s of one (config, batch, attn) train-step
    variant — _RUNS chained steps in ONE timed window bracketed by
    data-dependent host transfers (not per-step medians: per-step
    blocking is exactly what the axon runtime lies about); optionally
    capture a 3-step jax profiler trace while at it.  ``attn``:
    "dense" (XLA) or "flash" (the Pallas fused kernel — O(s) memory,
    the long-context/occupancy lever)."""
    import jax
    params, opt_state, tokens, step, flops_step = _train_setup(
        cfg, batch, seq, dev, attn=attn)
    if profile_dir:
        # the post-optimization HLO names the profiler's events: the
        # valid window-7 parses put ~70% of device time in bare
        # "%fusion.NN" buckets, which explains nothing — dumping the
        # compiled module lets profile_report resolve each fusion to
        # its constituent ops (dot/reduce/elementwise) and attribute
        # the MFU ceiling for real.  AOT lower+compile of the SAME jit
        # hits the compile cache; donation only applies at execution.
        try:
            txt = step.lower(params, opt_state, tokens).compile().as_text()
            os.makedirs(profile_dir, exist_ok=True)
            with open(os.path.join(profile_dir, "optimized_hlo.txt"),
                      "w") as f:
                f.write(txt)
        except Exception as e:          # remote helper may not serve it
            _log(f"suite: optimized-HLO dump unavailable: {e!r}")
    params, opt_state, loss = step(params, opt_state, tokens)  # compile
    jax.block_until_ready((params, opt_state, loss))
    # Timing discipline, third iteration.  Round-3 lesson: loss-only
    # blocking returned early (44x/163x peak).  Full-tree
    # block_until_ready fixed d2048 but the 2026-07-31T18:01 window
    # STILL ledgered d3072/d4096 at 114x/42x peak with rc=0 AND an
    # evolving, finite loss — on those shapes the axon runtime's
    # block_until_ready itself returns before execution while the
    # device runs the chain asynchronously.  So don't trust blocking at
    # all: bracket N CHAINED steps between data-dependent host
    # transfers.  float(loss) before the clock pins the start; the
    # final float() cannot produce bytes until every chained step has
    # executed (step k consumes step k-1's donated params), so
    # dispatch-only timing is impossible by construction.
    float(loss)                       # host round-trip: timeline start
    losses = []
    t0 = time.monotonic()
    for _ in range(_RUNS):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(loss)
    float(losses[-1])                 # forces the whole chain
    elapsed = time.monotonic() - t0
    rate = _RUNS * flops_step / elapsed
    _loss_sanity([float(x) for x in jax.device_get(losses)])
    if profile_dir:
        # the committed profile breakdown for the MFU story: 3 traced
        # steps, viewable in TensorBoard/xprof
        with jax.profiler.trace(profile_dir):
            for _ in range(3):
                params, opt_state, loss = step(params, opt_state,
                                               tokens)
            # data-dependent host fetch, NOT block_until_ready: on the
            # shapes where blocking returns early the trace context
            # would close before the steps execute, committing an
            # empty trace as MFU "evidence"
            float(loss)
        _log(f"suite: wrote jax profiler trace to {profile_dir}")
    del params, opt_state
    return rate


def bench_opt_offload(engine) -> tuple[float, str]:
    """Config 14: NVMe-offloaded Adam (parallel/opt_offload) priced
    against the in-HBM optax step on the same tree.

    The value is the moment-streaming rate: 4× moment payload (2 reads +
    2 writes) per update over the update's wall time — the number that
    says whether the engine keeps the optimizer fed.  The tag prices the
    capability: step-time overhead vs in-HBM adamw, and the HBM the
    moments actually occupy (one group) vs what in-HBM Adam would pin."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from nvme_strom_tpu.parallel.opt_offload import OffloadedAdam

    tiny = _tiny_compute()
    leaf = (1 << 18) if tiny else (1 << 22)       # elements per leaf
    n_leaves = 4 if tiny else 16                  # 4 MiB / 256 MiB params
    ks = jax.random.split(jax.random.key(0), n_leaves)
    params = {f"w{i:02d}": jax.random.normal(k, (leaf,), jnp.float32)
              for i, k in enumerate(ks)}
    grads = {k: jax.random.normal(jax.random.key(hash(k) % (1 << 30)),
                                  v.shape, jnp.float32)
             for k, v in params.items()}
    payload = 2 * sum(v.nbytes for v in params.values())

    # in-HBM reference: one fused jitted adamw step
    opt = optax.adamw(1e-3)
    state = opt.init(params)

    @jax.jit
    def hbm_step(p, s, g):
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    hbm_step(params, state, grads)  # compile
    t0 = time.monotonic()
    reps = 3
    p = params
    for _ in range(reps):
        p, state = hbm_step(p, state, grads)
    jax.block_until_ready(p)
    t_hbm = (time.monotonic() - t0) / reps

    # fresh state every invocation: a stale dir would either resume old
    # moments (not a step-1 benchmark) or refuse on a layout change
    odir = os.path.join(_scratch_dir(), "opt_offload")
    shutil.rmtree(odir, ignore_errors=True)
    with OffloadedAdam(odir, params, lr=1e-3, weight_decay=1e-4,
                       engine=engine,
                       group_bytes=(1 << 22) if tiny else (64 << 20)
                       ) as off:
        off.update(params, grads)   # compile + first touch
        t0 = time.monotonic()
        p = params
        for _ in range(reps):
            p = off.update(p, grads)
        jax.block_until_ready(p)
        t_off = (time.monotonic() - t0) / reps
        peak = off.peak_group_bytes()
        groups = off.num_groups()
    gibs = 2 * payload / t_off / (1 << 30)        # 2R + 2W of the payload
    over = (t_off - t_hbm) / t_hbm if t_hbm > 0 else float("inf")
    # Medium normalization (round-3 verdict #9: the on-silicon row
    # ledgered +3.6M% overhead with no frame — evidence AGAINST the
    # feature absent the link context).  The step must move 2x the
    # moment payload; at the same-run measured link that takes
    # t_floor = bytes/link, so overhead below is bounded by the medium,
    # not the implementation.  The projection column re-prices the step
    # at the same-run RAW SSD rate — the rate a local deployment's
    # storage path actually delivers — and the TUNNEL-BOUND tag fires
    # when >=50% of the step went to link-floor time, telling a reader
    # the headline overhead measures the tunnel.
    raw_ceiling = _CEILINGS.get("raw", 0.0)
    link_ceiling = _CEILINGS.get("link", 0.0)
    moved = 2 * payload
    extra = ""
    if link_ceiling > 0 and raw_ceiling > 0:
        t_floor = moved / (link_ceiling * (1 << 30))
        t_local = max(moved / (raw_ceiling * (1 << 30)), 1e-9)
        over_local = ((t_hbm + t_local) - t_hbm) / t_hbm \
            if t_hbm > 0 else float("inf")
        bound = "TUNNEL-BOUND, " if t_floor >= 0.5 * t_off else ""
        extra = (f", link-normalized: {bound}link-floor="
                 f"{t_floor * 1e3:.0f}ms of {t_off * 1e3:.0f}ms at "
                 f"{link_ceiling:.3f} GiB/s; projected at same-run raw "
                 f"{raw_ceiling:.3f} GiB/s: step="
                 f"{(t_hbm + t_local) * 1e3:.0f}ms "
                 f"overhead={over_local:+.0%}")
    return gibs, (f"moments={payload >> 20}MiB step={t_off * 1e3:.0f}ms "
                  f"overhead={over:+.0%} vs in-HBM "
                  f"({t_hbm * 1e3:.0f}ms), hbm_peak={peak >> 20}MiB of "
                  f"{payload >> 20}MiB, groups={groups}{extra}")


def bench_act_offload(engine, device=None) -> tuple[float, str]:
    """Config 18: NVMe-offloaded saved activations
    (parallel/act_offload, remat_policy="nvme") priced against
    remat="full" — the honest in-HBM comparison, since BOTH recompute
    every layer in backward; the delta is exactly the activation round
    trip (device→host→NVMe→host→device per layer per step) that buys
    O(1)-layers HBM activations below full remat's O(n_layers).

    The value is the activation-streaming rate (2 × layers × act
    bytes per step over the step time); the tag prices step overhead
    vs remat="full" and link-normalizes it like config 14 (on a
    tunneled chip the link floor, not the implementation, bounds the
    overhead)."""
    import jax
    import numpy as np
    from nvme_strom_tpu.parallel.act_offload import ActivationStore
    cfg = _bench_cfg(train_override=True)
    batch, seq = (2, 64) if _tiny_compute() else (8, 1024)
    # honor an applied s= override exactly like bench_train, so a
    # long-context window's config-18 row shares config 7's shape
    if not _tiny_compute() and cfg.max_seq != _bench_cfg().max_seq:
        seq = cfg.max_seq
    dev = device or jax.devices()[0]
    rcfg = dataclasses.replace(cfg, remat_policy="full")
    ncfg = dataclasses.replace(cfg, remat_policy="nvme")
    params, opt_state, tokens, _step_unused, flops_step = _train_setup(
        rcfg, batch, seq, dev)

    import optax
    opt = optax.adamw(1e-3)

    def run(step, p, s, reps=3):
        p, s, loss = step(p, s, tokens)          # compile + warm slots
        jax.block_until_ready(loss)
        float(loss)
        losses = []
        t0 = time.monotonic()
        for _ in range(reps):
            p, s, loss = step(p, s, tokens)
            losses.append(loss)
        float(losses[-1])
        dt = (time.monotonic() - t0) / reps
        _loss_sanity([float(x) for x in jax.device_get(losses)])
        return dt

    from nvme_strom_tpu.models.transformer import make_train_step
    t_full = run(jax.jit(make_train_step(rcfg, opt)), params, opt_state)

    adir = os.path.join(_scratch_dir(), "act_offload")
    shutil.rmtree(adir, ignore_errors=True)
    act_bytes = (batch * seq * cfg.d_model
                 * np.dtype(cfg.dtype).itemsize)
    with ActivationStore(os.path.join(adir, "acts.bin"),
                         cfg.n_layers, engine=engine) as st:
        t_nvme = run(jax.jit(make_train_step(ncfg, opt, act_store=st)),
                     params, opt_state)
    moved = 2 * cfg.n_layers * act_bytes          # 1W + 1R per layer
    gibs = moved / t_nvme / (1 << 30)
    over = (t_nvme - t_full) / t_full if t_full > 0 else float("inf")
    raw_c, link_c = _CEILINGS.get("raw", 0.0), _CEILINGS.get("link", 0.0)
    extra = ""
    if raw_c > 0 and link_c > 0:
        t_floor = moved / (link_c * (1 << 30))
        t_local = moved / (raw_c * (1 << 30))
        bound = "TUNNEL-BOUND, " if t_floor >= 0.5 * t_nvme else ""
        extra = (f", link-normalized: {bound}link-floor="
                 f"{t_floor * 1e3:.0f}ms of {t_nvme * 1e3:.0f}ms at "
                 f"{link_c:.3f} GiB/s; projected at same-run raw "
                 f"{raw_c:.3f} GiB/s: step="
                 f"{(t_full + t_local) * 1e3:.0f}ms "
                 f"overhead={t_local / t_full:+.0%}")
    tag = (f"acts={moved >> 20}MiB/step ({cfg.n_layers} layers x "
           f"{act_bytes >> 20}MiB x2) step={t_nvme * 1e3:.0f}ms "
           f"overhead={over:+.0%} vs remat-full "
           f"({t_full * 1e3:.0f}ms){extra}")
    _log(f"suite: act-offload {tag}")
    return gibs, tag


def bench_fed_train(engine, device=None) -> tuple[float, str]:
    """Config 17: the reference's core identity as ONE number — train
    while the NVMe pipeline feeds REAL token batches, paired in the
    same run against the identical model chained on a device-resident
    batch.  fed/synthetic ≈ 1.0 means storage never starves the MXU
    (the SSD→accelerator direct path doing the job the reference's
    SSD2GPU DMA does for PG-Strom's kernels, SURVEY.md §3.5, applied
    to the training loop); the tag carries both rates, the ratio, and
    the pipeline's byte demand so a sub-1.0 row names its own cause.

    Tokens ride the zero-copy wds_raw path: each tar member is one
    sample row of ``seq`` int32 tokens; bytes go staging→device
    untouched and the int32 assembly + vocab clamp run on device."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from nvme_strom_tpu.data.loader import ShardedLoader
    cfg = _bench_cfg(train_override=True)
    batch, seq = (2, 64) if _tiny_compute() else (8, 1024)
    n_steps = 4 if _tiny_compute() else 16
    dev = device or jax.devices()[0]
    item = seq * 4
    paths = make_wds_shards(os.path.join(_scratch_dir(), "fedtrain"),
                            n_steps * batch * item, item_bytes=item)
    params, opt_state, tokens0, step, flops_step = _train_setup(
        cfg, batch, seq, dev)

    @jax.jit
    def decode_tokens(arr):
        # (batch, seq*4) uint8 → (batch, seq) int32 tokens: assemble
        # little-endian words on the VPU, clamp into the vocab — the
        # raw member bytes ARE the training data, no host touch
        b = arr.reshape(batch, seq, 4).astype(jnp.int32)
        word = b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16)
        return word % cfg.vocab

    params, opt_state, loss = step(params, opt_state, tokens0)  # compile
    jax.block_until_ready((params, opt_state, loss))

    # synthetic window — _train_variant's chained bracket discipline,
    # loss-sanity-gated like every other train row (the axon runtime
    # returns garbage without raising on some shapes)
    float(loss)
    losses = []
    t0 = time.monotonic()
    for _ in range(n_steps):
        params, opt_state, loss = step(params, opt_state, tokens0)
        losses.append(loss)
    float(losses[-1])
    t_syn = time.monotonic() - t0
    _loss_sanity([float(x) for x in jax.device_get(losses)])
    rate_syn = n_steps * flops_step / t_syn

    mesh = Mesh(np.array([dev]).reshape(1), ("dp",))
    with ShardedLoader(paths, mesh, global_batch=batch, fmt="wds_raw",
                       engine=engine) as loader:
        for arr in loader:        # warm: loader jit + decode compile
            params, opt_state, loss = step(params, opt_state,
                                           decode_tokens(arr))
        for p in paths:
            bench.evict_file(p)   # the timed epoch reads the NVMe
        float(loss)
        losses = []
        t0 = time.monotonic()
        for arr in loader:
            params, opt_state, loss = step(params, opt_state,
                                           decode_tokens(arr))
            losses.append(loss)
        float(losses[-1])
        t_fed = time.monotonic() - t0
    n = len(losses)
    _loss_sanity([float(x) for x in jax.device_get(losses)])
    rate_fed = n * flops_step / t_fed
    ratio = rate_fed / rate_syn if rate_syn else float("nan")
    demand = n * batch * item / (1 << 30) / t_fed
    peak = _peak_flops(dev)
    suspect = (" SUSPECT-TIMING (above device peak)"
               if peak and max(rate_fed, rate_syn) > peak else "")
    tag = (f"fed={rate_fed / 1e12:.2f} TFLOP/s over {n} NVMe-fed steps "
           f"vs synthetic={rate_syn / 1e12:.2f} (same run) "
           f"ratio={ratio:.3f}{suspect}; "
           f"pipeline demand={demand:.4f} GiB/s "
           f"d={cfg.d_model} b={batch} s={seq}")
    _log(f"suite: fed-train {tag}")
    return rate_fed / 1e12, tag


def bench_train(device=None) -> tuple[float, str]:
    """Config 7: train-step throughput as model TFLOP/s (and MFU when the
    chip's peak is known).  FLOPs are the 6·T·P matmul estimate plus the
    12·L·b·s²·d attention term — model FLOPs, not hardware FLOPs, so
    remat or XLA fusion can't inflate the number.

    STROM_TRAIN_SWEEP="<batch>:<remat>[:<attn>],..." (remat
    none|dots|full, attn dense|flash) runs several variants and reports
    the best, each in the tag — the MFU lever sweep (batch amortizes
    weight streaming; dots-remat keeps the bigger batch inside HBM at a
    fraction of full remat's recompute; flash trades XLA's fused dense
    attention for the Pallas kernel's O(s) memory).
    STROM_PROFILE_DIR captures a 3-step jax profiler trace of the LAST
    sweep variant (order the sweep so the variant to profile is last —
    tracing rides that variant's measuring run, no re-compile)."""
    import jax
    cfg = _bench_cfg(train_override=True)
    batch, seq = (2, 64) if _tiny_compute() else (8, 1024)
    # an APPLIED max_seq override in STROM_TRAIN_CFG trains at that
    # sequence (the long-context rows); detected from the parsed
    # config — not by re-reading the env var — so a malformed spec
    # (which _bench_cfg logs and ignores) safely keeps the historical
    # s=1024 shape instead of silently training at the default
    # max_seq.  An explicit s= equal to the default is the one
    # indistinguishable case and keeps s=1024.
    if not _tiny_compute() and cfg.max_seq != _bench_cfg().max_seq:
        seq = cfg.max_seq
    dev = device or jax.devices()[0]
    sweep = os.environ.get("STROM_TRAIN_SWEEP", "")
    variants = []
    if sweep:
        for spec in sweep.split(","):
            spec = spec.strip()
            if not spec:
                continue
            parts = spec.split(":")
            try:
                variants.append((int(parts[0]),
                                 parts[1] if len(parts) > 1 and parts[1]
                                 else "none",
                                 parts[2] if len(parts) > 2
                                 and parts[2] else "dense"))
            except (ValueError, IndexError):
                # one typo must not lose the whole (scarce) TPU step
                _log(f"suite: ignoring bad sweep spec {spec!r} "
                     "(want '<batch>:<none|dots|full>[:<dense|flash>]')")
    if not variants:
        variants = [(batch, cfg.remat_policy or "none", "dense")]
    prof = os.environ.get("STROM_PROFILE_DIR")
    results, failures = [], []
    for i, (b, pol, attn) in enumerate(variants):
        vcfg = dataclasses.replace(cfg, remat_policy=pol, remat=False)
        try:
            # trace rides the measuring call of the final variant — no
            # separate re-compile/re-run just to profile
            fs = _train_variant(vcfg, b, seq, dev,
                                profile_dir=(prof if prof and
                                             i == len(variants) - 1
                                             else None), attn=attn)
        except Exception as e:  # noqa: BLE001 — OOM on a sweep point
            reason = (f"b={b} remat={pol} attn={attn} failed: "
                      f"{type(e).__name__}: {str(e)[:160]}")
            _log(f"suite: train variant {reason}")
            failures.append(reason)
            continue
        results.append((fs, b, pol, attn))
        _log(f"suite: train b={b} remat={pol} attn={attn}: "
             f"{fs / 1e12:.3f} TFLOP/s")
    if not results:
        # the reasons must ride the exception: the watcher ledgers only
        # the stderr TAIL, and a traceback alone pushed the per-variant
        # _log diagnosis out of it (2026-07-31 window, 4 opaque rows)
        raise RuntimeError("every train variant failed: "
                           + " | ".join(failures))
    best = max(results)
    peak = _peak_flops(dev)
    note = (f"mfu={best[0] / peak:.1%}" if peak
            else "mfu=null (unknown peak)")
    if peak and best[0] > peak:
        # physically impossible — keep the row but say it's broken so
        # no reader quotes it as a result (and the coverage scheduler
        # retries: _captured_steps treats SUSPECT rows as not-landed)
        note = (f"mfu=SUSPECT-TIMING ({best[0] / peak:.1f}x over "
                f"device peak {peak / 1e12:.0f} TFLOP/s)")
    per = " ".join(f"b{b}/{p}/{a}={fs / 1e12:.2f}"
                   for fs, b, p, a in results)
    # model shape in the tag: the d3072/d4096 sweep rows must be
    # distinguishable from the default-d2048 row in the ledger (every
    # field the STROM_TRAIN_CFG alias map can override appears)
    shape = (f"d={cfg.d_model} L={cfg.n_layers} ff={cfg.d_ff} "
             f"h={cfg.n_heads}/{cfg.n_kv_heads} v={cfg.vocab}"
             + (f" xc={cfg.xent_chunks}" if cfg.xent_chunks > 1 else ""))
    return best[0] / 1e12, (f"{note} {shape} b={best[1]} s={seq} "
                            f"remat={best[2]} attn={best[3]} [{per}]")


# ------------------------------- main ----------------------------------

def run(configs: list[int], emit=None) -> list[dict]:
    """Run ``configs``; returns the result rows.  ``emit`` (if given) is
    called with each row THE MOMENT it exists — the watcher harvests
    stdout even from a timed-out step, so a row printed before a tunnel
    death still lands in the ledger (round-3 weak #3: suite_15 completed
    its work, hung in teardown, and landed nothing)."""
    from nvme_strom_tpu.io import StromEngine
    from nvme_strom_tpu.utils.compile_cache import enable_compile_cache
    from nvme_strom_tpu.utils.config import EngineConfig
    from nvme_strom_tpu.utils.stats import StromStats

    # every suite step is a fresh subprocess through a tunnel where one
    # compile costs 20-40s (and has burned 900s step timeouts) — load
    # serialized executables from the repo-local disk cache instead
    enable_compile_cache()

    # hang budget (STROM_SUITE_BUDGET_S, set by the watcher to its step
    # timeout minus a margin): a wedged device op self-reports its phase
    # instead of silently burning the watcher's timeout
    budget_s = float(os.environ.get("STROM_SUITE_BUDGET_S", "0") or 0)
    if budget_s > 0:
        _WATCHDOG.arm(budget_s)

    nbytes = _suite_bytes()
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        device_ok = False      # explicitly pinned to CPU: skip the probe
    else:
        device_ok = bench.probe_device()
    if not device_ok:
        bench.force_cpu()
    dev_tag = "tpu" if device_ok else "cpu-fallback"

    raw_path = os.path.join(_scratch_dir(), "raw.bin")
    bench.make_file(raw_path, nbytes)
    stats = StromStats()
    results = []
    with StromEngine(EngineConfig(), stats=stats) as engine:
        _log(f"suite: backend={engine.backend} bytes/config={nbytes >> 20}"
             f"MiB dev={dev_tag}")
        # Backing-device topology: makes a striped (md-raid0) rig — the
        # reference's 6-10 GB/s configuration — observable in the log.
        from nvme_strom_tpu.io.engine import resolve_device
        dinfo = resolve_device(_scratch_dir())
        _log(f"suite: blockdev={dinfo.device or 'none'} "
             f"nvme={dinfo.is_nvme} "
             f"raid_level={dinfo.raid_level if dinfo.is_raid else None} "
             f"members={list(dinfo.members)}")
        raw = bench.bench_raw(engine, raw_path)
        link = bench.bench_link()
        # same-run ceilings, visible to configs that normalize against
        # the medium (config 14 prices its moment stream against the
        # link it actually rode — round-3 verdict #9)
        _CEILINGS.update(raw=raw, link=link)
        ceiling = 0.9 * (min(raw, link) if raw > 0 and link > 0
                         else max(raw, link, 1.0))
        _log(f"suite: raw={raw:.3f} GiB/s link={link:.3f} GiB/s "
             f"target=0.9·min={ceiling:.3f} GiB/s")
        link_probe = None
        if device_ok:
            # per-pass link pairing (module header ¶3): one quick burst
            # before every timed pass; plain numpy→device_put, so the
            # engine's bounce/direct accounting never sees probe bytes
            import jax
            _pdev = jax.devices()[0]
            _pbufs = bench._link_bufs(6, engine.config.chunk_bytes)
            jax.device_put(_pbufs[0], _pdev).block_until_ready()
            link_probe = lambda: bench._link_pass(_pbufs, _pdev)  # noqa: E731

        # (label, fn, unit, io_row) — io_row=True rows are GiB/s against
        # the north-star ceiling; compute rows have no BASELINE.json
        # target (the reference is a storage engine) → vs_baseline null.
        names = {
            1: ("raw-sequential-read", lambda: (raw, nbytes),
                "GiB/s", True),
            2: ("arrow-to-device", lambda: bench_arrow(engine, nbytes),
                "GiB/s", True),
            3: ("wds-sharded-loader", lambda: bench_loader(engine, nbytes),
                "GiB/s", True),
            4: ("safetensors-lazy-load",
                lambda: bench_weights(engine, nbytes), "GiB/s", True),
            5: ("parquet-groupby-scan", lambda: bench_sql(engine, nbytes),
                "GiB/s", True),
            6: ("decode-throughput", bench_decode, "tok/s", False),
            7: ("train-step-flops", bench_train, "TFLOP/s", False),
            8: ("multistream-scaling",
                lambda: bench_multistream(engine, nbytes), "GiB/s", True),
            # write bandwidth has no read-derived ceiling: io_row=False
            # keeps vs_baseline null rather than faking a ratio
            9: ("checkpoint-write",
                lambda: bench_checkpoint_write(engine, nbytes),
                "GiB/s", False),
            # storage-bound by design (decode beyond HBM): tok/s is not
            # a GiB/s row, so no north-star ratio applies
            10: ("kv-offload-decode",
                 lambda: bench_kv_offload(engine), "tok/s", False),
            11: ("serving-throughput", bench_serving, "tok/s", False),
            # decompression-bound, not link-bound: the speedup vs the
            # pyarrow fallback (in the tag) is the claim, not a ratio
            # against the raw-read ceiling
            12: ("parquet-zstd-scan",
                 lambda: bench_sql_zstd(engine, nbytes), "GiB/s", False),
            # accounting row: the tag's bounce_vs_idx_raw ratio is the
            # claim (host touches only the raw index stream); decode-
            # bound, so no north-star ceiling ratio (like config 12)
            13: ("parquet-dict-scan",
                 lambda: bench_dict_scan(engine, nbytes), "GiB/s", False),
            # moment-streaming rate (2R+2W of the payload per step);
            # compute+write mixed, so no read-ceiling ratio
            14: ("offloaded-optimizer-step",
                 lambda: bench_opt_offload(engine), "GiB/s", False),
            15: ("parquet-topk-scan",
                 lambda: bench_topk(engine, nbytes), "GiB/s", True),
            # metadata path, not payload: members/s of the shard-index
            # header walk (native C vs tarfile in the tag) — the
            # first-epoch cost of a many-shard WebDataset dataset
            16: ("tar-index-rate",
                 lambda: bench_tar_index(engine, nbytes), "Mmembers/s",
                 False),
            # compute row paired with its own same-run synthetic
            # baseline (the ratio in the tag is the claim) — no
            # read-ceiling ratio applies
            17: ("fed-train-mfu",
                 lambda: bench_fed_train(engine), "TFLOP/s", False),
            # activation round-trip rate; priced vs remat-full (both
            # recompute — the delta IS the NVMe leg), link-normalized
            # like config 14, so no read-ceiling ratio
            18: ("offloaded-activations-step",
                 lambda: bench_act_offload(engine), "GiB/s", False),
            # serving with the NVMe KV prefix store: aggregate tok/s
            # under shared-prefix traffic, paired with its own same-run
            # store-off baseline (the TTFT/ratio in the tag is the
            # claim) — no read-ceiling ratio, like configs 6/11
            19: ("kv-serving-prefix",
                 lambda: bench_kvserve(engine), "tok/s", False),
            # overlapped streaming through the double-buffered host→HBM
            # stage, paired with its own same-run serialized + SQPOLL-off
            # arms (the speedup/reduction in the tag is the claim) — the
            # hop is pad-emulated on the CPU fallback, so no read-ceiling
            # ratio applies
            20: ("overlap-stream",
                 lambda: bench_overlap(nbytes), "GiB/s", False),
            # read-once/ICI-scatter restore: aggregate GiB/s with each
            # host reading 1/N off flash, paired with its own same-run
            # read-all arm (the N·T→T flash reduction in the tag is the
            # claim) — emulated mesh on the CPU fallback, so no
            # read-ceiling ratio applies
            21: ("scatter-restore",
                 lambda: bench_scatter(nbytes), "GiB/s", False),
            # multi-tenant isolation storm: victim-p99 ratio tier-off /
            # tier-on under the same aggressor, alternating trials with
            # medians — paired with its own same-run no-aggressor and
            # tier-off arms (the containment in the tag is the claim),
            # so no read-ceiling ratio applies
            22: ("tenant-isolation-storm",
                 lambda: bench_tenant_storm(nbytes), "x", False),
            # partition-parallel pushdown scan: effective table GiB/s
            # with zone-map skips, paired with its own same-run serial
            # arm (the speedups in the tag are the claim; the headline
            # legitimately exceeds the link because skipped bytes never
            # cross it) — so no read-ceiling ratio applies
            23: ("sql-parallel-pushdown",
                 lambda: bench_sql_parallel(engine, nbytes), "GiB/s",
                 False),
            # elastic cold-start: TTFT-from-boot speedup of
            # serve-while-restoring over restore-then-serve, paired
            # with its own same-run off arm and the time-to-p99-steady
            # + token-identity verdict in the tag (the claim is boot
            # elasticity, pad-emulated service time on a page-cached
            # dev box) — so no read-ceiling ratio applies
            24: ("cold-start-restore",
                 lambda: bench_coldstart_suite(nbytes), "x", False),
            # drain & warm handoff: replacement TTFT-from-boot speedup
            # of a bundle-fed boot over an abrupt-kill cold boot, with
            # the zero-drop session ledger in the tag — same pairing
            # rationale as config 24
            25: ("drain-handoff",
                 lambda: bench_handoff_suite(nbytes), "x", False),
        }
        # only configs whose _steady passes move payload ACROSS the
        # link get per-pass pairing: config 8's passes are pure engine
        # reads (raw-bound, and raw does not flap) and config 1 has no
        # pass loop — pairing either with link bursts would ratio the
        # wrong medium and waste window seconds on compute rows
        link_paired = {2, 3, 4, 5, 15}
        try:
            for c in configs:
                label, fn, unit, io_row = names[c]
                _WATCHDOG.phase(f"config{c}:{label}")
                _PASS_LINK["probe"] = link_probe if c in link_paired else None
                _PASS_LINK["last"] = None       # no stale cross-config pairs
                val, extra = fn()
                pairs = _PASS_LINK["last"] if (io_row and device_ok) else None
                pass_ratios = [r / (0.9 * min(raw, l)) for r, l in pairs or []
                               if r > 0 and l > 0] if raw > 0 else []
                tag = f"dev={dev_tag}"
                if isinstance(extra, str):
                    tag += f", {extra}"
                if pass_ratios:
                    tag += (", per-pass rate@link=" + " ".join(
                        f"{r:.3f}@{l:.2f}" for r, l in pairs))
                results.append({
                    "metric": f"config{c}:{label} ({tag})",
                    # 4 significant figures, not 3 decimals: a tiny-compute
                    # CI run on a loaded box can dip below 0.0005 TFLOP/s
                    # and 3-decimal rounding would floor it to a 0.0 row
                    "value": float(f"{val:.4g}"),
                    "unit": unit,
                    # machine-readable platform tag: BENCH_r* trajectories
                    # mix tunnel-up TPU rows with CPU-fallback rows, and
                    # only this field makes them comparable after the fact
                    "platform": dev_tag,
                    # Ratios against a CPU-derived ceiling are not the north
                    # star — never emit a number a reader could mistake for
                    # "target met" from a CPU-fallback run.  On a live
                    # device, prefer the median of per-pass ratios against
                    # interleaved link ceilings (module header ¶3) over the
                    # stale step-start pairing.
                    "vs_baseline": (
                        round(statistics.median(pass_ratios), 3)
                        if pass_ratios else
                        round(val / ceiling, 3)
                        if io_row and device_ok else None),
                })
                if emit is not None:
                    emit(results[-1])
                ratio = results[-1]["vs_baseline"]
                _log(f"suite: config {c} {label}: {val:.3f} {unit} "
                     + (f"({ratio:.2f}x of target)" if ratio is not None
                        else f"(vs_baseline=null: "
                             f"{'no target' if not io_row else 'cpu fallback'})"))
        finally:
            # no stale device-bound probe may survive an
            # aborted run for later in-process _steady callers
            _PASS_LINK["probe"] = None

        # every result row is out the door: from here on a hang (engine
        # close, JAX runtime teardown over a dead tunnel) must cost at
        # most the grace period, and exits 0 — the evidence landed.
        # Gated on the budget: a direct run() caller (REPL, test) that
        # never asked for a watchdog must not get os._exit'd under it.
        if budget_s > 0:
            _WATCHDOG.teardown()
        engine.sync_stats()
    _log(f"suite: stats bounce={stats.bounce_bytes} "
         f"direct={stats.bytes_direct} fallback={stats.bytes_fallback}")
    return results


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, action="append",
                    choices=range(1, 25))
    ap.add_argument("--all", action="store_true")
    args = ap.parse_args()
    configs = sorted(set(args.config or [])) if args.config else []
    if args.all or not configs:
        configs = list(range(1, 26))
    run(configs, emit=lambda row: print(json.dumps(row), flush=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
