#!/usr/bin/env python
"""Per-config benchmark suite: one JSON line per BASELINE.json config.

`bench.py` is the driver-facing headline (sustained NVMe→HBM streaming);
this suite covers the full config list so every capability row has a
number:

  1 raw     — raw sequential engine read, payload discarded (ssd2gpu_test
              analogue, SURVEY.md §3.4)
  2 arrow   — Arrow column file → single-chip device columns
  3 loader  — WebDataset shards → sharded dataloader → device batches
  4 weights — safetensors shards → lazy sharded HBM param load
  5 sql     — Parquet row-group scan → on-device GROUP BY aggregate

Usage: python bench_suite.py [--config N ... | --all] [--json-only]

Each line: {"metric", "value" (GiB/s payload→device), "unit",
"vs_baseline" (value / 0.9·min(raw SSD, host→device link) — the
BASELINE.json north star; ≥1.0 means target met)}.

Env: STROM_SUITE_BYTES (per-config payload, default 256 MiB),
STROM_BENCH_DIR (scratch dir, default repo root).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench  # noqa: E402  (shared helpers: probe_device, make_file, ...)

_log = bench._log


def _scratch_dir() -> str:
    d = os.environ.get("STROM_BENCH_DIR",
                       os.path.dirname(os.path.abspath(__file__)))
    sub = os.path.join(d, ".bench_suite")
    os.makedirs(sub, exist_ok=True)
    return sub


def _suite_bytes() -> int:
    return int(os.environ.get("STROM_SUITE_BYTES", 256 << 20))


def _needs_regen(tag: str, nbytes: int) -> bool:
    """Size-aware scratch cache: True if data tagged `tag` must be
    (re)generated for this nbytes.  The .meta sentinel records the size a
    previous run FINISHED generating (written by _mark_generated after
    success), so changing STROM_SUITE_BYTES — or an interrupted
    generation — regenerates instead of silently benchmarking stale or
    truncated data."""
    meta = os.path.join(_scratch_dir(), f".{tag}.meta")
    try:
        return int(open(meta).read()) != nbytes
    except (OSError, ValueError):
        return True


def _mark_generated(tag: str, nbytes: int) -> None:
    with open(os.path.join(_scratch_dir(), f".{tag}.meta"), "w") as f:
        f.write(str(nbytes))


# --------------------------- data generators ---------------------------

def make_arrow_file(path: str, nbytes: int) -> int:
    """Multi-batch Arrow IPC file of float32/int32 columns; returns size."""
    import numpy as np
    import pyarrow as pa
    if not _needs_regen("arrow", nbytes) and os.path.exists(path):
        return os.path.getsize(path)
    rows_total = max(1024, nbytes // 12)     # 3 cols × 4 bytes
    per_batch = max(1024, rows_total // 16)
    rng = np.random.default_rng(0)
    schema = pa.schema([("a", pa.float32()), ("b", pa.float32()),
                        ("k", pa.int32())])
    with pa.OSFile(path, "wb") as f, pa.ipc.new_file(f, schema) as w:
        left = rows_total
        while left > 0:
            n = min(per_batch, left)
            w.write_batch(pa.record_batch(
                [pa.array(rng.standard_normal(n, dtype=np.float32)),
                 pa.array(rng.standard_normal(n, dtype=np.float32)),
                 pa.array(rng.integers(0, 64, n, dtype=np.int32))],
                schema=schema))
            left -= n
    _mark_generated("arrow", nbytes)
    return os.path.getsize(path)


def make_wds_shards(dirpath: str, nbytes: int, n_shards: int = 4,
                    item_bytes: int = 1 << 20) -> list:
    """Tar shards of fixed-size .bin samples; returns shard paths."""
    import io as _io
    import tarfile
    import numpy as np
    os.makedirs(dirpath, exist_ok=True)
    per_shard = max(2, nbytes // n_shards // item_bytes)
    rng = np.random.default_rng(0)
    regen = _needs_regen("wds", nbytes)
    paths = []
    for s in range(n_shards):
        p = os.path.join(dirpath, f"shard-{s:04d}.tar")
        paths.append(p)
        if os.path.exists(p) and not regen:
            continue
        with tarfile.open(p, "w") as tf:
            for i in range(per_shard):
                payload = rng.integers(0, 256, item_bytes,
                                       dtype=np.uint8).tobytes()
                ti = tarfile.TarInfo(f"{s:04d}{i:05d}.bin")
                ti.size = item_bytes
                tf.addfile(ti, _io.BytesIO(payload))
    _mark_generated("wds", nbytes)
    return paths


def make_safetensors_shards(dirpath: str, nbytes: int,
                            n_shards: int = 2) -> list:
    import numpy as np
    from nvme_strom_tpu.formats import write_safetensors
    os.makedirs(dirpath, exist_ok=True)
    per_shard = nbytes // n_shards
    n_tensors = 4
    rows = max(64, per_shard // n_tensors // (1024 * 4))
    rng = np.random.default_rng(0)
    regen = _needs_regen("st", nbytes)
    paths = []
    for s in range(n_shards):
        p = os.path.join(dirpath,
                         f"model-{s + 1:05d}-of-{n_shards:05d}.safetensors")
        paths.append(p)
        if os.path.exists(p) and not regen:
            continue
        write_safetensors(p, {
            f"w{s}_{i}": rng.standard_normal(
                (rows, 1024), dtype=np.float32)
            for i in range(n_tensors)})
    _mark_generated("st", nbytes)
    return paths


def make_parquet_file(path: str, nbytes: int, num_groups: int = 64) -> int:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    if not _needs_regen("parquet", nbytes) and os.path.exists(path):
        return os.path.getsize(path)
    rows = max(4096, nbytes // 8)            # int32 key + float32 value
    rng = np.random.default_rng(0)
    tbl = pa.table({
        "k": pa.array(rng.integers(0, num_groups, rows, dtype=np.int32)),
        "v": pa.array(rng.standard_normal(rows, dtype=np.float32))})
    pq.write_table(tbl, path, row_group_size=max(4096, rows // 16),
                   compression="none")
    _mark_generated("parquet", nbytes)
    return os.path.getsize(path)


# ------------------------------ benches --------------------------------

def bench_arrow(engine, nbytes: int, device=None) -> tuple[float, int]:
    path = os.path.join(_scratch_dir(), "cols.arrow")
    size = make_arrow_file(path, nbytes)
    from nvme_strom_tpu.formats.arrow import ArrowFileReader
    reader = ArrowFileReader(path)
    best, payload = 0.0, 0
    for _ in range(2):         # run 1 warms jit/IPC caches
        t0 = time.monotonic()
        cols = reader.read_columns_to_device(engine, device=device)
        for v in cols.values():
            v.block_until_ready()
        dt = time.monotonic() - t0
        payload = sum(int(v.nbytes) for v in cols.values())
        del cols
        best = max(best, payload / (1 << 30) / dt)
    return best, size


def bench_loader(engine, nbytes: int, batch: int = 8) -> tuple[float, int]:
    import jax
    import numpy as np
    from jax.sharding import Mesh
    from nvme_strom_tpu.data.loader import ShardedLoader
    paths = make_wds_shards(os.path.join(_scratch_dir(), "wds"), nbytes)
    mesh = Mesh(np.array(jax.local_devices()[:1]).reshape(1), ("dp",))
    best, n = 0.0, 0
    with ShardedLoader(paths, mesh, global_batch=batch, fmt="wds",
                       engine=engine) as loader:
        for _ in range(2):     # epoch 1 warms jit/placement caches
            n = 0
            t0 = time.monotonic()
            for arr in loader:
                arr.block_until_ready()
                n += int(arr.nbytes)
            dt = time.monotonic() - t0
            best = max(best, n / (1 << 30) / dt)
    return best, n


def bench_weights(engine, nbytes: int, device=None) -> tuple[float, int]:
    import jax
    from jax.sharding import SingleDeviceSharding
    from nvme_strom_tpu.parallel.weights import LazyCheckpoint
    paths = make_safetensors_shards(
        os.path.join(_scratch_dir(), "st"), nbytes)
    ckpt = LazyCheckpoint(paths)
    dev = device or jax.local_devices()[0]
    sh = SingleDeviceSharding(dev)
    best, payload = 0.0, 0
    for _ in range(2):         # run 1 warms jit/placement caches
        t0 = time.monotonic()
        params = ckpt.load_sharded(lambda name, shape: sh, engine=engine)
        for v in params.values():
            v.block_until_ready()
        dt = time.monotonic() - t0
        payload = sum(int(v.nbytes) for v in params.values())
        del params
        best = max(best, payload / (1 << 30) / dt)
    return best, payload


def bench_sql(engine, nbytes: int, num_groups: int = 64,
              device=None) -> tuple[float, int]:
    from nvme_strom_tpu.sql.parquet import ParquetScanner
    from nvme_strom_tpu.sql.groupby import sql_groupby
    path = os.path.join(_scratch_dir(), "table.parquet")
    size = make_parquet_file(path, nbytes, num_groups)
    scanner = ParquetScanner(path, engine)
    rows = scanner.num_rows
    best = 0.0
    for _ in range(2):         # run 1 warms the groupby jit
        t0 = time.monotonic()
        out = sql_groupby(scanner, "k", "v", num_groups,
                          aggs=("count", "sum", "mean"), device=device)
        for v in out.values():
            v.block_until_ready()
        dt = time.monotonic() - t0
        best = max(best, size / (1 << 30) / dt)
        _log(f"suite: sql scanned {rows} rows ({size >> 20} MiB) "
             f"in {dt:.3f}s = {rows / dt / 1e6:.1f} Mrows/s")
    return best, rows


# ------------------------------- main ----------------------------------

def run(configs: list[int]) -> list[dict]:
    from nvme_strom_tpu.io import StromEngine
    from nvme_strom_tpu.utils.config import EngineConfig
    from nvme_strom_tpu.utils.stats import StromStats

    nbytes = _suite_bytes()
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        device_ok = False      # explicitly pinned to CPU: skip the probe
    else:
        device_ok = bench.probe_device()
    if not device_ok:
        bench.force_cpu()
    dev_tag = "tpu" if device_ok else "cpu-fallback"

    raw_path = os.path.join(_scratch_dir(), "raw.bin")
    bench.make_file(raw_path, nbytes)
    stats = StromStats()
    results = []
    with StromEngine(EngineConfig(), stats=stats) as engine:
        _log(f"suite: backend={engine.backend} bytes/config={nbytes >> 20}"
             f"MiB dev={dev_tag}")
        raw = bench.bench_raw(engine, raw_path)
        link = bench.bench_link()
        ceiling = 0.9 * (min(raw, link) if raw > 0 and link > 0
                         else max(raw, link, 1.0))
        _log(f"suite: raw={raw:.3f} GiB/s link={link:.3f} GiB/s "
             f"target=0.9·min={ceiling:.3f} GiB/s")

        names = {
            1: ("raw-sequential-read", lambda: (raw, nbytes)),
            2: ("arrow-to-device", lambda: bench_arrow(engine, nbytes)),
            3: ("wds-sharded-loader", lambda: bench_loader(engine, nbytes)),
            4: ("safetensors-lazy-load",
                lambda: bench_weights(engine, nbytes)),
            5: ("parquet-groupby-scan", lambda: bench_sql(engine, nbytes)),
        }
        for c in configs:
            label, fn = names[c]
            val, extra = fn()
            results.append({
                "metric": f"config{c}:{label} (dev={dev_tag})",
                "value": round(val, 3),
                "unit": "GiB/s",
                # Ratios against a CPU-derived ceiling are not the north
                # star — never emit a number a reader could mistake for
                # "target met" from a CPU-fallback run.
                "vs_baseline": (round(val / ceiling, 3)
                                if device_ok else None),
            })
            ratio = results[-1]["vs_baseline"]
            _log(f"suite: config {c} {label}: {val:.3f} GiB/s "
                 + (f"({ratio:.2f}x of target)" if ratio is not None
                    else "(vs_baseline=null: cpu fallback)"))
        engine.sync_stats()
    _log(f"suite: stats bounce={stats.bounce_bytes} "
         f"direct={stats.bytes_direct} fallback={stats.bytes_fallback}")
    return results


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, action="append",
                    choices=range(1, 6))
    ap.add_argument("--all", action="store_true")
    args = ap.parse_args()
    configs = sorted(set(args.config or [])) if args.config else []
    if args.all or not configs:
        configs = [1, 2, 3, 4, 5]
    for line in run(configs):
        print(json.dumps(line), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
