#!/usr/bin/env python
"""End-to-end example: train the flagship LM with every framework layer.

This is the "switching user" walkthrough — the full consumer path the
reference serves for PG-Strom (SURVEY.md §3.5), assembled from this
framework's pieces:

  strom-io engine ── WebDataset shards ──► ShardedLoader ──► device batches
        │                                                      │
        ├─ safetensors shards ──► LazyCheckpoint ──► sharded params
        │                                                      │
        │                     jit(make_train_step) over a dp×tp Mesh
        │                                                      │
        └──◄── CheckpointManager (direct writes) ◄── step state ┘

Run on any backend (CPU works: JAX_PLATFORMS=cpu python examples/train_lm.py
--steps 5 --tiny).  Every byte of input and weights moves through the
engine; stats print at the end (bounce_bytes == 0 on the direct path to an
accelerator).
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_lr_schedule(args):
    """LR as a float (pure constant) or an optax schedule.

    Warmup is linear 0→lr over --warmup-steps; after that either flat
    (``constant``) or cosine-decayed to 10% of peak over the remaining
    --steps (``cosine``).  Returned as a plain float when neither knob
    is set so the offloaded-optimizer path (which takes float-or-
    callable) keeps its simplest form.  On resume the schedule position
    comes from the optimizer's own step count (optax count / Offloaded-
    Adam .step), not wall progress, so a resumed run continues the
    decay where it left off.
    """
    import optax
    if args.lr_schedule == "constant" and args.warmup_steps <= 0:
        return args.lr
    decay_steps = max(args.steps, args.warmup_steps + 1)
    if args.lr_schedule == "cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=args.lr,
            warmup_steps=args.warmup_steps,
            decay_steps=decay_steps, end_value=args.lr * 0.1)
    return optax.join_schedules(
        [optax.linear_schedule(0.0, args.lr, args.warmup_steps),
         optax.constant_schedule(args.lr)],
        boundaries=[args.warmup_steps])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mix", default=None, metavar="DIR:W,DIR:W",
                    help="train on a weighted MIXTURE of shard dirs "
                         "(seeded per-step source draws, identical on "
                         "every host) instead of one --data-dir")
    ap.add_argument("--data-dir", default=None,
                    help="dir of WebDataset .tar shards of token arrays "
                         "(int32, seq_len per sample); synthesized if "
                         "omitted")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--init-weights", default=None,
                    help="glob of safetensors shards to warm-start from "
                         "(lazy NVMe->HBM load)")
    ap.add_argument("--from-hf", default=None, metavar="HF_DIR",
                    help="warm-start from a HuggingFace Llama checkpoint "
                         "dir: converted once (tools/convert_llama) into "
                         "--ckpt-dir/hf_converted, model config taken "
                         "from its config.json")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--seq-len", type=int, default=2048,
                    help="training sequence length (--from-hf caps the "
                         "HF max_position_embeddings to this)")
    ap.add_argument("--global-batch", type=int, default=8)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--lr-schedule", choices=("constant", "cosine"),
                    default="constant",
                    help="learning-rate shape after warmup: constant, or "
                         "cosine decay to 10%% of --lr over --steps")
    ap.add_argument("--warmup-steps", type=int, default=0,
                    help="linear LR warmup from 0 to --lr over N steps")
    ap.add_argument("--grad-clip", type=float, default=0.0,
                    metavar="NORM",
                    help="clip gradients to this global L2 norm before "
                         "the optimizer update (0 = off)")
    ap.add_argument("--xent-chunks", type=int, default=0,
                    help="cross-entropy over N sequence slices so the "
                         "(b, s, vocab) logits never materialize — the "
                         "memory lever for 100k+ vocabs (0 = off)")
    ap.add_argument("--accum-steps", type=int, default=1,
                    help="gradient-accumulation microbatches per step "
                         "(activation memory of global-batch/N)")
    ap.add_argument("--watchdog", type=float, default=0.0,
                    metavar="SECONDS",
                    help="per-step deadline: a hung step dumps all "
                         "thread stacks + engine counters to stderr "
                         "(0 = off)")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny config (CI/demo) instead of the flagship")
    ap.add_argument("--save-every", type=int, default=10)
    ap.add_argument("--lora", type=int, default=0, metavar="RANK",
                    help="train rank-RANK LoRA adapters instead of full "
                         "weights (base stays frozen; checkpoints hold "
                         "only adapters + their optimizer state)")
    ap.add_argument("--lora-alpha", type=float, default=None,
                    help="LoRA scale numerator (default: RANK)")
    ap.add_argument("--remat", default="none",
                    choices=("none", "dots", "full", "nvme"),
                    help="rematerialization policy: 'dots' saves matmul "
                         "outputs and recomputes elementwise ops (most "
                         "of full remat's memory win at a fraction of "
                         "its recompute); 'full' recomputes whole "
                         "layers; 'nvme' additionally moves the "
                         "layer-boundary activations to NVMe "
                         "(--offload-acts DIR) — O(1)-layers HBM "
                         "activations")
    ap.add_argument("--offload-acts", default=None, metavar="DIR",
                    help="backing dir for --remat nvme "
                         "(parallel/act_offload ActivationStore)")
    ap.add_argument("--flash", action="store_true",
                    help="use the Pallas fused flash-attention kernel "
                         "(O(seq) memory) instead of XLA dense "
                         "attention")
    ap.add_argument("--offload-opt", default=None, metavar="DIR",
                    help="keep Adam moments on NVMe under DIR instead of "
                         "HBM (parallel/opt_offload): HBM holds one "
                         "group of moments at a time, so optimizer "
                         "state no longer bounds trainable model size")
    args = ap.parse_args(argv)
    if args.offload_opt and args.lora:
        ap.error("--offload-opt is for full fine-tunes; LoRA optimizer "
                 "state is adapter-sized and lives happily in HBM")
    if (args.remat == "nvme") != bool(args.offload_acts):
        ap.error("--remat nvme and --offload-acts DIR go together")
    if args.remat == "nvme" and args.lora:
        ap.error("--remat nvme is for full fine-tunes; LoRA's frozen "
                 "base already skips most activation memory")

    import jax
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # the tunneled-TPU plugin force-selects its platform regardless of
        # JAX_PLATFORMS; re-pin before any backend is instantiated
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import optax
    from nvme_strom_tpu.checkpoint.manager import CheckpointManager
    from nvme_strom_tpu.data.loader import ShardedLoader
    from nvme_strom_tpu.io import StromEngine
    from nvme_strom_tpu.models.transformer import (
        flagship_config, init_params, make_train_step, tiny_config)
    from nvme_strom_tpu.parallel.mesh import make_mesh
    from nvme_strom_tpu.parallel.shardings import (
        batch_shardings, param_shardings, replicate_scalars)
    from nvme_strom_tpu.parallel.weights import LazyCheckpoint

    if args.from_hf:
        # Convert ONCE (skipped when a prior run already converted into
        # this ckpt-dir) and adopt the HF architecture as the config.
        if args.init_weights or args.tiny:
            ap.error("--from-hf is mutually exclusive with "
                     "--init-weights/--tiny (it supplies both weights "
                     "and model config)")
        if not args.ckpt_dir:
            ap.error("--from-hf needs --ckpt-dir: the converted shards "
                     "are a durable multi-GB artifact")
        import json as _json
        from nvme_strom_tpu.tools.convert_llama import convert
        from nvme_strom_tpu.models.transformer import TransformerConfig
        import hashlib
        conv_dir = os.path.join(args.ckpt_dir, "hf_converted")
        marker = os.path.join(conv_dir, "strom_config.json")
        src_marker = os.path.join(conv_dir, "source.json")
        reusable = False
        if os.path.exists(marker) and os.path.exists(src_marker):
            with open(src_marker) as f:
                src = _json.load(f)
            with open(os.path.join(args.from_hf, "config.json"),
                      "rb") as f:
                sha = hashlib.sha256(f.read()).hexdigest()
            reusable = src.get("config_sha256") == sha
            if not reusable:
                ap.error(
                    f"{conv_dir} holds a conversion of a DIFFERENT "
                    f"checkpoint ({src.get('hf_dir')}); refusing to mix "
                    "— use a fresh --ckpt-dir or delete hf_converted/")
        if reusable:
            print(f"from-hf: reusing converted shards under {conv_dir}")
        else:
            summary = convert(args.from_hf, conv_dir)
            print(f"from-hf: converted {summary['tensors']} tensors "
                  f"into {summary['shards']} shard(s) under {conv_dir}")
        with open(marker) as f:
            cfg = TransformerConfig(**_json.load(f))
        if cfg.max_seq > args.seq_len:
            # HF configs carry max_position_embeddings up to 128k; the
            # training seq length is a run choice, not the model ceiling
            import dataclasses
            cfg = dataclasses.replace(cfg, max_seq=args.seq_len)
        args.init_weights = conv_dir  # dir form: every shard inside
    else:
        cfg = tiny_config() if args.tiny else flagship_config()
    if args.remat != "none":
        import dataclasses
        cfg = dataclasses.replace(cfg, remat_policy=args.remat)
    if args.xent_chunks > 1:
        import dataclasses
        cfg = dataclasses.replace(cfg, xent_chunks=args.xent_chunks)
    attn_fn = None
    if args.flash:
        from nvme_strom_tpu.ops.flash_attention import make_flash_attn
        attn_fn = make_flash_attn()
    mesh = make_mesh({"dp": -1, "tp": args.tp})
    print(f"mesh: {dict(mesh.shape)} devices={len(jax.devices())} "
          f"model: d={cfg.d_model} L={cfg.n_layers} vocab={cfg.vocab}")

    engine = StromEngine()
    tmp = None
    mix_specs = None           # [(shard list, weight)] when --mix
    if args.mix:
        if args.data_dir:
            ap.error("--mix and --data-dir conflict: list every corpus "
                     "in --mix (DIR:W,DIR:W)")
        mix_specs = []
        for part in args.mix.split(","):
            d, _, w = part.rpartition(":")
            try:
                weight = float(w)
            except ValueError:
                weight = -1.0
            if not d or weight <= 0:
                ap.error(f"--mix entry {part!r}: want DIR:WEIGHT "
                         "with a positive weight")
            entry = sorted(os.path.join(d, f) for f in os.listdir(d)
                           if f.endswith(".tar"))
            if not entry:
                ap.error(f"--mix: no .tar shards under {d}")
            mix_specs.append((entry, weight))
        data_dir = None
    else:
        data_dir = args.data_dir
        if data_dir is None:
            tmp = tempfile.TemporaryDirectory(prefix="strom_lm_")
            data_dir = tmp.name
            _synthesize_shards(data_dir, cfg, n_shards=4,
                               per_shard=8 * args.global_batch)
            print(f"data: synthesized 4 shards under {data_dir}")
        shards = sorted(
            os.path.join(data_dir, f) for f in os.listdir(data_dir)
            if f.endswith(".tar"))
        if not shards:
            ap.error(f"no .tar shards found under {data_dir}")

    ckpt_dir = args.ckpt_dir or os.path.join(
        tmp.name if tmp else ".", "ckpt")
    mgr = CheckpointManager(ckpt_dir, engine=engine)
    start = mgr.latest_step()

    p_sh = param_shardings(cfg, mesh)
    # Full fine-tune resumes overwrite params from the checkpoint, so
    # the warm start only matters on a fresh run — but a LoRA resume
    # restores ONLY adapters, so its frozen base must reload every time.
    if args.init_weights and (start is None or args.lora):
        params = LazyCheckpoint(args.init_weights).load_sharded(
            p_sh, engine=engine)
        print(f"params: lazy-loaded {len(params)} tensors from "
              f"{args.init_weights}")
    else:
        # fixed seed: the re-initialized base is identical across runs,
        # so a LoRA resume without a warm start is still coherent
        params = init_params(jax.random.key(0), cfg)
        params = {k: jax.device_put(v, p_sh[k]) for k, v in params.items()}

    lr_sched = _make_lr_schedule(args)
    optimizer = optax.adamw(lr_sched)
    if args.grad_clip > 0:
        optimizer = optax.chain(
            optax.clip_by_global_norm(args.grad_clip), optimizer)
    b_sh = batch_shardings(mesh)
    act_store = None
    if args.offload_acts:
        if len(jax.devices()) > 1:
            raise SystemExit(
                "--remat nvme is single-device: the store's ordered "
                "io_callbacks cannot lower inside a multi-device "
                "computation — use --remat full/dots on meshes")
        from nvme_strom_tpu.parallel.act_offload import ActivationStore
        act_store = ActivationStore(
            os.path.join(args.offload_acts, "acts.bin"),
            cfg.n_layers, engine=engine)
        print(f"offload-acts: {cfg.n_layers} layer slots under "
              f"{args.offload_acts} (O(1)-layers HBM activations)")
    if args.lora:
        # frozen streamed base + tiny trainable adapters: the
        # checkpoint/optimizer state shrinks to adapter size
        from nvme_strom_tpu.models.lora import (
            count_params, lora_init, make_lora_train_step)
        from jax.sharding import NamedSharding, PartitionSpec
        alpha = (args.lora_alpha if args.lora_alpha is not None
                 else float(args.lora))
        base = params
        rep = NamedSharding(mesh, PartitionSpec())   # adapters are tiny
        trainable = jax.device_put(
            lora_init(jax.random.key(1), base, args.lora), rep)
        opt_state = jax.device_put(optimizer.init(trainable), rep)
        _lora_step = jax.jit(
            make_lora_train_step(cfg, optimizer, alpha=alpha,
                                 accum_steps=args.accum_steps),
            donate_argnums=(0, 1))

        def step_fn(tr, ost, tokens):
            return _lora_step(tr, ost, base, tokens)
        print(f"lora: rank {args.lora} alpha {alpha:g} — "
              f"{count_params(trainable)} trainable of "
              f"{count_params(base)} base params")
    elif args.offload_opt:
        # grads on device, moments on NVMe: the jitted step stops at the
        # gradient; OffloadedAdam streams each moment group through the
        # engine around a per-group update
        from nvme_strom_tpu.models.transformer import (
            accumulate_grads, loss_fn)
        from nvme_strom_tpu.parallel.opt_offload import OffloadedAdam

        trainable = params
        opt_state = ()          # NVMe-resident; manifest is the state
        offl = OffloadedAdam(args.offload_opt, params, lr=lr_sched,
                             weight_decay=1e-4,  # = optax.adamw default
                             engine=engine)

        def gstep(p, tokens):
            loss, grads = accumulate_grads(
                lambda mb: jax.value_and_grad(
                    lambda q: loss_fn(q, mb, cfg, attn_fn,
                                      act_store=act_store))(p),
                p, tokens, args.accum_steps)
            if args.grad_clip > 0:
                grads, _ = optax.clip_by_global_norm(
                    args.grad_clip).update(grads, optax.EmptyState())
            return loss, grads

        grad_fn = jax.jit(gstep, in_shardings=(p_sh, b_sh))

        def step_fn(tr, ost, tokens):
            loss, grads = grad_fn(tr, tokens)
            return offl.update(tr, grads), ost, loss

        print(f"offload-opt: {offl.moment_bytes() >> 20} MiB of moments "
              f"on NVMe, peak {offl.peak_group_bytes() >> 20} MiB in "
              f"HBM, {offl.num_groups()} groups, resumed at step "
              f"{offl.step}")
    else:
        trainable = params
        opt_state = replicate_scalars(optimizer.init(params), mesh)
        step_fn = jax.jit(make_train_step(cfg, optimizer,
                                          attn_fn=attn_fn,
                                          accum_steps=args.accum_steps,
                                          act_store=act_store),
                          in_shardings=(p_sh, None, b_sh),
                          out_shardings=(p_sh, None, None),
                          donate_argnums=(0, 1))

    if start is not None:
        trainable, opt_state = mgr.restore((trainable, opt_state))
        if args.lora:
            # restore commits to single-device placements; the adapters
            # must live replicated beside the tp-sharded base
            trainable = jax.device_put(trainable, rep)
            opt_state = jax.device_put(opt_state, rep)
        print(f"resumed from step {start}")
    start = (start or 0)
    if args.offload_opt and offl.step != start:
        # A crash between --save-every checkpoints leaves the moment
        # manifest ahead of the params checkpoint; pairing step-M params
        # with step-N moments (and t=N+1 bias correction) diverges
        # SILENTLY, so refuse instead.
        raise SystemExit(
            f"offload-opt: moment manifest is at step {offl.step} but "
            f"params resume at step {start} — Adam would run a "
            "divergent trajectory.  Restore the params checkpoint "
            f"matching step {offl.step}, or start a fresh moment dir "
            "(the moments update in place every step; only "
            "checkpoint-aligned pairs are coherent)")

    def decode(parts):
        (payload,) = parts.values()
        return np.frombuffer(payload, dtype=np.int32) % cfg.vocab

    def batches():
        if mix_specs is not None:
            from contextlib import ExitStack
            from nvme_strom_tpu.data import MixtureLoader
            with ExitStack() as stack:
                loaders = [
                    (stack.enter_context(
                        ShardedLoader(e, mesh, args.global_batch,
                                      fmt="wds", decode=decode,
                                      engine=engine)), w)
                    for e, w in mix_specs]
                mix = MixtureLoader(loaders, seed=0)
                for b, _src in mix:     # unbounded: sources restart
                    yield b
            return
        while True:
            n = 0
            with ShardedLoader(shards, mesh, args.global_batch, fmt="wds",
                               decode=decode, engine=engine) as loader:
                for b in loader:
                    n += 1
                    yield b
            if n == 0:
                raise RuntimeError(
                    f"shards under {data_dir} yield zero full batches of "
                    f"{args.global_batch}")

    from contextlib import nullcontext
    from nvme_strom_tpu.data.prefetch import prefetch_to_device
    from nvme_strom_tpu.utils.watchdog import StepWatchdog
    it = prefetch_to_device(batches(), size=2)
    wd = (StepWatchdog(args.watchdog, engine=engine)
          if args.watchdog > 0 else None)
    t0 = time.monotonic()
    loss = None
    for step in range(start, args.steps):
        # the armed region covers the HOST SYNC POINTS too
        # (block_until_ready/float(loss)/save) — async dispatch means a
        # wedged collective usually hangs there, not in step_fn
        with wd.step(f"step {step}") if wd else nullcontext():
            tokens = next(it)
            trainable, opt_state, loss = step_fn(trainable, opt_state,
                                                 tokens)
            if (step + 1) % args.save_every == 0 or step + 1 == args.steps:
                jax.block_until_ready(loss)
                if jax.process_count() == 1:
                    # snapshot now (donation-safe numpy copies), NVMe
                    # write overlaps the next steps; errors surface at
                    # the next save/restore/wait
                    mgr.save_async(step + 1, (trainable, opt_state))
                else:
                    mgr.save(step + 1, (trainable, opt_state))
                print(f"step {step + 1}: loss={float(loss):.4f} "
                      f"(checkpointed)")
            elif (step + 1) % 5 == 0:
                print(f"step {step + 1}: loss={float(loss):.4f}")
    jax.block_until_ready(loss)
    dt = time.monotonic() - t0
    print(f"{args.steps - start} steps in {dt:.2f}s "
          f"({(args.steps - start) / max(dt, 1e-9):.2f} steps/s)")

    if wd:
        wd.close()
    it.close()  # drain the loader's prefetch thread BEFORE engine teardown
    mgr.wait_pending()  # last async save durable (or raising) before exit
    engine.sync_stats()
    s = engine.stats
    print(f"engine stats: direct={s.bytes_direct} "
          f"fallback={s.bytes_fallback} bounce={s.bounce_bytes} "
          f"to_device={s.bytes_to_device}")
    engine.close_all()
    if tmp:
        tmp.cleanup()
    return 0


def _synthesize_shards(dirpath: str, cfg, n_shards: int,
                       per_shard: int) -> None:
    """Tar shards of int32 token arrays (one .bin per sample)."""
    import io
    import tarfile
    import numpy as np
    rng = np.random.default_rng(0)
    for s in range(n_shards):
        with tarfile.open(os.path.join(dirpath, f"lm-{s:04d}.tar"),
                          "w") as tf:
            for i in range(per_shard):
                toks = rng.integers(0, cfg.vocab, cfg.max_seq,
                                    dtype=np.int32).tobytes()
                ti = tarfile.TarInfo(f"{s:04d}{i:05d}.bin")
                ti.size = len(toks)
                tf.addfile(ti, io.BytesIO(toks))


if __name__ == "__main__":
    sys.exit(main())
