"""End-to-end config-3 consumer: fixedrec image shards → ViT training.

BASELINE.json's headline config is "ImageNet-1k WebDataset shards →
infeed dataloader"; this example runs that loop on the framework's
FASTEST input path: fixed-size records stream NVMe → staging → device
with zero Python-side copies (data/loader.py fixedrec path, VERDICT
round-1 #2), and ALL decoding happens on device inside the jitted train
step — each record is ``C*H*W image bytes ++ 4 label bytes``, unpacked
with an on-device slice + bitcast (the same decode-on-the-accelerator
move as sql/pq_direct.py).

    python examples/train_vit.py --steps 20 --global-batch 32 --tp 2

For real WebDataset `.tar` image shards use examples/train_lm.py's
loader pattern with ``fmt="wds"`` and a host-side decode (counted as
bounce); this example sticks to fixedrec because it demonstrates the
bounce-free path.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None,
                    help="dir of .sfr fixedrec shards (synthesized if "
                         "omitted)")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--global-batch", type=int, default=16)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--classes", type=int, default=100)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args(argv)

    import jax
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nvme_strom_tpu.data.loader import ShardedLoader
    from nvme_strom_tpu.io import StromEngine
    from nvme_strom_tpu.models.vit import (
        ViTConfig, init_vit_params, make_vit_train_step,
        vit_param_shardings)
    from nvme_strom_tpu.parallel.mesh import make_mesh
    from nvme_strom_tpu.parallel.shardings import (
        prune_spec, replicate_scalars)

    cfg = ViTConfig(image_size=args.image_size, patch_size=8,
                    d_model=192, n_layers=4, n_heads=4, d_ff=768,
                    n_classes=args.classes)
    img_bytes = cfg.channels * cfg.image_size ** 2
    rec_bytes = img_bytes + 4                      # ++ int32 label
    mesh = make_mesh({"dp": -1, "tp": args.tp})
    print(f"mesh: {dict(mesh.shape)} model: d={cfg.d_model} "
          f"L={cfg.n_layers} img={cfg.image_size} rec={rec_bytes}B")

    engine = StromEngine()
    tmp = None
    data_dir = args.data_dir
    if data_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="strom_vit_")
        data_dir = tmp.name
        _synthesize_shards(data_dir, rec_bytes, img_bytes, args.classes,
                           n_shards=4, per_shard=4 * args.global_batch)
        print(f"data: synthesized 4 shards under {data_dir}")
    shards = sorted(os.path.join(data_dir, f)
                    for f in os.listdir(data_dir) if f.endswith(".sfr"))
    if not shards:
        ap.error(f"no .sfr shards found under {data_dir}")

    params = init_vit_params(jax.random.key(0), cfg)
    p_sh = vit_param_shardings(cfg, mesh)
    params = {k: jax.device_put(v, p_sh[k]) for k, v in params.items()}
    optimizer = optax.adamw(args.lr)
    opt_state = replicate_scalars(optimizer.init(params), mesh)
    b_sh = NamedSharding(mesh, prune_spec(P("dp"), mesh))

    vit_step = make_vit_train_step(cfg, optimizer)

    def step_raw(params, opt_state, records):
        """records (B, rec_bytes) uint8 → on-device unpack + train step.
        The slice/bitcast/normalize all run inside the jit — no host
        byte is ever touched (the PG-Strom decode-on-device pattern)."""
        imgs = records[:, :img_bytes].reshape(
            -1, cfg.image_size, cfg.image_size, cfg.channels)
        imgs = imgs.astype(cfg.dtype) / 255.0
        # (B, 4) uint8 → (B,) int32: bitcast folds the trailing dim
        labels = jax.lax.bitcast_convert_type(
            records[:, img_bytes:], jnp.int32)
        labels = jnp.clip(labels, 0, cfg.n_classes - 1)
        return vit_step(params, opt_state, imgs, labels)

    step_fn = jax.jit(step_raw,
                      in_shardings=(p_sh, None, b_sh),
                      out_shardings=(p_sh, None, None),
                      donate_argnums=(0, 1))

    t0 = time.monotonic()
    loss = None
    it = 0
    while it < args.steps:
        n_epoch = 0
        with ShardedLoader(shards, mesh, args.global_batch,
                           fmt="fixedrec", engine=engine) as loader:
            for rec in loader:
                params, opt_state, loss = step_fn(params, opt_state, rec)
                it += 1
                n_epoch += 1
                if it % 5 == 0 or it == args.steps:
                    print(f"step {it}: loss={float(loss):.4f}")
                if it >= args.steps:
                    break
        if n_epoch == 0:
            raise RuntimeError(
                f"shards under {data_dir} yield zero full batches of "
                f"{args.global_batch}")
    jax.block_until_ready(loss)
    dt = time.monotonic() - t0
    print(f"{args.steps} steps in {dt:.2f}s "
          f"({args.steps * args.global_batch / dt:.1f} img/s)")

    engine.sync_stats()
    s = engine.stats
    print(f"engine stats: direct={s.bytes_direct} "
          f"fallback={s.bytes_fallback} bounce={s.bounce_bytes} "
          f"to_device={s.bytes_to_device}")
    engine.close_all()
    if tmp:
        tmp.cleanup()
    return 0


def _synthesize_shards(dirpath: str, rec_bytes: int, img_bytes: int,
                       n_classes: int, n_shards: int,
                       per_shard: int) -> None:
    import numpy as np
    from nvme_strom_tpu.formats.fixedrec import write_fixedrec
    rng = np.random.default_rng(0)
    for s in range(n_shards):
        rec = np.empty((per_shard, rec_bytes), np.uint8)
        rec[:, :img_bytes] = rng.integers(
            0, 256, size=(per_shard, img_bytes), dtype=np.uint8)
        labels = rng.integers(0, n_classes, size=per_shard,
                              dtype=np.int32)
        rec[:, img_bytes:] = labels[:, None].view(np.uint8).reshape(
            per_shard, 4)
        write_fixedrec(os.path.join(dirpath, f"shard-{s:04d}.sfr"), rec)


if __name__ == "__main__":
    sys.exit(main())
