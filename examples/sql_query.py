#!/usr/bin/env python
"""Direct-SQL demo CLI: PG-Strom-style scans on TPU, end to end.

    SELECT k, COUNT(v), SUM(v), MEAN(v) FROM t [WHERE lo<=w<=hi] GROUP BY k
    SELECT city, AGG(v)  FROM t GROUP BY city          (string keys)
    SELECT d.attr, SUM(f.v) FROM fact JOIN dim ... GROUP BY d.attr LIMIT n
    SELECT v, k FROM t ORDER BY v DESC LIMIT n      (stats-eliminated scan)

Points at an existing Parquet file (--table) or synthesizes one
(--rows).  Column payloads ride the O_DIRECT engine and decode ON
DEVICE (sql/pq_direct.py: PLAIN bitcast, dictionary gather with the
on-device bit-unpack, compressed chunks direct); the aggregate runs on
device; per-query engine counters print after each query — on an
accelerator the uncompressed scan shows bounce_bytes == 0.

    python examples/sql_query.py --rows 2000000
    python examples/sql_query.py --table t.parquet --key k --value v
    python examples/sql_query.py --rows 500000 --compression zstd
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _synthesize(path: str, rows: int, groups: int,
                compression: str) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(0)
    cities = np.array(["tokyo", "osaka", "kyoto", "nagoya", "sapporo",
                       "fukuoka", "sendai", "kobe"])
    tbl = pa.table({
        "k": pa.array(rng.integers(0, groups, rows, dtype=np.int32)),
        "v": pa.array(rng.standard_normal(rows, dtype=np.float32)),
        "w": pa.array(rng.integers(0, 10_000, rows, dtype=np.int32)),
        "city": pa.array(cities[rng.integers(0, len(cities), rows)]),
    })
    pq.write_table(tbl, path, row_group_size=max(4096, rows // 16),
                   compression=compression, use_dictionary=["city"])
    print(f"synthesized {rows} rows -> {path} "
          f"({os.path.getsize(path) >> 20} MiB, {compression})")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--table", default=None,
                    help="existing Parquet file (else synthesized)")
    ap.add_argument("--sql", default=None, metavar="QUERY",
                    help="run this SQL string (table name 't') instead "
                         "of the demo queries — the sql.parser front "
                         "end plans it onto the device executors")
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--groups", type=int, default=64)
    ap.add_argument("--compression", default="none",
                    choices=("none", "zstd", "snappy", "gzip"))
    ap.add_argument("--key", default="k")
    ap.add_argument("--value", default="v")
    ap.add_argument("--top", type=int, default=5,
                    help="LIMIT for the ORDER BY demo query")
    ap.add_argument("--where", nargs=3, metavar=("COL", "LO", "HI"),
                    default=None,
                    help="range predicate; row groups the footer stats "
                         "exclude never leave the SSD")
    args = ap.parse_args(argv)

    import jax
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from nvme_strom_tpu.io import StromEngine
    from nvme_strom_tpu.sql import (ParquetScanner, sql_groupby,
                                    sql_groupby_str, sql_topk,
                                    top_k_groups)

    path = args.table
    if path is None:
        import atexit
        import shutil
        tmp = tempfile.mkdtemp(prefix="strom_sql_")
        # one cleanup for every exit path — early returns, exceptions
        atexit.register(shutil.rmtree, tmp, ignore_errors=True)
        path = os.path.join(tmp, "t.parquet")
        _synthesize(path, args.rows, args.groups, args.compression)

    with StromEngine() as eng:
        sc = ParquetScanner(path, eng)
        print(f"table: {sc.num_rows} rows, "
              f"{sc.num_row_groups} row groups; direct eligibility: "
              f"{sc.direct_reasons([args.key, args.value])}")

        def counters(label: str, t0: float) -> None:
            eng.sync_stats()
            s = eng.stats.snapshot()
            print(f"  [{label}: {time.monotonic() - t0:.3f}s  "
                  f"direct={s['bytes_direct'] >> 20}MiB "
                  f"bounce={s['bounce_bytes'] >> 20}MiB]")

        if args.sql:
            from nvme_strom_tpu.sql import sql_query as run_sql
            t0 = time.monotonic()
            out = run_sql(args.sql, {"t": sc}, engine=eng)
            for name, col in out.items():
                if not hasattr(col, "__len__"):
                    print(f"  {name}: {col}")
                    continue
                def _fmt(x):
                    try:
                        return round(float(x), 4)
                    except (TypeError, ValueError):
                        return x
                head = [_fmt(x) for x in list(col[:8])]
                print(f"  {name}: {head}"
                      + (" ..." if len(col) > 8 else ""))
            counters("sql", t0)
            return 0

        where_ranges = []
        if args.where:
            col, lo, hi = args.where
            where_ranges = [(col, float(lo), float(hi))]

        t0 = time.monotonic()
        out = sql_groupby(sc, args.key, args.value, args.groups,
                          aggs=("count", "sum", "mean"),
                          where_ranges=where_ranges)
        head = {a: [round(float(x), 3) for x in list(out[a][:5])]
                for a in out}
        print(f"GROUP BY {args.key} (first 5 groups): {head}")
        counters("groupby", t0)

        t0 = time.monotonic()
        tk = sql_topk(sc, args.value, columns=[args.key], k=args.top,
                      where_ranges=where_ranges)
        print(f"ORDER BY {args.value} DESC LIMIT {args.top}: "
              f"{[round(float(x), 4) for x in tk[args.value]]} "
              f"(rows {list(tk['_row'])}, "
              f"{tk['_skipped_row_groups']} row groups eliminated)")
        counters("order by / limit", t0)

        if args.table is None:       # the synthesized string column
            t0 = time.monotonic()
            s_out = sql_groupby_str(sc, "city", args.value,
                                    aggs=("count", "mean"))
            top = top_k_groups(
                {k: v for k, v in s_out.items() if k != "labels"},
                "count", 3)
            print("GROUP BY city, top-3 by count:")
            for i in range(3):
                lab = s_out["labels"][int(top["group"][i])]
                lab = lab.decode() if isinstance(lab, bytes) else lab
                print(f"  {lab:<10} count={int(top['count'][i])} "
                      f"mean={float(top['mean'][i]):+.4f}")
            counters("string groupby", t0)

    return 0


if __name__ == "__main__":
    sys.exit(main())
