"""Generation example: NVMe weight shards → KV-cache decode.

Completes the inference story end to end: weights lazy-load through the
O_DIRECT engine (per-tensor ranged reads, parallel/weights.py), the
whole generation loop is one jitted ``lax.scan`` (models/decode.py), and
long prompts automatically use the Pallas decode-attention kernel
(measured ~1.7x over the XLA einsum at S≈1856 on a v5e,
ops/decode_attention.py).

    # from a converted checkpoint dir (tools/convert_llama or
    # parallel.weights.save_checkpoint)
    python examples/generate.py --weights conv/ --prompt 1,2,3 --new 32

    # straight from a HuggingFace Llama checkpoint dir
    python examples/generate.py --from-hf Meta-Llama-3.1-8B/ \
        --out-dir conv/ --prompt 1,2,3 --new 32

Token-id in, token-id out — tokenizers are out of scope for a storage
framework; feed ids from whatever tokenizer matches the checkpoint.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--weights", default=None,
                     help="converted checkpoint dir (must contain "
                          "strom_config.json)")
    src.add_argument("--from-hf", default=None, metavar="HF_DIR",
                     help="HF Llama checkpoint dir; converted into "
                          "--out-dir first (reused when already there)")
    ap.add_argument("--out-dir", default=None,
                    help="conversion output dir for --from-hf")
    ap.add_argument("--prompt", default="1,2,3,4",
                    help="comma-separated token ids")
    ap.add_argument("--new", type=int, default=32,
                    help="tokens to generate")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy")
    ap.add_argument("--eos-id", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 quantization after load "
                         "(halved weight streaming; models/quant.py)")
    ap.add_argument("--int4", action="store_true",
                    help="packed int4 (with --int8: the mixed recipe — "
                         "int8 lm_head, int4 everything else)")
    ap.add_argument("--offload", default=None, metavar="PAGEFILE",
                    help="decode with the SSD-backed KV cache spilling "
                         "pages to this path (greedy only; HBM holds a "
                         "bounded window, history streams from NVMe)")
    ap.add_argument("--offload-window", type=int, default=1024,
                    help="HBM window positions for --offload")
    ap.add_argument("--offload-quant", choices=["int8"], default=None,
                    help="quantize cold pages (halves the NVMe stream)")
    ap.add_argument("--offload-chunked-prefill", action="store_true",
                    help="prefill the prompt in page-sized chunks too "
                         "(bounded HBM for arbitrary prompt lengths)")
    args = ap.parse_args(argv)

    import jax
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        # the tunneled-TPU plugin force-selects its platform regardless
        # of JAX_PLATFORMS; re-pin via config before any backend is
        # instantiated (same quirk handling as train_lm.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from nvme_strom_tpu.io import StromEngine
    from nvme_strom_tpu.models.decode import generate
    from nvme_strom_tpu.models.transformer import TransformerConfig
    from nvme_strom_tpu.ops.decode_attention import make_decode_attn
    from nvme_strom_tpu.parallel.weights import LazyCheckpoint

    weights_dir = args.weights
    if args.from_hf:
        if not args.out_dir:
            ap.error("--from-hf needs --out-dir for the conversion")
        from nvme_strom_tpu.tools.convert_llama import convert
        if not os.path.exists(os.path.join(args.out_dir,
                                           "strom_config.json")):
            summary = convert(args.from_hf, args.out_dir)
            print(f"converted {summary['tensors']} tensors", flush=True)
        weights_dir = args.out_dir

    cfg_path = os.path.join(weights_dir, "strom_config.json")
    if not os.path.exists(cfg_path):
        ap.error(f"{cfg_path} not found — convert with "
                 "tools/convert_llama or pass a converted dir")
    with open(cfg_path) as f:
        cfg = TransformerConfig(**json.load(f))

    if args.new < 1:
        ap.error("--new must be >= 1")
    prompt_ids = [int(t) for t in args.prompt.split(",") if t.strip()]
    if not prompt_ids:
        ap.error("empty prompt")
    if max(prompt_ids) >= cfg.vocab or min(prompt_ids) < 0:
        ap.error(f"prompt ids must be in [0, {cfg.vocab})")
    total = len(prompt_ids) + args.new
    if total > cfg.max_seq:
        ap.error(f"prompt+new = {total} exceeds max_seq {cfg.max_seq}")

    engine = StromEngine()
    t0 = time.monotonic()
    params = LazyCheckpoint(weights_dir).load_sharded(
        lambda name, shape: jax.sharding.SingleDeviceSharding(
            jax.devices()[0]),
        engine=engine)
    print(f"weights: {len(params)} tensors in "
          f"{time.monotonic() - t0:.2f}s", flush=True)
    if args.int8:
        from nvme_strom_tpu.models.quant import (quantize_weights_int8,
                                                 quantized_nbytes)
        sfx = ("lm_head",) if args.int4 else None
        params = quantize_weights_int8(params, suffixes=sfx)
        q, fp = quantized_nbytes(params)
        what = "lm_head only (mixed recipe)" if args.int4 \
            else "matmul weights"
        print(f"int8: {what} {q >> 20} MiB "
              f"(vs {fp >> 20} MiB fp32)", flush=True)
    if args.int4:
        from nvme_strom_tpu.models.quant import (quantize_weights_int4,
                                                 quantized_nbytes)
        params = quantize_weights_int4(params)
        q, fp = quantized_nbytes(params)
        print(f"int4: all quantized leaves now {q >> 20} MiB "
              f"(vs {fp >> 20} MiB fp32; incl. any int8 lm_head)",
              flush=True)

    prompt = jnp.asarray([prompt_ids], jnp.int32)
    rng = jax.random.key(args.seed)
    if args.offload:
        # bounded-HBM decode: history beyond the window lives on NVMe
        if args.temperature != 0.0:
            ap.error("--offload decode is greedy (temperature 0)")
        from nvme_strom_tpu.models.kv_offload import (
            OffloadConfig, offloaded_generate)
        page_len = max(4, args.offload_window // 4)
        window_pages = max(1, args.offload_window // page_len)
        if args.offload_chunked_prefill and window_pages < 2:
            ap.error("--offload-chunked-prefill needs --offload-window "
                     ">= 8 (at least two pages)")
        ocfg = OffloadConfig(
            path=args.offload, page_len=page_len,
            window_pages=window_pages, quantize=args.offload_quant)
        t0 = time.monotonic()
        out = offloaded_generate(
            params, prompt, cfg, ocfg, engine, args.new,
            eos_id=args.eos_id,
            chunked_prefill=args.offload_chunked_prefill)
        dt = time.monotonic() - t0
        # single cold run: the time INCLUDES XLA compilation of the
        # prefill and per-layer segments — not comparable to the dense
        # branch's warm number (bench_suite config 10 measures warm)
        print(f"offloaded decode: window={ocfg.window} "
              f"quant={args.offload_quant or 'off'} "
              f"(cold timing, includes compile)")
    else:
        # long live-cache decodes win with the fused Pallas kernel;
        # short ones with XLA's einsum (measured crossover ~1k
        # positions)
        cache_attn = make_decode_attn() if total >= 1024 else None
        gen = jax.jit(functools.partial(
            generate, cfg=cfg, max_new_tokens=args.new,
            temperature=args.temperature, eos_id=args.eos_id,
            cache_attn=cache_attn))
        out = gen(params, prompt, rng=rng)
        out.block_until_ready()                  # compile (discarded)
        t0 = time.monotonic()
        out = gen(params, prompt, rng=rng)
        out.block_until_ready()
        dt = time.monotonic() - t0
    ids = [int(t) for t in out[0]]
    print(f"generated {args.new} tokens in {dt:.3f}s "
          f"({args.new / dt:.1f} tok/s)")
    print("output ids:", ",".join(map(str, ids)))

    engine.sync_stats()
    s = engine.stats
    print(f"engine stats: direct={s.bytes_direct} "
          f"fallback={s.bytes_fallback} bounce={s.bounce_bytes}")
    engine.close_all()
    return 0


if __name__ == "__main__":
    sys.exit(main())
