"""Evaluation example: NVMe tokens → perplexity.

Completes the train/eval/generate/serve quartet: weights lazy-load
through the engine, evaluation tokens stream from either WebDataset
shards (the training layout) or a single ``.npy`` of shape
``(n_sequences, seq_len)`` int32 (the ``formats/npy.py`` direct
reader — payload bytes go NVMe→device untouched), and the metric is
token-mean cross-entropy / perplexity.

    python examples/eval_ppl.py --weights conv/ --npy heldout.npy
    python examples/eval_ppl.py --weights conv/ --data-dir shards/ \
        --batches 50
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--weights", required=True,
                    help="converted checkpoint dir (strom_config.json)")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--npy", default=None,
                     help=".npy of (n, seq) int32 token sequences")
    src.add_argument("--data-dir", default=None,
                     help="dir of WebDataset .tar token shards")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--batches", type=int, default=0,
                    help="cap on evaluated batches (0 = everything)")
    ap.add_argument("--xent-chunks", type=int, default=0,
                    help="evaluate the cross-entropy in N sequence "
                         "slices — (b, s, vocab) logits never "
                         "materialize (the 100k+-vocab memory lever)")
    ap.add_argument("--int4", action="store_true",
                    help="weight-only int4 (lm_head stays fp; combine "
                         "with --int8 for the int8-lm_head mixed "
                         "recipe); the ppl delta vs fp is the cost")
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 quantization after load "
                         "(models/quant.py) - also measures the "
                         "quantization's perplexity cost")
    args = ap.parse_args(argv)

    import jax
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from nvme_strom_tpu.io import StromEngine
    from nvme_strom_tpu.models.transformer import TransformerConfig
    from nvme_strom_tpu.parallel.weights import LazyCheckpoint

    cfg_path = os.path.join(args.weights, "strom_config.json")
    if not os.path.exists(cfg_path):
        ap.error(f"{cfg_path} not found")
    with open(cfg_path) as f:
        cfg = TransformerConfig(**json.load(f))

    engine = StromEngine()
    params = LazyCheckpoint(args.weights).load_sharded(
        lambda name, shape: jax.sharding.SingleDeviceSharding(
            jax.devices()[0]),
        engine=engine)
    if args.int8:
        from nvme_strom_tpu.models.quant import quantize_weights_int8
        # with --int4 too: int8 ONLY the lm_head (the mixed recipe) —
        # int4 then converts the rest and passes dict leaves through
        sfx = ("lm_head",) if args.int4 else None
        params = quantize_weights_int8(params, suffixes=sfx)
        what = "lm_head (mixed recipe)" if args.int4 else "matmul weights"
        print(f"int8: {what} quantized "
              "(ppl delta vs fp measures the cost)", flush=True)
    if args.int4:
        from nvme_strom_tpu.models.quant import quantize_weights_int4
        params = quantize_weights_int4(params)
        print("int4: matmul weights packed 2/byte "
              "(ppl delta vs fp measures the cost)", flush=True)

    @jax.jit
    def eval_loss(params, tokens):
        # PURE token cross-entropy — loss_fn would fold in the MoE
        # router aux penalty and inflate the metric on expert configs
        if args.xent_chunks > 1:
            import dataclasses
            from nvme_strom_tpu.models.transformer import loss_fn
            # aux coef zeroed == pure token CE through the library's
            # own chunked path (no drift if its convention changes)
            return loss_fn(params, tokens, dataclasses.replace(
                cfg, xent_chunks=args.xent_chunks,
                router_aux_coef=0.0))
        from nvme_strom_tpu.models.transformer import forward
        logits = forward(params, tokens, cfg)
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        ll = jnp.take_along_axis(lp, tokens[:, 1:, None], axis=-1)
        return -jnp.mean(ll)

    def batches():
        if args.npy:
            from nvme_strom_tpu.formats.npy import plan_npy
            from nvme_strom_tpu.ops.bridge import (DeviceStream,
                                                   split_ranges)
            entry = plan_npy(args.npy)
            if len(entry.shape) != 2:
                ap.error(f"--npy must be (n, seq), got {entry.shape}")
            if entry.dtype != "<i4":
                ap.error(f"--npy must be int32 token ids, "
                         f"got {entry.dtype}")
            n, seq = entry.shape
            if seq < 2:
                ap.error(f"--npy seq length {seq} < 2: nothing to "
                         "predict")
            # stream one batch of contiguous rows at a time — the file
            # need not fit in device memory, and --batches caps I/O
            row = seq * 4
            ds = DeviceStream(engine,
                              depth=engine.config.queue_depth)
            fh = engine.open(args.npy)
            try:
                for i in range(0, n - args.batch + 1, args.batch):
                    ranges, _ = split_ranges(
                        [(entry.offset + i * row, args.batch * row)],
                        engine.config.chunk_bytes)
                    parts = list(ds.stream_ranges(fh, ranges))
                    flat = (parts[0] if len(parts) == 1
                            else jnp.concatenate(parts))
                    toks = flat.view(jnp.int32).reshape(args.batch, seq)
                    if int(jnp.max(toks)) >= cfg.vocab or \
                            int(jnp.min(toks)) < 0:
                        ap.error(f"--npy holds ids outside "
                                 f"[0, {cfg.vocab}) at batch {i}")
                    yield toks
            finally:
                engine.close(fh)
            return
        import glob
        shards = sorted(glob.glob(os.path.join(args.data_dir, "*.tar")))
        if not shards:
            ap.error(f"no .tar shards under {args.data_dir}")
        from nvme_strom_tpu.data.loader import ShardedLoader
        from nvme_strom_tpu.parallel.mesh import make_mesh
        mesh = make_mesh({"dp": 1})

        def decode(parts):
            (payload,) = parts.values()
            return np.frombuffer(payload, dtype=np.int32) % cfg.vocab
        with ShardedLoader(shards, mesh, args.batch, fmt="wds",
                           decode=decode, engine=engine) as loader:
            yield from loader

    t0 = time.monotonic()
    total_loss, total_tok, n = 0.0, 0, 0
    for tokens in batches():
        if args.batches and n >= args.batches:
            break
        loss = float(eval_loss(params, tokens))   # token-mean CE
        ntok = tokens.shape[0] * (tokens.shape[1] - 1)
        total_loss += loss * ntok
        total_tok += ntok
        n += 1
    if n == 0:
        ap.error("no full batches to evaluate")
    dt = time.monotonic() - t0
    ce = total_loss / total_tok
    print(f"evaluated {n} batches / {total_tok} predicted tokens "
          f"in {dt:.2f}s")
    print(f"cross-entropy: {ce:.4f} nats/token   "
          f"perplexity: {float(np.exp(ce)):.2f}")

    engine.sync_stats()
    s = engine.stats
    print(f"engine stats: direct={s.bytes_direct} "
          f"fallback={s.bytes_fallback} bounce={s.bounce_bytes}")
    engine.close_all()
    return 0


if __name__ == "__main__":
    sys.exit(main())
