"""Serving example: NVMe weight shards → continuous-batching decode.

The inference-serving walkthrough: weights lazy-load through the
O_DIRECT engine (parallel/weights.py), requests with different prompts
and budgets share fixed slots (models/serving.py), and every step
advances all active requests — freed slots admit queued work
immediately.

    python examples/serve.py --weights conv/ \
        --request 1,2,3:16 --request 7,8:32 --request 5:8

Each --request is ``comma-separated-prompt-ids:max_new``.  Token-id in,
token-id out — tokenizers are out of scope for a storage framework.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--weights", required=True,
                    help="converted checkpoint dir (must contain "
                         "strom_config.json; see tools/convert_llama)")
    ap.add_argument("--request", action="append", default=[],
                    metavar="IDS:MAX_NEW",
                    help="prompt token ids and budget, e.g. 1,2,3:16 "
                         "(repeatable)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=None,
                    help="per-slot sequence capacity (default: model "
                         "max_seq)")
    ap.add_argument("--eos-id", type=int, default=None)
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="sampling temperature for every request "
                         "(0 = greedy)")
    ap.add_argument("--top-p", type=float, default=1.0,
                    help="nucleus truncation (with --temperature > 0)")
    ap.add_argument("--seed", type=int, default=0,
                    help="base sampling seed; request i uses seed+i")
    ap.add_argument("--pallas", action="store_true",
                    help="use the fused decode-attention kernel "
                         "(wins past ~1k live positions)")
    ap.add_argument("--paged", type=int, default=0, metavar="BLOCKS",
                    help="serve from a shared KV pool of BLOCKS blocks "
                         "(paged attention; capacity = total live "
                         "tokens, not slots×max-len)")
    ap.add_argument("--block-len", type=int, default=128,
                    help="positions per pool block for --paged")
    ap.add_argument("--lookahead", type=int, default=1,
                    help="decode steps per host readback (8-16 "
                         "amortizes a high-latency host<->device link; "
                         "token-identical to 1)")
    args = ap.parse_args(argv)
    if not args.request:
        ap.error("at least one --request")
    if args.slots < 1:
        ap.error(f"--slots must be >= 1, got {args.slots}")
    if args.paged:
        # pure-argument conditions fail BEFORE the expensive weight load
        if args.pallas:
            ap.error("--paged always uses its own paged-attention "
                     "kernel; drop --pallas")
        if args.paged < 1 or args.block_len < 1:
            ap.error("--paged and --block-len must be >= 1")

    import jax
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from nvme_strom_tpu.io import StromEngine
    from nvme_strom_tpu.models.serving import DecodeServer
    from nvme_strom_tpu.models.transformer import TransformerConfig
    from nvme_strom_tpu.parallel.weights import LazyCheckpoint

    cfg_path = os.path.join(args.weights, "strom_config.json")
    if not os.path.exists(cfg_path):
        ap.error(f"{cfg_path} not found — convert with "
                 "tools/convert_llama first")
    with open(cfg_path) as f:
        cfg = TransformerConfig(**json.load(f))
    max_len = args.max_len or cfg.max_seq

    reqs = []
    for i, spec in enumerate(args.request):
        ids_part, _, new_part = spec.partition(":")
        try:
            ids = [int(t) for t in ids_part.split(",") if t.strip()]
            max_new = int(new_part or 16)
        except ValueError:
            ap.error(f"bad --request {spec!r} (want IDS:MAX_NEW)")
        if not ids:
            ap.error(f"empty prompt in --request {spec!r}")
        if max(ids) >= cfg.vocab or min(ids) < 0:
            ap.error(f"--request {spec!r}: ids must be in "
                     f"[0, {cfg.vocab})")
        # validate bounds BEFORE the expensive weight load — the same
        # checks DecodeServer.submit enforces, surfaced as ap.error
        if max_new < 1:
            ap.error(f"--request {spec!r}: MAX_NEW must be >= 1")
        if len(ids) + max_new > max_len:
            ap.error(f"--request {spec!r}: prompt {len(ids)} + "
                     f"{max_new} exceeds max_len {max_len}")
        if args.paged and (len(ids) + max_new
                           > args.paged * args.block_len):
            ap.error(f"--request {spec!r}: worst case "
                     f"{len(ids) + max_new} tokens can never fit the "
                     f"{args.paged}x{args.block_len} pool")
        reqs.append((f"r{i}", ids, max_new))

    engine = StromEngine()
    t0 = time.monotonic()
    params = LazyCheckpoint(args.weights).load_sharded(
        lambda name, shape: jax.sharding.SingleDeviceSharding(
            jax.devices()[0]),
        engine=engine)
    print(f"weights: {len(params)} tensors in "
          f"{time.monotonic() - t0:.2f}s", flush=True)

    if args.paged:
        from nvme_strom_tpu.models.serving import PagedDecodeServer
        srv = PagedDecodeServer(params, cfg, max_batch=args.slots,
                                max_len=max_len,
                                total_blocks=args.paged,
                                block_len=args.block_len)
    else:
        cache_attn = None
        if args.pallas:
            from nvme_strom_tpu.ops.decode_attention import (
                make_decode_attn)
            cache_attn = make_decode_attn()
        srv = DecodeServer(params, cfg, max_batch=args.slots,
                           max_len=max_len, cache_attn=cache_attn)
    for i, (rid, ids, max_new) in enumerate(reqs):
        srv.submit(rid, ids, max_new, eos_id=args.eos_id,
                   temperature=args.temperature, top_p=args.top_p,
                   seed=args.seed + i)

    t0 = time.monotonic()
    results = srv.run(lookahead=args.lookahead)
    dt = time.monotonic() - t0
    total = sum(len(v) for v in results.values())
    for rid, ids, _ in reqs:
        print(f"{rid}: {','.join(map(str, results[rid]))}")
    print(f"served {len(reqs)} requests / {total} tokens in {dt:.2f}s "
          f"({total / dt:.1f} tok/s aggregate, {args.slots} slots)")

    engine.sync_stats()
    s = engine.stats
    print(f"engine stats: direct={s.bytes_direct} "
          f"fallback={s.bytes_fallback} bounce={s.bounce_bytes}")
    engine.close_all()
    return 0


if __name__ == "__main__":
    sys.exit(main())
