"""Zero-downtime drain & warm handoff: rolling replica replacement
with shipped warm-state bundles (docs/RESILIENCE.md "Drain & handoff").

PR 19 made a replica's boot elastic (serve-while-restoring); this
module makes its RETIREMENT elastic.  Today a replacement is an abrupt
kill — in-flight decode sessions error out, nothing sheds ahead of
time, and the replacement boots against whatever stale manifests
happen to be on disk.  With ``STROM_HANDOFF=1`` the retiring replica
instead walks a forward-only phase machine mirroring the cold-start
coordinator's:

    serving ──drain requested──▶ draining ──in-flight done /
                                           │ deadline hit
                              bundle built ▼
              retired ◀──published── handing_off

* ``serving``     — normal operation; the coordinator is passive.
* ``draining``    — new prefill admissions DEFER (the PR-10/17 shed
  path's semantics: requests stay queued, nothing fails) while
  in-flight sessions run to completion under a bounded
  ``STROM_DRAIN_DEADLINE_S``.  A drain that outlives its deadline with
  sessions still decoding dumps ``reason=handoff_stall`` with the
  drain phase and the scheduler's per-class backlog.
* ``handing_off`` — the warm state ships: fresh ``.warmhints.json``
  hostcache snapshots, the ``PrefixStore``'s proven-drained flush +
  clean manifest, the cold-start claim-table residue (tensors the old
  replica demand-faulted — its measured hot set), per-tenant SLO/
  ledger state, and — for sessions still queued or decoding past the
  deadline — exported session state (prompt token chain + KV page
  keys) so the replacement re-admits them through the PR-9 prefix
  store instead of recomputing from scratch.  Everything lands in one
  atomic ``<base>.handoff.json`` bundle (the io/warmup.py temp+rename
  + staleness-validation discipline).
* ``retired``     — bundle published; the process may exit.

On the receiving side :func:`consume_bundle` replays a bundle at boot:
warm hints and the KV manifest at ``prefetch`` class, claim-table
residue at ``restore`` class ahead of the bulk stream, exported
sessions re-admitted FIRST at ``decode`` class.  A torn, stale, or
missing bundle is a brown-out to a plain PR-19 cold start
(``handoff_brownouts``) — never a black-out, never an error.

The phase is exported as the ``drain_phase`` gauge through StromStats
→ strom_stat/strom-top/debugsrv ``/health``; every counter lives in
the ``handoff_*`` block.  ``STROM_HANDOFF=0`` (default) is bit-for-bit
inert, proven by test.

Locking: ``handoff.DrainCoordinator._lock`` is a leaf-facing
coordinator lock (group ``handoff`` in analysis/lock_order.conf).
Engine work — serving steps, store flushes, hint collection, flight
dumps — runs OUTSIDE the lock; only phase/word-size state mutates
under it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

from nvme_strom_tpu.utils.config import HandoffConfig
from nvme_strom_tpu.utils.lockwitness import make_lock
from nvme_strom_tpu.utils.stats import _atomic_write_text

#: drain phases in order; index = numeric gauge code
DRAIN_PHASES = ("serving", "draining", "handing_off", "retired")

#: bundle sidecar suffix; checkpoint/manager.py lists it next to
#: ``.kvman.json``/``.warmhints.json`` in its age-gated orphan sweep
HANDOFF_SUFFIX = ".handoff.json"

_VERSION = 1


def bundle_path(base: str) -> str:
    """``<base>.handoff.json`` — the bundle location anchored to
    ``base`` (the KV prefix-store page file, normally): the orphan GC's
    base-file-gone verdict and the staleness validation both key off
    the anchor, exactly like the warm-hint sidecars."""
    return base + HANDOFF_SUFFIX


def _stat_block(path: str) -> Optional[dict]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return {"size": st.st_size, "mtime_ns": st.st_mtime_ns}


def write_handoff_bundle(base: str, doc: dict) -> Optional[str]:
    """Atomically publish ``doc`` as ``base``'s handoff bundle
    (temp + rename: a replacement sees the old bundle or the new one,
    never a prefix).  Stamps the anchor's size/mtime_ns so a bundle
    outliving a rewritten base file loads as a cold start.  Returns
    the bundle path, or None when the anchor is gone."""
    anchor = _stat_block(base)
    if anchor is None:
        return None
    out = bundle_path(base)
    doc = dict(doc)
    doc["version"] = _VERSION
    doc["base"] = anchor
    _atomic_write_text(out, json.dumps(doc, sort_keys=True))
    return out


def load_handoff_bundle(base: str) -> Optional[dict]:
    """Load and validate ``base``'s bundle against the CURRENT anchor
    file: a missing, corrupt, version-skewed, or stale bundle (anchor
    rewritten since publish) yields ``None`` — the brown-out ladder's
    first rung, a plain cold start, never an error."""
    manifest = bundle_path(base)
    try:
        with open(manifest, "r") as f:
            doc = json.load(f)
        st = os.stat(base)
    except (OSError, ValueError):
        return None
    if (not isinstance(doc, dict)
            or doc.get("version") != _VERSION
            or not isinstance(doc.get("base"), dict)
            or doc["base"].get("size") != st.st_size
            or doc["base"].get("mtime_ns") != st.st_mtime_ns):
        return None
    ck = doc.get("checkpoint")
    if ck is not None:
        # the replacement must serve the SAME checkpoint generation:
        # sessions and hot tensors from yesterday's weights would
        # restore the wrong model's state
        if (not isinstance(ck, dict)
                or _stat_block(str(ck.get("path", ""))) !=
                {"size": ck.get("size"), "mtime_ns": ck.get("mtime_ns")}):
            return None
    sessions = doc.get("sessions", [])
    if not isinstance(sessions, list):
        return None
    for s in sessions:
        try:
            if (not s["prompt"] or int(s["max_new"]) < 1
                    or not all(isinstance(t, int) for t in s["prompt"])
                    or not all(isinstance(t, int)
                               for t in s.get("emitted", []))):
                return None
        except (TypeError, KeyError, ValueError):
            return None
    return doc


class DrainCoordinator:
    """Drives one replica's retirement: the drain phase machine, the
    deferred-admission gate on the server, the stall dump, and the
    bundle publish.

    Thread-safe like the cold-start coordinator; construction alone
    changes nothing — the machine only moves when :meth:`begin_drain`
    (or a ``STROM_DRAIN_ON_SIGTERM`` handler) fires.  Integrators gate
    construction on ``handoff_enabled()``; with the gate off nothing
    builds one and the stack is bit-for-bit the pre-handoff code.
    """

    def __init__(self, engine=None, server=None,
                 cfg: Optional[HandoffConfig] = None,
                 checkpoint: Optional[str] = None,
                 hint_paths: Optional[Sequence[str]] = None,
                 bundle: Optional[str] = None) -> None:
        self.cfg = cfg or HandoffConfig()
        self.engine = engine
        self.server = server
        self.checkpoint = checkpoint
        self.hint_paths = list(hint_paths or [])
        self._bundle = bundle
        self._lock = make_lock("handoff.DrainCoordinator._lock")
        self._phase = "serving"
        self._t0 = time.monotonic()
        self._t_phase: Dict[str, float] = {"serving": 0.0}
        self._published: Optional[str] = None

    # -- phase machine -----------------------------------------------------

    @property
    def phase(self) -> str:
        with self._lock:
            return self._phase

    def phase_times(self) -> Dict[str, float]:
        """Seconds-from-construction each phase was entered."""
        with self._lock:
            return dict(self._t_phase)

    @property
    def bundle(self) -> Optional[str]:
        """Where the bundle goes (anchored to the KV store's page file
        unless given explicitly); None when nothing anchors it."""
        if self._bundle is not None:
            return self._bundle
        store = getattr(self.server, "kv_store", None)
        path = getattr(store, "path", None)
        return bundle_path(path) if path else None

    def _advance(self, new: str) -> bool:
        """Move forward only — a late drain request from a slow thread
        never rewinds the machine.  Returns True on a real
        transition."""
        with self._lock:
            if DRAIN_PHASES.index(new) <= DRAIN_PHASES.index(self._phase):
                return False
            self._phase = new
            self._t_phase[new] = round(time.monotonic() - self._t0, 6)
        self._export_gauge()
        return True

    def _export_gauge(self) -> None:
        stats = self._stats()
        if stats is not None:
            ph = self.phase
            stats.set_gauges(drain_phase=ph,
                             drain_phase_code=DRAIN_PHASES.index(ph))

    def _stats(self):
        return getattr(self.engine, "stats", None)

    # -- the protocol ------------------------------------------------------

    def begin_drain(self) -> bool:
        """Enter ``draining``: the server stops admitting new prefills
        (deferred with the shed path's semantics, never dropped).
        Idempotent; returns True on the real transition."""
        if not self._advance("draining"):
            return False
        stats = self._stats()
        if stats is not None:
            stats.add(handoff_drains=1)
        srv = self.server
        if srv is not None and hasattr(srv, "begin_drain"):
            srv.begin_drain()
        return True

    def drain(self, lookahead: int = 4,
              deadline_s: Optional[float] = None) -> Dict[str, object]:
        """The full retirement: drain in-flight sessions under the
        deadline (stepping the server so they finish and their tokens
        are DELIVERED by this replica), then publish the bundle and
        retire.  Returns ``{"results": {rid: tokens}, "bundle": path}``
        — ``results`` are the sessions that completed here; everything
        still live rode the bundle instead.  Zero sessions are ever
        dropped."""
        self.begin_drain()
        deadline = (self.cfg.deadline_s if deadline_s is None
                    else float(deadline_s))
        srv = self.server
        results: Dict[object, List[int]] = {}
        stalled = False
        t0 = time.monotonic()
        while srv is not None and not srv.idle:
            if time.monotonic() - t0 >= deadline:
                stalled = True
                break
            if all(s is None for s in srv.slots):
                # only deferred queue entries remain: they export —
                # stepping again would spin on the closed admission gate
                break
            results.update(srv.step_many(lookahead))
            if self.cfg.poll_ms > 0:
                time.sleep(0.0)   # yield; decode paces the loop itself
        if stalled:
            self._stall_dump(time.monotonic() - t0, deadline)
        path = self.publish_bundle()
        self._advance("retired")
        return {"results": results, "bundle": path}

    def publish_bundle(self) -> Optional[str]:
        """Build and atomically publish the warm-state bundle
        (``handing_off`` → the write).  Best-effort per part — a piece
        that cannot be collected ships as absent, and the replacement's
        validation decides what it can still use.  Returns the bundle
        path or None (nothing to anchor to / anchor gone)."""
        self._advance("handing_off")
        out = self.bundle
        if out is None:
            return None
        base = out[:-len(HANDOFF_SUFFIX)]
        srv = self.server
        store = getattr(srv, "kv_store", None)
        stats = self._stats()

        # 1) sessions still queued or decoding: exported, then removed
        # from the retiring server so it can end idle
        sessions: List[dict] = []
        if srv is not None and hasattr(srv, "export_sessions"):
            sessions = srv.export_sessions(self.cfg.max_sessions,
                                           pop=True)

        # 2) the PrefixStore's proven-drained flush (the PR-13 stamping
        # — the ONLY flush a clean manifest may come from), plus the
        # stamped key set so the bundle never references a page whose
        # write was not proven complete
        ready: set = set()
        if store is not None:
            try:
                ready = set(store.flush_for_handoff())
            except Exception:
                ready = set()
            for s in sessions:
                s["kv_keys"] = [k for k in s.get("kv_keys", [])
                                if k in ready]

        # 3) fresh hostcache warm-hint snapshots for every file the
        # replica served hot (the store's page file rides implicitly)
        from nvme_strom_tpu.io.warmup import refresh_hints
        paths = list(self.hint_paths)
        if store is not None and getattr(store, "path", None):
            paths.append(store.path)
        hints = refresh_hints(self.engine, paths)

        # 4) cold-start claim-table residue: the tensors requests could
        # not wait for — the old replica's measured hot set
        hot: List[str] = []
        src = getattr(srv, "_param_source", None)
        names = getattr(src, "fault_names", None)
        if callable(names):
            try:
                hot = list(names())
            except Exception:
                hot = []

        # 5) per-tenant SLO/ledger state (share_boost notches + the
        # per-tenant counter ledger) so isolation decisions survive
        # the replacement
        tenants = self._tenant_state(stats)

        doc = {
            "checkpoint": (dict(_stat_block(self.checkpoint) or {},
                                path=self.checkpoint)
                           if self.checkpoint else None),
            "kv_manifest": (store.manifest_path
                            if store is not None else None),
            "warm_hints": hints,
            "hot_tensors": hot,
            "tenants": tenants,
            "sessions": sessions,
        }
        path = write_handoff_bundle(base, doc)
        if path is not None and stats is not None:
            stats.add(handoff_bundles=1,
                      handoff_bundle_bytes=os.path.getsize(path),
                      handoff_sessions_exported=len(sessions))
        return path

    def _tenant_state(self, stats) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        try:
            from nvme_strom_tpu.io.tenants import get_registry
            out = get_registry().export_state()
        except Exception:
            out = {}
        ledger = (stats.tenant_stats if stats is not None else {})
        for tid, counters in ledger.items():
            out.setdefault(tid, {})["ledger"] = dict(counters)
        return out

    def _stall_dump(self, waited_s: float, deadline_s: float) -> None:
        flight = getattr(self.engine, "flight", None)
        if flight is None:
            return
        sched = getattr(self.engine, "scheduler", None)
        backlog = sched.backlog() if sched is not None else {}
        srv = self.server
        path = flight.dump("handoff_stall", extra={
            "drain_phase": self.phase,
            "waited_s": round(waited_s, 3),
            "deadline_s": deadline_s,
            "slots_busy": (sum(s is not None for s in srv.slots)
                           if srv is not None else 0),
            "queued": len(srv.queue) if srv is not None else 0,
            "backlog": backlog,
        })
        stats = self._stats()
        if path is not None and stats is not None:
            stats.add(handoff_stall_dumps=1)

    # -- graceful-shutdown exit hook ---------------------------------------

    def final_snapshot(self, reason: str = "exit") -> None:
        """The exit flush a TERM used to lose: a last metrics snapshot
        to the export/textfile targets plus a FORCED flight dump of the
        tail ops."""
        stats = self._stats()
        if stats is not None:
            try:
                stats.maybe_export()
            except Exception:
                pass
        flight = getattr(self.engine, "flight", None)
        if flight is not None:
            try:
                flight.dump("handoff_exit", extra={
                    "drain_phase": self.phase,
                    "reason": reason,
                }, force=True)
            except Exception:
                pass


def install_drain_signals(coord: DrainCoordinator, signals=None,
                          chain: bool = True) -> Optional[dict]:
    """Install SIGTERM/SIGINT handlers that drain-and-retire before the
    process dies (``STROM_DRAIN_ON_SIGTERM=1``; a no-op dict-less None
    when the knob is off, so stock signal semantics survive the gate).

    The handler enters the full drain (bundle publish included), then
    flushes the final snapshot; with ``chain`` it forwards to the
    previously-installed handler (or raises ``SystemExit(128+sig)`` for
    the default action) so supervisors still observe the termination.
    Returns ``{signum: previous_handler}`` for
    :func:`uninstall_drain_signals`."""
    import signal as _signal
    if not coord.cfg.drain_on_sigterm:
        return None
    sigs = tuple(signals or (_signal.SIGTERM, _signal.SIGINT))
    prev: dict = {}

    def _handler(signum, frame):
        try:
            coord.drain()
        finally:
            coord.final_snapshot(reason=f"signal {signum}")
            if chain:
                p = prev.get(signum)
                if callable(p):
                    p(signum, frame)
                elif p == _signal.SIG_DFL:
                    raise SystemExit(128 + signum)

    for s in sigs:
        prev[s] = _signal.signal(s, _handler)
    return prev


def uninstall_drain_signals(prev: Optional[dict]) -> None:
    """Restore the handlers :func:`install_drain_signals` displaced."""
    import signal as _signal
    for s, h in (prev or {}).items():
        _signal.signal(s, h)


# ---------------------------------------------------------------------------
# the receiving side: bundle consumption at boot
# ---------------------------------------------------------------------------

def consume_bundle(base: str, engine=None, server=None,
                   coordinator=None, checkpoint=None,
                   stats=None) -> Optional[dict]:
    """Replay ``base``'s handoff bundle into a freshly-booted replica.

    * exported sessions re-admit FIRST (``server.submit`` — the decode
      class; their prefix pages restore through the PR-9 store instead
      of re-prefilling) — the returned ``{"sessions": {rid: emitted}}``
      carries each session's already-delivered tokens so the consumer
      composes ``emitted + replacement_tokens`` into the full answer;
    * claim-table residue pre-faults at ``restore`` class ahead of the
      bulk stream (``checkpoint`` = the FaultingCheckpoint, optional);
    * warm hints replay at ``prefetch`` class — through the cold-start
      coordinator's warming phase when one is given, else inline.

    A torn/stale/missing bundle returns None and counts ONE
    ``handoff_brownouts`` — the replacement then runs a plain PR-19
    cold start with zero errors (the brown-out ladder)."""
    stats = stats if stats is not None \
        else getattr(engine, "stats", None)
    doc = load_handoff_bundle(base)
    if doc is None:
        if stats is not None:
            stats.add(handoff_brownouts=1)
        return None

    restored = 0
    sessions: Dict[object, List[int]] = {}
    for s in doc.get("sessions", []):
        emitted = [int(t) for t in s.get("emitted", [])]
        prompt = [int(t) for t in s["prompt"]] + emitted
        rid = s.get("rid")
        if server is not None:
            try:
                server.submit(rid, prompt, int(s["max_new"]),
                              eos_id=s.get("eos_id"),
                              temperature=float(s.get("temperature",
                                                      0.0)),
                              top_p=float(s.get("top_p", 1.0)),
                              seed=int(s.get("seed", 0)),
                              tenant=s.get("tenant"))
            except (ValueError, TypeError):
                continue   # one bad session never blacks out the rest
        sessions[rid] = emitted
        restored += 1
    if restored and stats is not None:
        stats.add(handoff_sessions_restored=restored)

    hot = [str(n) for n in doc.get("hot_tensors", [])]
    prefault_thread = None
    if hot and checkpoint is not None and hasattr(checkpoint, "get"):
        def _prefault(names=tuple(hot), ckpt=checkpoint):
            for name in names:
                try:
                    ckpt.get(name, klass="restore")
                except Exception:
                    return   # bulk lane still owns completeness
        prefault_thread = threading.Thread(target=_prefault,
                                           name="strom-handoff-hot",
                                           daemon=True)
        prefault_thread.start()

    hints = [str(p) for p in doc.get("warm_hints", [])]
    n_hints = 0
    if engine is not None and hints:
        from nvme_strom_tpu.io.warmup import prefetch_hints
        if coordinator is not None \
                and hasattr(coordinator, "add_warmup"):
            for p in hints:
                coordinator.add_warmup(
                    lambda eng=engine, pp=p: prefetch_hints(eng, pp))
            n_hints = len(hints)
        else:
            for p in hints:
                n_hints += 1 if prefetch_hints(engine, p) else 0

    _restore_tenants(doc.get("tenants", {}), stats)
    if stats is not None:
        stats.set_gauges(handoff_source="bundle")
    # callers tearing the stack down early must join prefault_thread
    # BEFORE closing the engine — its reads target live engine state
    # (the bulk thread has join_bulk for the same reason)
    return {"sessions": sessions, "restored": restored,
            "hints": n_hints, "hot_tensors": len(hot),
            "prefault_thread": prefault_thread,
            "bundle": bundle_path(base)}


def _restore_tenants(state: Dict[str, dict], stats) -> None:
    """Re-apply per-tenant SLO boosts and fold the shipped ledger into
    the replacement's stats — isolation pressure and fleet dashboards
    survive the replacement instead of resetting."""
    if not state:
        return
    try:
        from nvme_strom_tpu.io.tenants import get_registry, \
            tenants_enabled
        if tenants_enabled():
            get_registry().restore_state(state)
    except Exception:
        pass
    if stats is None:
        return
    for tid, st in state.items():
        ledger = st.get("ledger")
        if isinstance(ledger, dict):
            try:
                stats.add_tenant_stat(tid, **{
                    k: int(v) for k, v in ledger.items()})
            except (TypeError, ValueError):
                pass
