"""Hostcache warm-state manifests: ``.warmhints.json`` sidecars
(docs/RESILIENCE.md "Elastic cold-start").

A long-running replica's pinned-DRAM cache (io/hostcache.py) encodes
hours of learned access pattern — which weight tiles, KV pages, and
scan windows the workload actually re-reads.  A restart throws that
away; a scaled-out replica never had it.  This module makes the warm
state portable: :func:`collect_warm_hints` snapshots one file's
resident spans into an atomically-published ``<path>.warmhints.json``
sidecar, and :func:`prefetch_hints` replays the manifest through the
normal engine read path at ``prefetch`` class with ``hot=True`` during
the cold-start ``warming`` phase — so the lines are re-filled (and
hot-pinned) behind live traffic, and the new replica reaches
steady-state hit rates in minutes, not hours.

Hygiene (the part that makes hints safe to trust):

* The manifest records the base file's size and mtime_ns; a hint list
  written against yesterday's file loads as empty rather than warming
  the wrong bytes.
* Writes go through the one atomic temp+rename primitive
  (:func:`~nvme_strom_tpu.utils.stats._atomic_write_text`) — a crash
  mid-publish leaves the old manifest or none, never a torn one.
* Orphans (hint file outliving its base) are swept by the same
  age-gated GC as ``.kvman.json`` (checkpoint/manager.py,
  ``strom-scrub --gc``) so a crashed replica never leaves debris that
  mis-warms the next boot.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Tuple

from nvme_strom_tpu.io.plan import plan_and_submit
from nvme_strom_tpu.utils.stats import _atomic_write_text

#: manifest sidecar suffix; checkpoint/manager.py lists it next to
#: ``.kvman.json`` in its orphan sweep
WARMHINT_SUFFIX = ".warmhints.json"

_VERSION = 1


def hint_path(path: str) -> str:
    """``<path>.warmhints.json`` — the sidecar location for ``path``."""
    return path + WARMHINT_SUFFIX


def collect_warm_hints(engine, path: str,
                       max_spans: int = 1024) -> Optional[str]:
    """Snapshot ``path``'s hostcache-resident spans into its sidecar.

    Returns the manifest path, or None when there is nothing worth
    writing (cache tier off, file unknown, no resident spans, or a
    zero budget).  Spans come back from the cache largest-first, so
    trimming to ``max_spans`` keeps the ranges that buy the most DRAM
    hits on the next boot.
    """
    if max_spans <= 0:
        return None
    from nvme_strom_tpu.io import hostcache as _hc
    cache = _hc._cache
    if cache is None:
        return None
    try:
        st = os.stat(path)
    except OSError:
        return None
    fkey = (st.st_dev, st.st_ino, st.st_mtime_ns, st.st_size)
    spans = cache.resident_spans(fkey)[:max_spans]
    if not spans:
        return None
    out = hint_path(path)
    write_warm_hints(out, spans, size=st.st_size,
                     mtime_ns=st.st_mtime_ns)
    return out


def refresh_hints(engine, paths: Sequence[str],
                  max_spans: int = 1024) -> List[str]:
    """Re-snapshot warm hints for every path in ``paths`` (drain-time:
    a handoff bundle ships FRESH ``.warmhints.json`` sidecars, not
    whatever a periodic snapshot last left behind).  Returns the BASE
    paths whose sidecars were (re)written — the list a bundle records
    so the replacement knows which files to replay at prefetch class.
    Best-effort per path; duplicates collapse."""
    out: List[str] = []
    seen = set()
    for p in paths:
        if not p or p in seen:
            continue
        seen.add(p)
        if collect_warm_hints(engine, p, max_spans=max_spans):
            out.append(p)
    return out


def write_warm_hints(manifest: str, spans: Sequence[Tuple[int, int]], *,
                     size: int, mtime_ns: int) -> None:
    """Atomically publish a hint manifest (temp + rename: readers see
    the old list or the new one, never a prefix)."""
    doc = {
        "version": _VERSION,
        "size": int(size),
        "mtime_ns": int(mtime_ns),
        "spans": [[int(o), int(n)] for o, n in spans],
    }
    _atomic_write_text(manifest, json.dumps(doc, sort_keys=True))


def load_warm_hints(path: str) -> List[Tuple[int, int]]:
    """Load ``path``'s hint spans, validating the manifest against the
    CURRENT file: a missing, corrupt, version-skewed, or stale sidecar
    (base file rewritten since the snapshot) yields ``[]`` — a cold
    boot, never a mis-warmed one."""
    manifest = hint_path(path)
    try:
        with open(manifest, "r") as f:
            doc = json.load(f)
        st = os.stat(path)
    except (OSError, ValueError):
        return []
    if (not isinstance(doc, dict)
            or doc.get("version") != _VERSION
            or doc.get("size") != st.st_size
            or doc.get("mtime_ns") != st.st_mtime_ns):
        return []
    spans = []
    for item in doc.get("spans", []):
        try:
            off, ln = int(item[0]), int(item[1])
        except (TypeError, ValueError, IndexError):
            return []
        if off < 0 or ln <= 0 or off + ln > st.st_size:
            return []
        spans.append((off, ln))
    return spans


def prefetch_hints(engine, path: str,
                   spans: Optional[Sequence[Tuple[int, int]]] = None,
                   klass: str = "prefetch") -> int:
    """Replay a hint manifest through the engine at ``prefetch`` class
    with ``hot=True`` (fills hot-pin their lines, mirroring the KV
    decode path) and wait for completion.  Returns the span count
    prefetched; best-effort — any failure warms less, never errors."""
    if spans is None:
        spans = load_warm_hints(path)
    if not spans:
        return 0
    warmed = 0
    try:
        fh = engine.open(path)
        try:
            per_extent = plan_and_submit(
                engine, [(fh, off, ln) for off, ln in spans],
                klass=klass, hot=True)
            for pieces in per_extent:
                done = True
                for piece in pieces:
                    try:
                        piece.wait()
                    except Exception:
                        done = False
                    finally:
                        piece.release()
                if done and pieces:
                    warmed += 1
        finally:
            engine.close(fh)
    except Exception:
        pass
    stats = getattr(engine, "stats", None)
    if stats is not None and warmed:
        stats.add(coldstart_warm_spans=warmed)
    return warmed
