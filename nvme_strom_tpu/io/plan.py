"""Extent-coalescing I/O planner — the shared read-plan layer between
consumers and the engine's vectored submit.

The reference amortizes per-request overhead by carrying MANY chunks in
one MEMCPY_SSD2GPU command (SURVEY.md §3.1); before this module every
consumer crossed Python→ctypes→``io_uring_enter`` once per extent and
hand-rolled its own chunk-split loop.  The planner is the one place
both problems are solved:

  coalesce   extents that are adjacent — or separated by at most
             ``STROM_COALESCE_GAP`` bytes (default one 4 KiB block) —
             on the SAME file merge into one larger O_DIRECT read.
             Consumers get zero-copy SUB-VIEWS of the completed span
             buffer (legal because the engine already returns offset
             views instead of memcpy'ing: slicing a numpy view costs
             nothing).  Overlapping/duplicate extents dedupe into one
             read the same way.  Cross-file extents never coalesce.
  split      extents larger than the split size (the ledger-tuned
             chunk from ``utils/tuning.tuned_chunk_bytes``, capped at
             the engine's staging-buffer capacity) break into pieces —
             replacing the near-identical hard-coded loops each
             consumer carried.  ``split_unit`` keeps piece boundaries
             on record boundaries (fixedrec) — pieces of one extent
             are always multiples of the unit from the extent's start.
  batch      the resulting spans submit through the engine's
             ``submit_readv`` (ONE C call, ONE ``io_uring_enter``
             doorbell) when available, falling back to per-span
             ``submit_read`` for engine wrappers that predate it.

Accounting: every merged extent counts ``StromStats.spans_coalesced``;
the C engine counts ``submit_batches`` / ``submit_syscalls_saved`` at
the vectored boundary.  ``bench.py`` reports the resulting coalesce
ratio and syscalls/GiB next to the throughput headline; thresholds and
semantics are documented in docs/PERF.md.

The planner composes with the resilience stack unchanged: a
``ResilientEngine`` submits the batch through the wrapped engine and
wraps EACH span in its own recovery loop (a failed span retries alone,
never the whole batch), and ``FaultyEngine`` injects per-span faults
into the vectored path (docs/RESILIENCE.md).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

#: default coalesce-gap: one O_DIRECT logical block — reading one
#: wasted block is cheaper than a second NVMe round trip, and tar's
#: 512 B inter-member headers / the offload file's slot padding both
#: fall under it
DEFAULT_COALESCE_GAP = 4096


def coalesce_gap() -> int:
    """Gap threshold in bytes (env ``STROM_COALESCE_GAP``; default one
    4 KiB block).  0 disables coalescing across gaps (adjacent and
    overlapping extents still merge); negative values clamp to 0."""
    try:
        return max(0, int(os.environ.get("STROM_COALESCE_GAP",
                                         DEFAULT_COALESCE_GAP)))
    except ValueError:
        return DEFAULT_COALESCE_GAP


def split_spans(spans, chunk: int):
    """(offset, length) spans → (flat sub-ranges ≤ ``chunk``, per-span
    sub-range counts).  The one splitting rule every chunk-bound
    consumer shares (engine reads are capped at chunk_bytes);
    zero-length spans contribute zero sub-ranges but keep their count
    entry so group boundaries stay aligned.  (Formerly
    ``ops.bridge.split_ranges``, which now delegates here.)"""
    flat, counts = [], []
    for off, ln in spans:
        before = len(flat)
        while ln > 0:
            take = min(chunk, ln)
            flat.append((off, take))
            off += take
            ln -= take
        counts.append(len(flat) - before)
    return flat, counts


@dataclass(frozen=True)
class ExtentPlan:
    """The pure (side-effect-free) plan: which engine reads to submit
    and where each input extent's bytes land in them.

    ``spans``       (fh, offset, length) engine reads, each ≤ the split
                    size, in submission order.
    ``placements``  per input extent (input order), the ordered pieces
                    covering it: (span_index, lo, hi) byte ranges
                    RELATIVE to that span's completed view.  Zero-
                    length extents get an empty piece list.
    ``spans_coalesced``  input extents that merged into a span opened
                    by an earlier extent (k-extent merge counts k-1).
    ``gap_bytes``   dead bytes deliberately read through when merging
                    near-adjacent extents (the coalesce-gap waste class
                    of obs/ledger.py: cheaper than extra NVMe round
                    trips, but bandwidth nonetheless — honestly
                    accounted as ``waste_coalesce_gap_bytes``).
    """

    spans: List[Tuple[int, int, int]]
    placements: List[List[Tuple[int, int, int]]]
    spans_coalesced: int
    n_extents: int
    gap_bytes: int = 0

    @property
    def submits_saved(self) -> int:
        """Engine submissions a per-extent caller would have made minus
        what this plan makes (coalescing net of splitting)."""
        return self.n_extents - len(self.spans)


def plan_extents(extents: Sequence[Tuple[int, int, int]], *,
                 chunk_bytes: int, gap: Optional[int] = None,
                 split_unit: int = 1) -> ExtentPlan:
    """Sort + coalesce + split ``(fh, offset, length)`` extents.

    ``chunk_bytes``: max bytes of one engine read (≤ the engine's
    staging-buffer capacity).  ``gap``: max bytes of dead space to read
    through when merging (None = env/default via :func:`coalesce_gap`).
    ``split_unit``: piece boundaries of a SPLIT extent stay multiples
    of this from the extent's start (record size for fixedrec); a
    merged span is never split, so sub-views inside it keep exact
    byte placement regardless of the unit.
    """
    if gap is None:
        gap = coalesce_gap()
    if split_unit <= 0:
        raise ValueError(f"split_unit must be >= 1, got {split_unit}")
    split = (chunk_bytes // split_unit) * split_unit
    if split <= 0:
        raise ValueError(
            f"split_unit ({split_unit}) exceeds chunk_bytes "
            f"({chunk_bytes}); raise EngineConfig.chunk_bytes")
    n = len(extents)
    placements: List[List[Tuple[int, int, int]]] = [[] for _ in range(n)]
    spans: List[Tuple[int, int, int]] = []
    coalesced = 0

    for i in range(n):
        if extents[i][2] < 0:
            raise ValueError(f"extent {i}: negative length "
                             f"{extents[i][2]}")
    order = sorted((i for i in range(n) if extents[i][2] > 0),
                   key=lambda i: (extents[i][0], extents[i][1],
                                  extents[i][2]))

    def emit(group: list) -> None:
        """One coalesced group → spans + placements.  Multi-extent
        groups fit one span by construction; a lone oversized extent
        splits at unit-aligned piece boundaries."""
        nonlocal coalesced
        fh = extents[group[0]][0]
        start = extents[group[0]][1]
        end = max(extents[i][1] + extents[i][2] for i in group)
        length = end - start
        if length <= split:
            si = len(spans)
            spans.append((fh, start, length))
            for i in group:
                off, ln = extents[i][1], extents[i][2]
                placements[i].append((si, off - start, off - start + ln))
            coalesced += len(group) - 1
            return
        # lone oversized extent: piece k covers [start + k*split, ...)
        assert len(group) == 1
        i = group[0]
        pos = 0
        while pos < length:
            take = min(split, length - pos)
            si = len(spans)
            spans.append((fh, start + pos, take))
            placements[i].append((si, 0, take))
            pos += take

    group: list = []
    g_fh = g_start = g_end = 0
    gap_bytes = 0
    for i in order:
        fh, off, ln = extents[i]
        if group and fh == g_fh and off <= g_end + gap \
                and max(g_end, off + ln) - g_start <= split:
            if off > g_end:
                # dead bytes read through to merge (ledger waste class)
                gap_bytes += off - g_end
            group.append(i)
            g_end = max(g_end, off + ln)
            continue
        if group:
            emit(group)
        group = [i]
        g_fh, g_start, g_end = fh, off, off + ln
    if group:
        emit(group)
    return ExtentPlan(spans=spans, placements=placements,
                      spans_coalesced=coalesced, n_extents=n,
                      gap_bytes=gap_bytes)


class _SharedSpan:
    """One submitted span read, shared by every sub-view cut from it.
    The underlying request releases when the LAST view releases."""

    __slots__ = ("pending", "_refs")

    def __init__(self, pending, refs: int):
        self.pending = pending
        self._refs = refs

    def release_one(self) -> None:
        self._refs -= 1
        if self._refs <= 0:
            self.pending.release()


_EMPTY = np.empty(0, dtype=np.uint8)


class SpanView:
    """PendingRead-shaped zero-copy sub-view of a (possibly coalesced)
    span read.

    ``wait()`` returns ``span_view[lo:hi]`` — a numpy slice of the
    engine's staging buffer, no copy; validity follows the span's
    buffer (until every view of the span releases).  ``length``/
    ``fh``/``offset`` describe THIS piece, so ``wait_exact`` reports
    name the exact range.  A span completing short (EOF/device short
    read) surfaces here as a short sub-view, which ``wait_exact``
    turns into the loud OSError.  Piece of a zero-length extent:
    ``lo == hi``, waits to an empty view without any I/O dependency
    beyond its span.
    """

    __slots__ = ("_span", "_lo", "_hi", "fh", "offset", "_released")

    def __init__(self, span: _SharedSpan, lo: int, hi: int,
                 fh: int, offset: int):
        self._span = span
        self._lo = lo
        self._hi = hi
        self.fh = fh
        self.offset = offset
        self._released = False

    @property
    def length(self) -> int:
        return self._hi - self._lo

    @property
    def was_fallback(self) -> bool:
        return bool(getattr(self._span.pending, "was_fallback", False))

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        view = self._span.pending.wait(timeout)
        lo = min(self._lo, view.nbytes)
        return view[lo:min(self._hi, view.nbytes)]

    def is_ready(self) -> bool:
        return self._span.pending.is_ready()

    def release(self) -> None:
        """Idempotent; the shared span's request frees once every view
        cut from it has released (refcounted — the engine's
        release-waits-if-live contract applies to the last one)."""
        if self._released:
            return
        self._released = True
        self._span.release_one()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class JoinedPieces:
    """Pending-shaped join of one extent's MULTIPLE pieces.

    Pre-tier, an extent ≤ the split size always came back as exactly
    one piece; the host tier's hit/miss splitting (docs/PERF.md §4) can
    return several (one per cache line plus miss runs).  Consumers
    whose shape logic needs ONE view per extent (weight row chunks)
    join them here: ``wait()`` assembles the pieces into one host
    buffer — a host copy, honestly counted as ``bounce_bytes`` — and
    ``release()`` releases every piece.  :func:`join_pieces` returns
    the piece ITSELF when there is only one, so the common case stays
    zero-copy."""

    __slots__ = ("_pieces", "_stats", "_buf", "fh", "offset", "length")

    def __init__(self, pieces, stats=None):
        self._pieces = list(pieces)
        self._stats = stats
        self._buf: Optional[np.ndarray] = None
        first = self._pieces[0]
        self.fh = first.fh
        self.offset = first.offset
        self.length = sum(p.length for p in self._pieces)

    @property
    def was_fallback(self) -> bool:
        return any(getattr(p, "was_fallback", False)
                   for p in self._pieces)

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if self._buf is None:
            views = [p.wait(timeout).reshape(-1).view(np.uint8)
                     for p in self._pieces]
            self._buf = np.concatenate(views)
            if self._stats is not None:
                self._stats.add(bounce_bytes=int(self._buf.nbytes))
        return self._buf

    def is_ready(self) -> bool:
        return all(p.is_ready() for p in self._pieces)

    def release(self) -> None:
        for p in self._pieces:
            p.release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


def join_pieces(pieces, stats=None):
    """One pending-shaped object for an extent's ordered pieces: the
    single piece itself (zero-copy) or a :class:`JoinedPieces` host
    assembly.  ``pieces`` must be non-empty."""
    if len(pieces) == 1:
        return pieces[0]
    return JoinedPieces(pieces, stats)


#: per-engine-class cache: does this engine's submit_readv accept the
#: ``klass`` keyword?  In-repo engines all do; a foreign/stub wrapper
#: without it still works (the class tag is dropped, traffic rides the
#: scheduler's default class if one sits below).
_READV_KLASS: dict = {}


def _readv_accepts_klass(engine) -> bool:
    t = type(engine)
    ok = _READV_KLASS.get(t)
    if ok is None:
        import inspect
        try:
            params = inspect.signature(engine.submit_readv).parameters
            ok = "klass" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values())
        except (TypeError, ValueError):
            ok = False
        _READV_KLASS[t] = ok
    return ok


def submit_spans(engine, spans: Sequence[Tuple[int, int, int]],
                 klass: Optional[str] = None) -> list:
    """Submit planned spans through the engine's vectored path when it
    has one (StromEngine/Resilient/Faulty all do), else per-span —
    returns pending reads aligned with ``spans``.  All-or-nothing
    either way: the C path validates atomically, and the per-span
    fallback releases already-submitted reads before re-raising, so a
    mid-list failure never strands staging buffers.

    ``klass`` tags the batch's latency class (io/sched.py: ``decode`` >
    ``restore`` > ``prefetch`` > ``scan`` > ``scrub``); on a sharded
    engine the QoS
    scheduler dispatches accordingly, and the resilience layer applies
    that class's hedge/retry budgets.  None rides the default class.

    Failure-domain fallback (io/health.py, docs/RESILIENCE.md): when
    the engine's supervisor reports the DEVICE degraded (every ring
    breaker open, or the error budget blown across domains), the batch
    is served as plain synchronous buffered preads instead — bypassing
    the engine, the scheduler, AND any Faulty/Resilient wrapper above
    it, exactly like host-cache hits do — so serving browns out at
    reduced bandwidth instead of blacking out.  One half-open probe
    per interval rides the real path; its success restores the fast
    path for the very batch that probed."""
    sup = getattr(engine, "supervisor", None)
    if sup is not None:
        sup.tick()
        if sup.degraded():
            out = sup.serve_degraded(engine, spans)
            if out is not None:
                return out      # still degraded (None = probe healed)
    readv = getattr(engine, "submit_readv", None)
    if readv is not None:
        if klass is not None and _readv_accepts_klass(engine):
            return readv(spans, klass=klass)
        return readv(spans)
    out: list = []
    try:
        for fh, off, ln in spans:
            out.append(engine.submit_read(fh, off, ln))
    except BaseException:
        for p in out:
            p.release()
        raise
    return out


def plan_and_submit(engine, extents: Sequence[Tuple[int, int, int]], *,
                    gap: Optional[int] = None, split_unit: int = 1,
                    chunk_bytes: Optional[int] = None,
                    klass: Optional[str] = None, hot: bool = False
                    ) -> List[List[SpanView]]:
    """Plan ``(fh, offset, length)`` extents, submit the spans as ONE
    batch, and return — aligned with the input — each extent's ordered
    list of :class:`SpanView` pieces (one piece unless the extent was
    split; empty list for zero-length extents).

    The split size defaults to the ledger-tuned chunk
    (``utils.tuning.tuned_chunk_bytes``); pass ``chunk_bytes`` to pin
    it (must be ≤ the engine's staging capacity).  Coalescing counts
    into ``StromStats.spans_coalesced``.

    ``klass`` is the batch's latency class (see :func:`submit_spans`) —
    the one knob consumers use to tag their traffic for the QoS
    scheduler and the per-class resilience budgets.

    When the pinned-host tier is on (``STROM_HOSTCACHE_MB``,
    io/hostcache.py) each extent is first split into HIT spans — served
    as zero-copy views over resident cache lines, bypassing the engine
    (and any Faulty/Resilient wrapper) entirely — and MISS spans, which
    ride the planner/scheduler exactly as below and fill the cache on
    completion behind the admission gate.  Record-unit-pinned plans
    (``split_unit > 1``) bypass the tier: line boundaries cannot
    guarantee unit-aligned pieces.

    ``hot`` declares the batch latency-critical REPEAT traffic (the KV
    prefix store's page restores): tier lines it touches are admitted
    on first miss (no ghost round) and pinned sticky under the class's
    residency quota — hot prefix pages ride DRAM on the next restore
    instead of rotating out behind a bulk scan (docs/PERF.md §5).  With
    the tier off it changes nothing.
    """
    if chunk_bytes is None:
        from nvme_strom_tpu.utils.tuning import tuned_chunk_bytes
        chunk_bytes = tuned_chunk_bytes(engine)
    if split_unit == 1:
        from nvme_strom_tpu.io import hostcache
        cache = hostcache.get_cache(engine)
        if cache is not None:
            return _plan_and_submit_tiered(cache, engine, extents,
                                           gap=gap,
                                           chunk_bytes=chunk_bytes,
                                           klass=klass, hot=hot)
    plan = plan_extents(extents, chunk_bytes=chunk_bytes, gap=gap,
                        split_unit=split_unit)
    pendings = submit_spans(engine, plan.spans, klass=klass)
    shared = _share_spans(pendings, plan.placements)
    out = [_views_for(shared, pieces, fh, off)
           for (fh, off, _ln), pieces in zip(extents, plan.placements)]
    stats = getattr(engine, "stats", None)
    if stats is not None and plan.spans_coalesced:
        stats.add(spans_coalesced=plan.spans_coalesced)
    if stats is not None and plan.gap_bytes:
        from nvme_strom_tpu.obs.ledger import charge_waste
        charge_waste(stats, "coalesce_gap", plan.gap_bytes)
    return out


def _fill_keys_for_span(cache, fkey, admitted: dict, s_off: int,
                        s_ln: int) -> dict:
    """Admitted line keys (→ admission epoch) whose fill data this
    span's completion can provide (line starts covered from their
    beginning)."""
    lb = cache.line_bytes
    start = s_off if s_off % lb == 0 else s_off - s_off % lb + lb
    return {(fkey, lo): admitted[(fkey, lo)]
            for lo in range(start, s_off + s_ln, lb)
            if (fkey, lo) in admitted}


def _share_spans(pendings, placements) -> list:
    """Refcount each submitted span by the pieces cut from it — the
    release unit both submit paths share (the span's request frees when
    the LAST view does)."""
    refs = [0] * len(pendings)
    for pieces in placements:
        for si, _, _ in pieces:
            refs[si] += 1
    return [_SharedSpan(p, max(1, r)) for p, r in zip(pendings, refs)]


def _views_for(shared, pieces, fh: int, start_off: int) -> list:
    """One placement's ordered pieces → SpanViews (offsets advance from
    ``start_off`` piece by piece)."""
    views = []
    pos = 0
    for si, lo, hi in pieces:
        views.append(SpanView(shared[si], lo, hi, fh, start_off + pos))
        pos += hi - lo
    return views


def _plan_and_submit_tiered(cache, engine, extents, *, gap, chunk_bytes,
                            klass, hot: bool = False
                            ) -> List[List[SpanView]]:
    """The host-tier path of :func:`plan_and_submit`: probe each extent
    against the cache, serve hit spans as pinned zero-copy line views,
    plan+submit only the miss spans (which fill admitted lines when
    they complete)."""
    from nvme_strom_tpu.io.hostcache import (CacheHitRead, _FillOnWait,
                                             file_key_of)
    stats = getattr(engine, "stats", None)
    tracer = getattr(engine, "tracer", None)
    if tracer is not None and not tracer.enabled:
        tracer = None
    for i, (_fh, _off, ln) in enumerate(extents):
        if ln < 0:   # validate BEFORE probing: probes pin cache lines
            raise ValueError(f"extent {i}: negative length {ln}")
    fkeys: dict = {}
    segs_all: List[list] = []
    miss_exts: List[Tuple[int, int, int]] = []
    admitted: dict = {}      # line key → admission-time epoch
    for fh, off, ln in extents:
        if ln == 0:
            segs_all.append([])
            continue
        if fh not in fkeys:
            fkeys[fh] = file_key_of(engine, fh)
        fkey = fkeys[fh]
        if fkey is None:
            segs = [("miss", off, ln)]
        else:
            segs, adm = cache.probe_range(fkey, off, ln, klass, stats,
                                          hot=hot)
            admitted.update(adm)
        segs_all.append(segs)
        for s in segs:
            if s[0] == "miss":
                miss_exts.append((fh, s[1], s[2]))
    try:
        plan = plan_extents(miss_exts, chunk_bytes=chunk_bytes, gap=gap)
        pendings = submit_spans(engine, plan.spans, klass=klass)
    except BaseException:
        for segs in segs_all:       # pinned hits must not leak
            for s in segs:
                if s[0] == "hit":
                    cache.unpin(s[3])
        raise
    wrapped = []
    for (fh, s_off, s_ln), p in zip(plan.spans, pendings):
        fkey = fkeys.get(fh)
        keys = (_fill_keys_for_span(cache, fkey, admitted, s_off, s_ln)
                if fkey is not None and admitted else {})
        wrapped.append(_FillOnWait(p, cache, fkey, s_off, keys, klass,
                                   stats, sticky=hot, tracer=tracer)
                       if keys else p)
    shared = _share_spans(wrapped, plan.placements)
    out: List[List[SpanView]] = []
    hit_bytes = hit_count = 0
    mi = 0
    for (fh, _off, ln), segs in zip(extents, segs_all):
        pieces_out: list = []
        for s in segs:
            if s[0] == "hit":
                _, a, sl, line = s
                rel = a - line.key[1]
                pieces_out.append(CacheHitRead(cache, line, rel,
                                               rel + sl, fh, a))
                hit_bytes += sl
                hit_count += 1
            else:
                _, a, _sl = s
                pieces_out.extend(_views_for(shared,
                                             plan.placements[mi], fh, a))
                mi += 1
        out.append(pieces_out)
    if tracer is not None and hit_count:
        # one aggregate span per probed batch (per-line spans would
        # dominate the trace on a hot run): the DRAM-served portion of
        # this batch, causally under the requester
        import time as _time
        now = _time.monotonic_ns()
        tracer.add_span("strom.cache.hit", now, now,
                        category="strom.cache", klass=klass,
                        hits=hit_count, bytes=hit_bytes)
    if stats is not None and plan.spans_coalesced:
        stats.add(spans_coalesced=plan.spans_coalesced)
    if stats is not None and plan.gap_bytes:
        from nvme_strom_tpu.obs.ledger import charge_waste
        charge_waste(stats, "coalesce_gap", plan.gap_bytes)
    return out


def submit_spans_tiered(engine, spans: Sequence[Tuple[int, int, int]],
                        klass: Optional[str] = None) -> list:
    """:func:`submit_spans` with the pinned-host tier in front: spans
    fully resident in ONE cache line return as ready zero-copy cache
    views (no engine submission, no retry/hedge), the rest submit as
    one vectored batch exactly like :func:`submit_spans` — and fill
    admitted lines when they complete.  This is the refill primitive of
    ``DeviceStream.stream_ranges``, which is how kv_offload/opt_offload/
    pq_direct streams get the tier; with the tier off it IS
    ``submit_spans``."""
    from nvme_strom_tpu.io import hostcache
    cache = hostcache.get_cache(engine)
    if cache is None:
        return submit_spans(engine, spans, klass=klass)
    from nvme_strom_tpu.io.hostcache import (CacheHitRead, _FillOnWait,
                                             file_key_of)
    stats = getattr(engine, "stats", None)
    tracer = getattr(engine, "tracer", None)
    if tracer is not None and not tracer.enabled:
        tracer = None
    spans = list(spans)
    out: list = [None] * len(spans)
    miss: list = []
    meta: list = []    # (out index, fkey, admitted keys)
    fkeys: dict = {}
    hit_bytes = hit_count = 0
    for i, (fh, off, ln) in enumerate(spans):
        if fh not in fkeys:
            fkeys[fh] = file_key_of(engine, fh)
        fkey = fkeys[fh]
        line = None
        adm: dict = {}
        if fkey is not None and ln > 0:
            line, adm = cache.probe_span(fkey, off, ln, klass, stats)
        if line is not None:
            rel = off - line.key[1]
            out[i] = CacheHitRead(cache, line, rel, rel + ln, fh, off)
            hit_bytes += ln
            hit_count += 1
        else:
            miss.append((fh, off, ln))
            meta.append((i, fkey, adm))
    if tracer is not None and hit_count:
        import time as _time
        now = _time.monotonic_ns()
        tracer.add_span("strom.cache.hit", now, now,
                        category="strom.cache", klass=klass,
                        hits=hit_count, bytes=hit_bytes)
    try:
        pendings = submit_spans(engine, miss, klass=klass)
    except BaseException:
        for p in out:
            if p is not None:
                p.release()
        raise
    for (i, fkey, adm), p in zip(meta, pendings):
        fh, off, ln = spans[i]
        keys = (_fill_keys_for_span(cache, fkey, adm, off, ln)
                if fkey is not None and adm else {})
        out[i] = _FillOnWait(p, cache, fkey, off, keys, klass,
                             stats, tracer=tracer) if keys else p
    return out
