"""Extent-coalescing I/O planner — the shared read-plan layer between
consumers and the engine's vectored submit.

The reference amortizes per-request overhead by carrying MANY chunks in
one MEMCPY_SSD2GPU command (SURVEY.md §3.1); before this module every
consumer crossed Python→ctypes→``io_uring_enter`` once per extent and
hand-rolled its own chunk-split loop.  The planner is the one place
both problems are solved:

  coalesce   extents that are adjacent — or separated by at most
             ``STROM_COALESCE_GAP`` bytes (default one 4 KiB block) —
             on the SAME file merge into one larger O_DIRECT read.
             Consumers get zero-copy SUB-VIEWS of the completed span
             buffer (legal because the engine already returns offset
             views instead of memcpy'ing: slicing a numpy view costs
             nothing).  Overlapping/duplicate extents dedupe into one
             read the same way.  Cross-file extents never coalesce.
  split      extents larger than the split size (the ledger-tuned
             chunk from ``utils/tuning.tuned_chunk_bytes``, capped at
             the engine's staging-buffer capacity) break into pieces —
             replacing the near-identical hard-coded loops each
             consumer carried.  ``split_unit`` keeps piece boundaries
             on record boundaries (fixedrec) — pieces of one extent
             are always multiples of the unit from the extent's start.
  batch      the resulting spans submit through the engine's
             ``submit_readv`` (ONE C call, ONE ``io_uring_enter``
             doorbell) when available, falling back to per-span
             ``submit_read`` for engine wrappers that predate it.

Accounting: every merged extent counts ``StromStats.spans_coalesced``;
the C engine counts ``submit_batches`` / ``submit_syscalls_saved`` at
the vectored boundary.  ``bench.py`` reports the resulting coalesce
ratio and syscalls/GiB next to the throughput headline; thresholds and
semantics are documented in docs/PERF.md.

The planner composes with the resilience stack unchanged: a
``ResilientEngine`` submits the batch through the wrapped engine and
wraps EACH span in its own recovery loop (a failed span retries alone,
never the whole batch), and ``FaultyEngine`` injects per-span faults
into the vectored path (docs/RESILIENCE.md).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

#: default coalesce-gap: one O_DIRECT logical block — reading one
#: wasted block is cheaper than a second NVMe round trip, and tar's
#: 512 B inter-member headers / the offload file's slot padding both
#: fall under it
DEFAULT_COALESCE_GAP = 4096


def coalesce_gap() -> int:
    """Gap threshold in bytes (env ``STROM_COALESCE_GAP``; default one
    4 KiB block).  0 disables coalescing across gaps (adjacent and
    overlapping extents still merge); negative values clamp to 0."""
    try:
        return max(0, int(os.environ.get("STROM_COALESCE_GAP",
                                         DEFAULT_COALESCE_GAP)))
    except ValueError:
        return DEFAULT_COALESCE_GAP


def split_spans(spans, chunk: int):
    """(offset, length) spans → (flat sub-ranges ≤ ``chunk``, per-span
    sub-range counts).  The one splitting rule every chunk-bound
    consumer shares (engine reads are capped at chunk_bytes);
    zero-length spans contribute zero sub-ranges but keep their count
    entry so group boundaries stay aligned.  (Formerly
    ``ops.bridge.split_ranges``, which now delegates here.)"""
    flat, counts = [], []
    for off, ln in spans:
        before = len(flat)
        while ln > 0:
            take = min(chunk, ln)
            flat.append((off, take))
            off += take
            ln -= take
        counts.append(len(flat) - before)
    return flat, counts


@dataclass(frozen=True)
class ExtentPlan:
    """The pure (side-effect-free) plan: which engine reads to submit
    and where each input extent's bytes land in them.

    ``spans``       (fh, offset, length) engine reads, each ≤ the split
                    size, in submission order.
    ``placements``  per input extent (input order), the ordered pieces
                    covering it: (span_index, lo, hi) byte ranges
                    RELATIVE to that span's completed view.  Zero-
                    length extents get an empty piece list.
    ``spans_coalesced``  input extents that merged into a span opened
                    by an earlier extent (k-extent merge counts k-1).
    """

    spans: List[Tuple[int, int, int]]
    placements: List[List[Tuple[int, int, int]]]
    spans_coalesced: int
    n_extents: int

    @property
    def submits_saved(self) -> int:
        """Engine submissions a per-extent caller would have made minus
        what this plan makes (coalescing net of splitting)."""
        return self.n_extents - len(self.spans)


def plan_extents(extents: Sequence[Tuple[int, int, int]], *,
                 chunk_bytes: int, gap: Optional[int] = None,
                 split_unit: int = 1) -> ExtentPlan:
    """Sort + coalesce + split ``(fh, offset, length)`` extents.

    ``chunk_bytes``: max bytes of one engine read (≤ the engine's
    staging-buffer capacity).  ``gap``: max bytes of dead space to read
    through when merging (None = env/default via :func:`coalesce_gap`).
    ``split_unit``: piece boundaries of a SPLIT extent stay multiples
    of this from the extent's start (record size for fixedrec); a
    merged span is never split, so sub-views inside it keep exact
    byte placement regardless of the unit.
    """
    if gap is None:
        gap = coalesce_gap()
    if split_unit <= 0:
        raise ValueError(f"split_unit must be >= 1, got {split_unit}")
    split = (chunk_bytes // split_unit) * split_unit
    if split <= 0:
        raise ValueError(
            f"split_unit ({split_unit}) exceeds chunk_bytes "
            f"({chunk_bytes}); raise EngineConfig.chunk_bytes")
    n = len(extents)
    placements: List[List[Tuple[int, int, int]]] = [[] for _ in range(n)]
    spans: List[Tuple[int, int, int]] = []
    coalesced = 0

    for i in range(n):
        if extents[i][2] < 0:
            raise ValueError(f"extent {i}: negative length "
                             f"{extents[i][2]}")
    order = sorted((i for i in range(n) if extents[i][2] > 0),
                   key=lambda i: (extents[i][0], extents[i][1],
                                  extents[i][2]))

    def emit(group: list) -> None:
        """One coalesced group → spans + placements.  Multi-extent
        groups fit one span by construction; a lone oversized extent
        splits at unit-aligned piece boundaries."""
        nonlocal coalesced
        fh = extents[group[0]][0]
        start = extents[group[0]][1]
        end = max(extents[i][1] + extents[i][2] for i in group)
        length = end - start
        if length <= split:
            si = len(spans)
            spans.append((fh, start, length))
            for i in group:
                off, ln = extents[i][1], extents[i][2]
                placements[i].append((si, off - start, off - start + ln))
            coalesced += len(group) - 1
            return
        # lone oversized extent: piece k covers [start + k*split, ...)
        assert len(group) == 1
        i = group[0]
        pos = 0
        while pos < length:
            take = min(split, length - pos)
            si = len(spans)
            spans.append((fh, start + pos, take))
            placements[i].append((si, 0, take))
            pos += take

    group: list = []
    g_fh = g_start = g_end = 0
    for i in order:
        fh, off, ln = extents[i]
        if group and fh == g_fh and off <= g_end + gap \
                and max(g_end, off + ln) - g_start <= split:
            group.append(i)
            g_end = max(g_end, off + ln)
            continue
        if group:
            emit(group)
        group = [i]
        g_fh, g_start, g_end = fh, off, off + ln
    if group:
        emit(group)
    return ExtentPlan(spans=spans, placements=placements,
                      spans_coalesced=coalesced, n_extents=n)


class _SharedSpan:
    """One submitted span read, shared by every sub-view cut from it.
    The underlying request releases when the LAST view releases."""

    __slots__ = ("pending", "_refs")

    def __init__(self, pending, refs: int):
        self.pending = pending
        self._refs = refs

    def release_one(self) -> None:
        self._refs -= 1
        if self._refs <= 0:
            self.pending.release()


_EMPTY = np.empty(0, dtype=np.uint8)


class SpanView:
    """PendingRead-shaped zero-copy sub-view of a (possibly coalesced)
    span read.

    ``wait()`` returns ``span_view[lo:hi]`` — a numpy slice of the
    engine's staging buffer, no copy; validity follows the span's
    buffer (until every view of the span releases).  ``length``/
    ``fh``/``offset`` describe THIS piece, so ``wait_exact`` reports
    name the exact range.  A span completing short (EOF/device short
    read) surfaces here as a short sub-view, which ``wait_exact``
    turns into the loud OSError.  Piece of a zero-length extent:
    ``lo == hi``, waits to an empty view without any I/O dependency
    beyond its span.
    """

    __slots__ = ("_span", "_lo", "_hi", "fh", "offset", "_released")

    def __init__(self, span: _SharedSpan, lo: int, hi: int,
                 fh: int, offset: int):
        self._span = span
        self._lo = lo
        self._hi = hi
        self.fh = fh
        self.offset = offset
        self._released = False

    @property
    def length(self) -> int:
        return self._hi - self._lo

    @property
    def was_fallback(self) -> bool:
        return bool(getattr(self._span.pending, "was_fallback", False))

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        view = self._span.pending.wait(timeout)
        lo = min(self._lo, view.nbytes)
        return view[lo:min(self._hi, view.nbytes)]

    def is_ready(self) -> bool:
        return self._span.pending.is_ready()

    def release(self) -> None:
        """Idempotent; the shared span's request frees once every view
        cut from it has released (refcounted — the engine's
        release-waits-if-live contract applies to the last one)."""
        if self._released:
            return
        self._released = True
        self._span.release_one()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


#: per-engine-class cache: does this engine's submit_readv accept the
#: ``klass`` keyword?  In-repo engines all do; a foreign/stub wrapper
#: without it still works (the class tag is dropped, traffic rides the
#: scheduler's default class if one sits below).
_READV_KLASS: dict = {}


def _readv_accepts_klass(engine) -> bool:
    t = type(engine)
    ok = _READV_KLASS.get(t)
    if ok is None:
        import inspect
        try:
            params = inspect.signature(engine.submit_readv).parameters
            ok = "klass" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values())
        except (TypeError, ValueError):
            ok = False
        _READV_KLASS[t] = ok
    return ok


def submit_spans(engine, spans: Sequence[Tuple[int, int, int]],
                 klass: Optional[str] = None) -> list:
    """Submit planned spans through the engine's vectored path when it
    has one (StromEngine/Resilient/Faulty all do), else per-span —
    returns pending reads aligned with ``spans``.  All-or-nothing
    either way: the C path validates atomically, and the per-span
    fallback releases already-submitted reads before re-raising, so a
    mid-list failure never strands staging buffers.

    ``klass`` tags the batch's latency class (io/sched.py: ``decode`` >
    ``restore`` > ``prefetch`` > ``scrub``); on a sharded engine the QoS
    scheduler dispatches accordingly, and the resilience layer applies
    that class's hedge/retry budgets.  None rides the default class."""
    readv = getattr(engine, "submit_readv", None)
    if readv is not None:
        if klass is not None and _readv_accepts_klass(engine):
            return readv(spans, klass=klass)
        return readv(spans)
    out: list = []
    try:
        for fh, off, ln in spans:
            out.append(engine.submit_read(fh, off, ln))
    except BaseException:
        for p in out:
            p.release()
        raise
    return out


def plan_and_submit(engine, extents: Sequence[Tuple[int, int, int]], *,
                    gap: Optional[int] = None, split_unit: int = 1,
                    chunk_bytes: Optional[int] = None,
                    klass: Optional[str] = None
                    ) -> List[List[SpanView]]:
    """Plan ``(fh, offset, length)`` extents, submit the spans as ONE
    batch, and return — aligned with the input — each extent's ordered
    list of :class:`SpanView` pieces (one piece unless the extent was
    split; empty list for zero-length extents).

    The split size defaults to the ledger-tuned chunk
    (``utils.tuning.tuned_chunk_bytes``); pass ``chunk_bytes`` to pin
    it (must be ≤ the engine's staging capacity).  Coalescing counts
    into ``StromStats.spans_coalesced``.

    ``klass`` is the batch's latency class (see :func:`submit_spans`) —
    the one knob consumers use to tag their traffic for the QoS
    scheduler and the per-class resilience budgets.
    """
    if chunk_bytes is None:
        from nvme_strom_tpu.utils.tuning import tuned_chunk_bytes
        chunk_bytes = tuned_chunk_bytes(engine)
    plan = plan_extents(extents, chunk_bytes=chunk_bytes, gap=gap,
                        split_unit=split_unit)
    pendings = submit_spans(engine, plan.spans, klass=klass)
    refs = [0] * len(pendings)
    for pieces in plan.placements:
        for si, _, _ in pieces:
            refs[si] += 1
    shared = [_SharedSpan(p, max(1, r))
              for p, r in zip(pendings, refs)]
    out: List[List[SpanView]] = []
    for (fh, off, _ln), pieces in zip(extents, plan.placements):
        views = []
        pos = 0
        for si, lo, hi in pieces:
            views.append(SpanView(shared[si], lo, hi, fh, off + pos))
            pos += hi - lo
        out.append(views)
    stats = getattr(engine, "stats", None)
    if stats is not None and plan.spans_coalesced:
        stats.add(spans_coalesced=plan.spans_coalesced)
    return out
