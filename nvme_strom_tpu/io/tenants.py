"""Tenant identity and isolation primitives — fleet-grade multi-tenancy.

PRs 7-10 built single-process QoS (latency classes, host-tier quotas,
failure domains) for ONE well-behaved client.  Production serving is
many tenants on one box: a single tenant's prompt storm used to evict
every other tenant's hot cache lines, flood the shared admission queue,
and drag every tenant's p99 down together.  This module carries the
identity that makes isolation enforceable:

  Tenant        descriptor (id, SLO tier, fair-share weight, residency
                quota fraction, admission rate) every serving request
                and I/O batch can carry.
  TIER_ORDER    SLO tiers, best first — ``gold`` > ``silver`` >
                ``bronze``.  Under overload the admission path sheds
                worst tier first (models/serving.py), so a bronze storm
                defers while gold admits.
  tenant_context / current_tenant
                contextvar propagation: the serving layer enters a
                request's tenant scope once and every layer below —
                the QoS scheduler (io/sched.py stamps batches at
                enqueue), the host cache (io/hostcache.py stamps lines
                at fill), the KV prefix store (models/kv_offload.py
                stamps pages at put) — reads it without signature
                changes, exactly like trace contexts.
  TenantRegistry
                the process's tenant table, parsed from
                ``STROM_TENANT_SPEC`` and extended on first sight of an
                unknown id with the ``STROM_TENANT_*`` defaults.  Reads
                are lock-free dict lookups (the serving hot path);
                only registration mutates under the lock.
  TokenBucket   per-tenant admission rate limiting (tokens/s + burst,
                injectable clock so tests drive time).

Everything is inert while ``STROM_TENANTS=0`` (the default): the
serving layer never enters a tenant scope, ``current_tenant()`` stays
None everywhere, and every consumer's tenant branch short-circuits to
the exact pre-tenant code path (tests/test_tenants.py proves
bit-for-bit equality).  Semantics: docs/RESILIENCE.md "Multi-tenant
isolation".
"""

from __future__ import annotations

import contextlib
import time
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Dict, List, Optional

from nvme_strom_tpu.utils.lockwitness import make_lock

#: SLO tiers, best first — admission sheds from the BACK of this list
#: under overload (docs/RESILIENCE.md "Multi-tenant isolation")
TIER_ORDER = ("gold", "silver", "bronze")

#: tier of a tenant that never declared one
DEFAULT_TIER = "silver"


def tier_rank(tier: str) -> int:
    """Position in TIER_ORDER (lower = better); unknown tiers rank
    worst so a typo can never outrank a declared gold tenant."""
    try:
        return TIER_ORDER.index(tier)
    except ValueError:
        return len(TIER_ORDER)


@dataclass
class Tenant:
    """One tenant's isolation policy (mutable: the SLO governor adjusts
    ``share_boost`` at runtime; everything else is configuration).

    ``weight``      hierarchical fair-share weight inside each QoS
                    class (io/sched.py): under contention tenants split
                    a class's grants by weight ratio; the aging bound
                    still guarantees no batch starves at any weight.
    ``quota_frac``  residency quota as a fraction of the host-cache
                    arena / KV prefix store (0 = fair share, 1/N of the
                    tenants seen).  Borrowing free space past the quota
                    is allowed; pressure reclaims over-quota tenants
                    first, so a storm pays for itself.
    ``rate``/``burst``
                    admission token bucket (requests/s, burst depth);
                    rate 0 = unlimited.
    ``slo_p99_ms``  per-tenant decode TTFT p99 target; violations boost
                    only THIS tenant's scheduler share (``share_boost``
                    notches), never the device-global hedge budget.
    """

    id: str
    tier: str = DEFAULT_TIER
    weight: float = 1.0
    quota_frac: float = 0.0
    rate: float = 0.0
    burst: float = 0.0
    slo_p99_ms: float = 0.0
    share_boost: int = 0

    def __post_init__(self):
        if not self.id:
            raise ValueError("tenant id must be non-empty")
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.id!r}: weight ({self.weight}) must be "
                f"> 0 (the aging bound protects weight-1 tenants; 0 "
                f"would starve without it)")
        if not 0.0 <= self.quota_frac <= 1.0:
            raise ValueError(
                f"tenant {self.id!r}: quota_frac ({self.quota_frac}) "
                f"must be in [0, 1]")
        if self.rate < 0 or self.burst < 0:
            raise ValueError(
                f"tenant {self.id!r}: rate/burst must be >= 0")
        if self.slo_p99_ms < 0:
            raise ValueError(
                f"tenant {self.id!r}: slo_p99_ms must be >= 0")

    @property
    def effective_weight(self) -> float:
        """Fair-share weight including the SLO governor's boost."""
        return self.weight * (1 + self.share_boost)


def parse_tenant_spec(spec: str) -> Dict[str, Tenant]:
    """Parse ``STROM_TENANT_SPEC``: ``;``-separated tenants, each
    ``<id>[:key=value,...]`` with keys ``tier``/``weight``/``quota``/
    ``rate``/``burst``/``slo_ms``.  Example::

        gold_t:tier=gold,weight=8,quota=0.5,slo_ms=50;batch:tier=bronze,weight=1,rate=10

    Raises ValueError on malformed entries (config-time, loudly)."""
    out: Dict[str, Tenant] = {}
    for part in filter(None, (s.strip() for s in spec.split(";"))):
        tid, _colon, body = part.partition(":")
        tid = tid.strip()
        kw: Dict[str, object] = {}
        for item in filter(None, (s.strip() for s in body.split(","))):
            key, eq, val = item.partition("=")
            key = key.strip()
            if not eq:
                raise ValueError(
                    f"STROM_TENANT_SPEC entry {item!r}: expected "
                    f"key=value")
            if key == "tier":
                if val not in TIER_ORDER:
                    raise ValueError(
                        f"STROM_TENANT_SPEC tenant {tid!r}: tier "
                        f"{val!r} not in {TIER_ORDER}")
                kw["tier"] = val
            elif key in ("weight", "quota", "rate", "burst", "slo_ms"):
                field = {"quota": "quota_frac",
                         "slo_ms": "slo_p99_ms"}.get(key, key)
                kw[field] = float(val)
            else:
                raise ValueError(
                    f"STROM_TENANT_SPEC tenant {tid!r}: unknown key "
                    f"{key!r}")
        if tid in out:
            raise ValueError(
                f"STROM_TENANT_SPEC: duplicate tenant id {tid!r}")
        out[tid] = Tenant(tid, **kw)   # Tenant validates
    return out


class TokenBucket:
    """Admission rate limiter: ``rate`` tokens/s refill up to ``burst``.

    ``rate <= 0`` means unlimited (every take succeeds).  ``clock`` is
    injectable so tests drive time deterministically.  Not thread-safe
    by itself — the serving loop takes from ONE thread; the registry
    lock covers creation only."""

    __slots__ = ("rate", "burst", "_tokens", "_t", "_clock")

    def __init__(self, rate: float, burst: float, clock=None):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self._clock = clock if clock is not None else time.monotonic
        self._tokens = self.burst
        self._t = self._clock()

    def try_take(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


class TenantRegistry:
    """The process's tenant table.

    ``get(id)`` is the hot path: a lock-free dict read (dict access is
    atomic under the GIL; the dict is replaced, never mutated in place,
    on registration) — the serving loop resolves a tenant per submit
    and the scheduler reads ``effective_weight`` per grant, neither may
    contend.  Unknown ids register on first sight with the
    ``STROM_TENANT_*`` default rate/burst/quota, so a replayed trace
    with thousands of tenant ids never needs a spec entry each."""

    def __init__(self, config=None):
        if config is None:
            from nvme_strom_tpu.utils.config import TenantConfig
            config = TenantConfig()
        self.config = config
        self._lock = make_lock("tenants.TenantRegistry._lock")
        self._tenants: Dict[str, Tenant] = dict(
            parse_tenant_spec(config.spec))

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    def get(self, tid) -> Tenant:
        """Resolve (and lazily register) a tenant by id; a Tenant
        passes through so callers accept either form."""
        if isinstance(tid, Tenant):
            return tid
        tid = str(tid)
        t = self._tenants.get(tid)          # lock-free fast path
        if t is not None:
            return t
        with self._lock:
            t = self._tenants.get(tid)
            if t is None:
                cfg = self.config
                t = Tenant(tid, rate=cfg.default_rate,
                           burst=cfg.default_burst,
                           quota_frac=cfg.default_quota_frac)
                # replace, never mutate: readers hold no lock
                nxt = dict(self._tenants)
                nxt[tid] = t
                self._tenants = nxt
            return t

    def lookup(self, tid: str) -> Optional[Tenant]:
        """Read-only resolve: None for unknown ids (no registration)."""
        return self._tenants.get(str(tid))

    def tenants(self) -> List[Tenant]:
        return list(self._tenants.values())

    # -- drain & handoff (io/handoff.py) ----------------------------------

    def export_state(self) -> Dict[str, dict]:
        """Per-tenant MUTABLE state worth shipping to a replacement
        replica: today that is the SLO governor's share_boost notches
        (the spec itself travels via STROM_TENANT_SPEC, not the
        bundle).  Zero-boost tenants are omitted — nothing to restore."""
        out: Dict[str, dict] = {}
        for t in self._tenants.values():
            if t.share_boost:
                out[t.id] = {"share_boost": int(t.share_boost)}
        return out

    def restore_state(self, state: Dict[str, dict]) -> int:
        """Re-apply shipped per-tenant state (bounded exactly as the
        governor bounds live boosts) so isolation pressure survives a
        replacement instead of resetting to zero.  Returns tenants
        touched; malformed entries are skipped — a handoff bundle is
        advisory, never load-bearing."""
        from nvme_strom_tpu.models.kv_offload import SloGovernor
        cap = getattr(SloGovernor, "_MAX_BOOST", 3)
        n = 0
        for tid, st in (state or {}).items():
            try:
                boost = int(st.get("share_boost", 0))
            except (AttributeError, TypeError, ValueError):
                continue
            if boost < 1:
                continue
            t = self.get(tid)
            t.share_boost = max(t.share_boost, min(boost, cap))
            n += 1
        return n


# ---------------------------------------------------------------------------
# contextvar propagation (the trace-context pattern)
# ---------------------------------------------------------------------------

_current: ContextVar[Optional[Tenant]] = ContextVar(
    "strom_tenant", default=None)


def current_tenant() -> Optional[Tenant]:
    """The tenant the running code is working for (None outside any
    tenant scope — every consumer's None branch is the exact pre-tenant
    code path)."""
    return _current.get()


@contextlib.contextmanager
def tenant_context(tenant: Optional[Tenant]):
    """Enter ``tenant``'s scope: batches the QoS scheduler enqueues,
    lines the host cache fills, and pages the prefix store puts inside
    the scope are attributed (and quota-charged) to it."""
    token = _current.set(tenant)
    try:
        yield tenant
    finally:
        _current.reset(token)


# ---------------------------------------------------------------------------
# module singleton — ONE registry per process (mirrors hostcache's)
# ---------------------------------------------------------------------------

_registry: Optional[TenantRegistry] = None
_registry_lock = make_lock("tenants._registry_lock")


def get_registry() -> TenantRegistry:
    """The process-wide registry, built from the environment on first
    use (``configure`` overrides; ``reset`` drops it)."""
    global _registry
    r = _registry
    if r is not None:
        return r
    with _registry_lock:
        if _registry is None:
            _registry = TenantRegistry()
        return _registry


def configure(config) -> TenantRegistry:
    """Install a registry built from an explicit TenantConfig
    (tests/bench); returns it."""
    global _registry
    with _registry_lock:
        _registry = TenantRegistry(config)
        return _registry


def reset() -> None:
    """Drop the singleton (tests) — the next get_registry() re-reads
    the environment."""
    global _registry
    with _registry_lock:
        _registry = None


def tenants_enabled() -> bool:
    """Master gate: True only when STROM_TENANTS=1 (or an explicit
    configure() with enabled=True).  EVERY entry point that would set a
    tenant scope checks this first, so the default-off stack never sees
    a tenant anywhere."""
    return get_registry().enabled
