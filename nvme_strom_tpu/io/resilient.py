"""ResilientEngine — turn every detected I/O failure into a recovery.

The stack already *detects* failures (``wait(timeout=...)`` in
io/engine.py, the step watchdog in utils/watchdog.py); before this
module one bad read killed the run.  ``ResilientEngine`` wraps any
engine-shaped object (StromEngine, or FaultyEngine for chaos runs) and
gives its reads three recovery mechanisms (knobs: ResilientConfig in
utils/config.py; semantics: docs/RESILIENCE.md):

  retry    a read that completes with an error, or returns fewer bytes
           than the file holds, is released and resubmitted with
           exponential backoff + deterministic jitter, up to
           ``max_retries`` times; the final failure raises ReadError
           carrying the full per-attempt fault history.
  hedge    a read still in flight past a latency threshold (explicit
           ``hedge_after_s``, or derived from the engine's own log2
           latency histogram: p<hedge_percentile> * hedge_multiplier)
           gets a duplicate submission; whichever completes first wins,
           the loser is released.  Stragglers cost one duplicate read
           instead of a stalled pipeline.
  cancel   a read still in flight after ``stuck_timeout_s`` is presumed
           wedged: it is cancelled (released — safe per the engine's
           release-waits-if-live contract) and resubmitted, counted
           against the same retry budget.

The write path carries the same contract through ``submit_write`` →
:class:`ResilientWrite`: a write that completes with an error is
resubmitted with backoff, a SHORT write resubmits exactly the remaining
span (``data[n:]`` at ``offset+n``), and budget exhaustion raises
``WriteError`` with the full attempt history.  Hedging is deliberately
read-only — duplicate in-flight writes of one range can interleave.

Recovery policy is PER LATENCY CLASS, not process-global: reads tagged
with a class (``submit_read(..., klass=...)`` / ``submit_readv(...,
klass=...)`` — the same tags the QoS scheduler ranks, io/sched.py) run
under that class's ``ResilientConfig`` (``class_configs``) and charge a
per-class CONCURRENT-hedge token budget (``hedge_budgets``): a scrub
storm that exhausts its own hedge quota is denied further hedges
(counted ``hedges_denied``) while the decode class's quota stays
untouched.  Untagged reads keep the engine-wide config and unlimited
legacy hedging (capped, as always, at one hedge per primary).

Every action is accounted (StromStats: resilient_retries, hedges_issued,
hedges_won, hedges_denied, stuck_cancelled, write_retries — plus the
per-class breakdown in ``class_stats``) and traced
(strom.resilient.* spans), so a recovered run shows its scars in
``strom_stat`` instead of hiding them.

The wrapper preserves the engine read contract: ``wait(timeout=...)``
raises TimeoutError with the request still live; ``release()`` frees
both the original and any outstanding hedge; views obey the
valid-until-release rule.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

import numpy as np

from nvme_strom_tpu.io.tenants import current_tenant
from nvme_strom_tpu.utils.config import ResilientConfig
from nvme_strom_tpu.utils.lockwitness import make_lock

#: granularity of the hedged/stuck wait loop: long enough to stay off
#: the hot path (one wake per slice only while a read is *already* a
#: straggler), short enough to notice a hedge winning promptly
_POLL_S = 0.02


class ReadError(OSError):
    """A read that stayed failed after the full retry budget.

    ``attempts`` is the per-attempt fault history: a list of
    ``{"error": str, "kind": str, "elapsed_s": float}`` dicts, oldest
    first — the loud, fully-accounted failure the error budget demands.
    """

    def __init__(self, msg: str, attempts):
        super().__init__(msg)
        self.attempts = list(attempts)


class WriteError(OSError):
    """A write that stayed failed after the full retry budget —
    ReadError's mirror, same ``attempts`` fault-history shape."""

    def __init__(self, msg: str, attempts):
        super().__init__(msg)
        self.attempts = list(attempts)


class _Attempt:
    """One submission of the logical read (original, retry, or hedge)."""

    def __init__(self, pending, t0: float):
        self.pending = pending
        self.t0 = t0


class ResilientRead:
    """The recoverable counterpart of ``PendingRead``.

    Holds (fh, offset, length) so a failed attempt can be resubmitted;
    the underlying PendingRead is replaced across retries, invisibly to
    the caller.
    """

    def __init__(self, engine: "ResilientEngine", fh: int, offset: int,
                 length: int, pending, expected: int,
                 klass: Optional[str] = None):
        self._engine = engine
        self._fh = fh
        self._offset = offset
        self._length = length
        self._expected = expected    # bytes the file actually holds here
        #: latency class: selects this read's ResilientConfig (per-class
        #: retry/backoff/hedge policy) and charges its hedge budget
        self._klass = klass
        self._cfg = engine.config_for(klass)
        #: causal identity for recovery spans (hedge/retry may fire from
        #: a wait() on another thread/context — capture at submit)
        self._ctx = None
        tracer = getattr(engine._engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            from nvme_strom_tpu.utils.trace import current_context
            self._ctx = current_context()
        self._primary = _Attempt(pending, time.monotonic())
        self._hedge: Optional[_Attempt] = None
        self._hedge_token = False    # class hedge-budget token held
        self._hedge_denied = False   # denial counted for this primary
        self._attempts: list = []    # fault history of failed attempts
        self._retries = 0
        self._hedges = 0             # hedges issued for the CURRENT
        # primary: capped at one — a fast-failing hedge must not turn
        # into a resubmission storm against an unhealthy device
        self._view: Optional[np.ndarray] = None
        self._winner = None          # the attempt whose view we returned
        self._released = False
        self.was_fallback = False

    @property
    def length(self) -> int:
        """Bytes requested at submit (PendingRead.length parity)."""
        return self._length

    @property
    def fh(self) -> int:
        return self._fh

    @property
    def offset(self) -> int:
        return self._offset

    # -- the recovery loop -------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        """Blocking wait with retry/hedge/cancel recovery.

        ``timeout`` bounds THIS call (the engine contract): TimeoutError
        means the logical read is still live — recovery continues on the
        next wait; release() aborts it.
        """
        if self._view is not None:
            return self._view
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        cfg = self._cfg
        while True:
            try:
                view = self._wait_attempts(deadline)
            except TimeoutError:
                raise            # caller's bound, read still live
            except OSError as e:
                self._note_failure(e)
                if self._retries >= cfg.max_retries:
                    self._release_attempts()
                    self._released = True
                    raise ReadError(
                        f"read fh={self._fh} off={self._offset} "
                        f"len={self._length} failed after "
                        f"{self._retries + 1} attempts: {e} "
                        f"(history: {self._attempts})",
                        self._attempts) from e
                self._retry(deadline)
                continue
            short = self._expected - view.nbytes
            if short > 0:
                # the short attempt's delivered bytes are discarded and
                # re-read whole by the resubmission — retry-reread waste
                from nvme_strom_tpu.obs.ledger import charge_waste
                charge_waste(self._engine.stats, "retry_reread",
                             int(view.nbytes))
                self._note_failure(OSError(
                    f"short read: {view.nbytes} of {self._expected} "
                    f"bytes"), kind="short")
                if self._retries >= cfg.max_retries:
                    self._release_attempts()
                    self._released = True
                    raise ReadError(
                        f"read fh={self._fh} off={self._offset} "
                        f"len={self._length} still short after "
                        f"{self._retries + 1} attempts "
                        f"(history: {self._attempts})", self._attempts)
                self._retry(deadline)
                continue
            self._view = view
            return view

    def _wait_attempts(self, deadline) -> np.ndarray:
        """Wait for the primary (or a hedge) to produce a view; raises
        OSError on a completed-with-error attempt, TimeoutError only at
        the caller's deadline."""
        eng = self._engine
        cfg = self._cfg
        hedge_after = eng._hedge_after(self._klass)
        while True:
            # primary probe FIRST: a read whose payload already landed
            # must return its view even at timeout=0 (PendingRead.wait
            # parity — engine.is_ready builds on exactly that)
            slice_s = _POLL_S
            if deadline is not None:
                slice_s = min(slice_s,
                              max(0.0, deadline - time.monotonic()))
            try:
                view = self._primary.pending.wait(timeout=slice_s)
            except TimeoutError:
                pass
            else:
                if self._hedge is not None:
                    # primary won the race: the losing hedge hands its
                    # staging buffer back as soon as it lands (deferred
                    # — it may still be in flight, and release() would
                    # block).  Its bytes are the hedge's bandwidth
                    # price — the ledger's hedge-loss waste class.
                    from nvme_strom_tpu.obs.ledger import charge_waste
                    charge_waste(eng.stats, "hedge_loss", self._length)
                    eng._defer_release(self._fh, self._hedge.pending)
                    self._drop_hedge()
                self._winner = self._primary
                self.was_fallback = bool(getattr(
                    self._primary.pending, "was_fallback", False))
                return view
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                raise TimeoutError(
                    f"read fh={self._fh} off={self._offset} still in "
                    f"flight (recovery continues on the next wait)")
            # supervision heartbeat while a read is a straggler: the
            # stall detector and the hot-restart run on exactly the
            # threads that are stuck waiting on the wedged domain
            # (time-gated inside — one monotonic compare per slice)
            sup = eng._supervisor
            if sup is not None:
                sup.tick()
            elapsed = now - self._primary.t0
            # hedge: the primary is a straggler — race ONE duplicate
            if (self._hedge is None and self._hedges == 0
                    and hedge_after is not None
                    and elapsed >= hedge_after):
                self._hedge = self._submit_hedge()
            # stuck: cancel-then-retry (counts against the retry budget)
            if elapsed >= cfg.stuck_timeout_s:
                raise _Stuck(f"request still in flight after "
                             f"{elapsed:.3f}s (stuck_timeout_s="
                             f"{cfg.stuck_timeout_s})")
            if self._hedge is not None and self._hedge.pending.is_ready():
                try:
                    view = self._hedge.pending.wait(timeout=0.0)
                except TimeoutError:
                    pass
                except OSError:
                    # a failed hedge never fails the read — drop it and
                    # keep waiting on the primary (wait() released it)
                    self._drop_hedge()
                else:
                    eng.stats.add(hedges_won=1)
                    # the parked primary's bytes are the losing side of
                    # this race — same hedge-loss waste class
                    from nvme_strom_tpu.obs.ledger import charge_waste
                    charge_waste(eng.stats, "hedge_loss", self._length)
                    if self._klass:
                        eng.stats.add_class_stat(self._klass,
                                                 hedges_won=1)
                    eng._trace("strom.resilient.hedge_won",
                               int(self._hedge.t0 * 1e9),
                               ctx=self._ctx, fh=self._fh,
                               offset=self._offset)
                    # the straggler primary may run for a while yet:
                    # release() would BLOCK until its I/O lands, erasing
                    # the hedge's entire latency win — park it instead
                    eng._defer_release(self._fh, self._primary.pending)
                    self._primary = self._hedge
                    self._drop_hedge()
                    self._winner = self._primary
                    self.was_fallback = bool(getattr(
                        self._primary.pending, "was_fallback", False))
                    return view

    def _submit_hedge(self) -> Optional[_Attempt]:
        """Issue the duplicate read IF the class's concurrent-hedge
        budget has a token; None (counted hedges_denied, once per
        primary) when the budget is exhausted — this is the isolation
        that keeps a scrub storm from eating the decode class's hedge
        quota."""
        eng = self._engine
        if not eng._acquire_hedge(self._klass):
            if not self._hedge_denied:
                self._hedge_denied = True
                eng.stats.add(hedges_denied=1)
                if self._klass:
                    eng.stats.add_class_stat(self._klass, hedges_denied=1)
            return None
        try:
            pending = eng._engine.submit_read(self._fh, self._offset,
                                              self._length,
                                              klass=self._klass)
        except OSError:
            # a hedge that cannot even submit (pool teardown, routing
            # refusal) must neither fail the read NOR strand the token:
            # hand it straight back — the deferral-queue wedge a leaked
            # token eventually becomes is exactly what the audit closed
            eng._release_hedge(self._klass)
            return None
        self._hedge_token = True
        self._hedges += 1
        eng.stats.add(hedges_issued=1)
        if self._klass:
            eng.stats.add_class_stat(self._klass, hedges_issued=1)
        tenant = current_tenant()
        if tenant is not None:
            # hedges are real duplicate I/O on the shared device: the
            # per-tenant ledger shows WHO is spending the budget
            eng.stats.add_tenant_stat(tenant.id, hedges_issued=1)
        eng._trace("strom.resilient.hedge", time.monotonic_ns(),
                   ctx=self._ctx, fh=self._fh, offset=self._offset,
                   length=self._length)
        return _Attempt(pending, time.monotonic())

    def _drop_hedge(self) -> None:
        """Clear the hedge slot and hand its budget token back (every
        transition out of 'hedge outstanding' funnels here exactly
        once)."""
        if self._hedge_token:
            self._engine._release_hedge(self._klass)
            self._hedge_token = False
        self._hedge = None

    def _note_failure(self, e: OSError, kind: Optional[str] = None):
        self._attempts.append({
            "error": str(e),
            "kind": kind or ("stuck" if isinstance(e, _Stuck) else "io"),
            "elapsed_s": round(time.monotonic() - self._primary.t0, 4),
        })
        # feed the failure-domain supervisor (io/health.py): a Python-
        # level fault plan never moves the C ring counters, yet must
        # trip the same breakers.  Ring attribution via the request id's
        # ring bits; cancellations are requeues and filtered inside.
        sup = self._engine._supervisor
        if sup is not None:
            sup.note_error(getattr(self._primary.pending, "ring", -1),
                           err=getattr(e, "errno", None),
                           engine_counted=getattr(e, "engine_counted",
                                                  False))

    def _retry(self, deadline) -> None:
        """Release the failed/stuck attempt, back off, resubmit."""
        eng = self._engine
        cfg = self._cfg
        stuck = self._attempts[-1]["kind"] == "stuck"
        t0 = time.monotonic_ns()
        self._release_attempts()
        if stuck:
            eng.stats.add(stuck_cancelled=1)
            # a cancelled stuck read typically completes into the void
            # after the resubmission: its whole range is re-read
            from nvme_strom_tpu.obs.ledger import charge_waste
            charge_waste(eng.stats, "retry_reread", self._length)
        eng.stats.add(resilient_retries=1)
        if self._klass:
            eng.stats.add_class_stat(self._klass, retries=1)
        delay = min(cfg.backoff_max_s,
                    cfg.backoff_base_s * (2 ** self._retries))
        delay *= 1.0 + cfg.jitter * (2 * eng._rng.random() - 1)
        if deadline is not None:
            delay = min(delay, max(0.0, deadline - time.monotonic()))
        if delay > 0:
            time.sleep(delay)
        self._retries += 1
        self._hedges = 0     # a fresh primary earns a fresh hedge budget
        self._hedge_denied = False
        sup = eng._supervisor
        if sup is not None and sup.degraded():
            # the device breaker opened while this read was mid-
            # recovery: its next attempt browns out onto the buffered
            # path (io/health.py) instead of burning the remaining
            # retry budget against a device the supervisor already
            # condemned — zero consumer errors is the contract
            self._primary = _Attempt(
                sup.degraded_pending(self._fh, self._offset,
                                     self._length,
                                     getattr(eng, "stats", None),
                                     probe_engine=eng._engine),
                time.monotonic())
            eng._trace("strom.resilient.retry", t0, ctx=self._ctx,
                       fh=self._fh, offset=self._offset,
                       attempt=self._retries, stuck=stuck, degraded=True,
                       error=self._attempts[-1]["error"])
            return
        try:
            pending = eng._engine.submit_read(self._fh, self._offset,
                                              self._length,
                                              klass=self._klass)
        except OSError as e:
            # the RESUBMISSION itself failed (engine teardown, pool
            # refusal): every prior attempt is already released/parked —
            # surface the loud, history-carrying ReadError instead of a
            # raw OSError with the logical read half-alive (audit:
            # wait_exact/consumers treat ReadError's released state as
            # final; a live-looking read here would strand its slot)
            self._note_failure(e, kind="resubmit")
            self._released = True
            raise ReadError(
                f"read fh={self._fh} off={self._offset} "
                f"len={self._length} could not be resubmitted after "
                f"{self._retries} retries: {e} "
                f"(history: {self._attempts})", self._attempts) from e
        self._primary = _Attempt(pending, time.monotonic())
        eng._trace("strom.resilient.retry", t0, ctx=self._ctx,
                   fh=self._fh, offset=self._offset,
                   attempt=self._retries, stuck=stuck,
                   error=self._attempts[-1]["error"])

    def _release_attempts(self) -> None:
        """Hand every outstanding attempt back — DEFERRED for attempts
        still in flight: a synchronous release() blocks until the I/O
        lands, which on a genuinely wedged request means the stuck
        recovery would never get to resubmit.  Internal-recovery use
        only; the caller-facing :meth:`release` blocks, preserving the
        engine's release-before-close invariant."""
        self._engine._defer_release(self._fh, self._primary.pending)
        if self._hedge is not None:
            self._engine._defer_release(self._fh, self._hedge.pending)
        self._drop_hedge()

    # -- PendingRead-compatible surface ------------------------------------

    def is_ready(self) -> bool:
        """Non-blocking probe; True once wait() would not block on I/O
        (recovery work — backoff, resubmit — may still run inside it)."""
        if self._view is not None or self._released:
            return True
        if self._primary.pending.is_ready():
            return True
        return self._hedge is not None and self._hedge.pending.is_ready()

    def release(self) -> None:
        """Caller-facing abort/free: BLOCKS until every attempt is out
        of flight (the PendingRead contract drain paths rely on — the
        caller may close the fh right after)."""
        if self._released:
            return
        self._released = True
        self._view = None
        self._primary.pending.release()   # waits if still in flight
        if self._hedge is not None:
            self._hedge.pending.release()
        self._drop_hedge()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class _Stuck(OSError):
    """Internal: a wait that exceeded stuck_timeout_s (cancel + retry)."""


class ResilientWrite:
    """The recoverable counterpart of ``PendingWrite`` — the write-path
    mirror of :class:`ResilientRead` (docs/RESILIENCE.md, write path).

    Holds (fh, offset, source bytes) so a failed attempt can be
    resubmitted whole and a SHORT write can resubmit exactly the
    remaining span (``data[n:]`` at ``offset + n``) instead of
    rewriting committed bytes.  Hedging does not apply: two in-flight
    writes of one range could land out of order and interleave torn
    content — retry/backoff is the whole recovery vocabulary here.
    The source buffer stays referenced until the logical write
    completes (the engine works from a raw pointer).
    """

    def __init__(self, engine: "ResilientEngine", fh: int, offset: int,
                 data: np.ndarray, pending):
        self._engine = engine
        self._fh = fh
        self._offset = offset
        self._data = data            # contiguous uint8; keepalive
        self._pending = pending
        self._done_total: Optional[int] = None
        self._written = 0            # bytes committed by prior attempts
        self._attempt_off = offset   # submit offset of the CURRENT attempt
        self._attempts: list = []
        self._retries = 0
        self._t0 = time.monotonic()
        self._released = False

    @property
    def fh(self) -> int:
        return self._fh

    @property
    def offset(self) -> int:
        return self._offset

    @property
    def length(self) -> int:
        return self._data.nbytes

    def wait(self, timeout: Optional[float] = None) -> int:
        """Block until every byte is committed (retrying failed and
        short attempts under the engine's retry budget); returns the
        total byte count, PendingWrite.wait parity.  ``timeout`` bounds
        THIS call: TimeoutError means the logical write is still live
        and recovery continues on the next wait."""
        if self._done_total is not None:
            return self._done_total
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        cfg = self._engine.rconfig
        while True:
            slice_t = None
            if deadline is not None:
                slice_t = max(0.0, deadline - time.monotonic())
            try:
                n = self._pending.wait(timeout=slice_t)
            except TimeoutError:
                raise            # caller's bound, write still live
            except OSError as e:
                self._note(e, kind="io")
                # retry exactly the failed attempt's span (bytes before
                # self._written were committed by earlier short attempts)
                self._retry_or_raise(cfg, deadline,
                                     resubmit_from=self._written)
                continue
            expected = self._data.nbytes - self._written
            if n < expected:
                self._note(OSError(
                    f"short write: {n} of {expected} bytes at "
                    f"offset {self._attempt_off}"), kind="short")
                # bytes [0, n) of this attempt ARE committed: resubmit
                # only the remainder
                self._written += n
                self._retry_or_raise(cfg, deadline,
                                     resubmit_from=self._written)
                continue
            self._done_total = self._written + n
            self._released = True
            return self._done_total

    def _note(self, e: OSError, kind: str) -> None:
        self._attempts.append({
            "error": str(e), "kind": kind,
            "elapsed_s": round(time.monotonic() - self._t0, 4)})
        sup = self._engine._supervisor
        if sup is not None:   # write failures feed the same breakers
            sup.note_error(getattr(self._pending, "ring", -1),
                           err=getattr(e, "errno", None),
                           engine_counted=getattr(e, "engine_counted",
                                                  False))

    def _retry_or_raise(self, cfg, deadline, resubmit_from: int) -> None:
        eng = self._engine
        if self._retries >= cfg.max_retries:
            self._released = True
            raise WriteError(
                f"write fh={self._fh} off={self._offset} "
                f"len={self._data.nbytes} failed after "
                f"{self._retries + 1} attempts "
                f"(history: {self._attempts})", self._attempts)
        delay = min(cfg.backoff_max_s,
                    cfg.backoff_base_s * (2 ** self._retries))
        delay *= 1.0 + cfg.jitter * (2 * eng._rng.random() - 1)
        if deadline is not None:
            delay = min(delay, max(0.0, deadline - time.monotonic()))
        if delay > 0:
            time.sleep(delay)
        self._retries += 1
        eng.stats.add(write_retries=1)
        self._attempt_off = self._offset + resubmit_from
        remaining = self._data[resubmit_from:]
        self._pending = eng._engine.submit_write(
            self._fh, self._attempt_off, remaining)
        eng._trace("strom.resilient.write_retry", time.monotonic_ns(),
                   fh=self._fh, offset=self._attempt_off,
                   attempt=self._retries,
                   error=self._attempts[-1]["error"])

    def release(self) -> None:
        """Abort/free: blocks until the current attempt is out of
        flight (the PendingWrite contract), then drops the keepalive."""
        if self._released:
            return
        self._released = True
        self._pending.release()


class ResilientEngine:
    """Engine wrapper adding retry / hedging / stuck-cancel to reads,
    and retry / short-write-resubmit to writes.

    Drop-in for StromEngine everywhere I/O happens (ShardedLoader,
    CheckpointManager, OffloadedAdam, PagedKVCache, parallel/weights):
    ``submit_read`` returns a ResilientRead, ``submit_write`` a
    ResilientWrite; all other attributes delegate to the wrapped
    engine.  Write recovery is SAFE under the checkpoint path's
    atomicity story: every consumer writes into a staged temp file or
    an exclusively-owned slot, so rewriting the same bytes at the same
    offset is idempotent, and the commit record (marker/manifest/
    rename) only lands after the waits succeed — a retry can never
    resurrect a save the commit sequence already abandoned.
    """

    def __init__(self, engine, config: Optional[ResilientConfig] = None,
                 class_configs: Optional[dict] = None,
                 hedge_budgets: Optional[dict] = None):
        self._engine = engine
        self.rconfig = config or ResilientConfig()
        #: per-latency-class ResilientConfig overrides ({class: config})
        #: — recovery policy is no longer process-global: tests and
        #: serving deployments vary a class's retry/backoff/hedging
        #: without touching env vars or the other classes
        self.class_configs = dict(class_configs or {})
        # concurrent-hedge budget per class (tokens; {class: int}).
        # Default from the scheduler's stock policies so the two layers
        # agree on class names and relative generosity; explicit
        # ``hedge_budgets`` wins.  Reads with NO class share the
        # unlimited legacy pool (hedging capped at 1 per primary as
        # before), so un-tagged callers keep exact pre-PR behavior.
        if hedge_budgets is None:
            from nvme_strom_tpu.io.sched import default_policies
            hedge_budgets = {name: p.hedge_budget
                            for name, p in default_policies().items()}
        self.hedge_budgets = dict(hedge_budgets)
        # the failure-domain supervisor (io/health.py) of the BASE
        # engine, reached through the wrapper chain's delegation;
        # cached — _note_failure runs on error paths, but the wait
        # loop's supervision tick runs per poll slice
        self._supervisor = getattr(engine, "supervisor", None)
        self._hedge_out: dict = {}           # class -> outstanding hedges
        self._hedge_lock = make_lock("resilient.ResilientEngine._hedge_lock")
        self._rng = random.Random(self.rconfig.seed)
        # abandoned attempts (lost hedges, cancelled stuck reads) whose
        # I/O may still be in flight: released opportunistically once
        # complete — a synchronous release would block on the very
        # straggler/wedge being recovered from.  Bounded: at most
        # 1 + max_retries outstanding attempts exist per logical read.
        self._zombies: list = []
        self._zombie_lock = make_lock("resilient.ResilientEngine._zombie_lock")
        # derived hedge threshold, refreshed at most once a second PER
        # CLASS: the percentile walk over the C histogram is cheap but
        # runs per wait — uncached it becomes measurable on tens of
        # thousands of small reads per second
        self._hedge_cache: dict = {}   # class -> (computed_at, value)

    def config_for(self, klass: Optional[str]) -> ResilientConfig:
        """The ResilientConfig governing reads of ``klass`` (the
        engine-wide config unless a per-class override is registered)."""
        if klass is not None:
            cfg = self.class_configs.get(klass)
            if cfg is not None:
                return cfg
        return self.rconfig

    # -- per-class hedge budget (token accounting) -------------------------

    def _acquire_hedge(self, klass: Optional[str]) -> bool:
        """Take one concurrent-hedge token for ``klass``; False when the
        class's budget is exhausted.  Class-less reads always succeed
        (legacy behavior: their only cap is one hedge per primary)."""
        if klass is None:
            return True
        budget = self.hedge_budgets.get(klass)
        if budget is None:
            return True
        with self._hedge_lock:
            if self._hedge_out.get(klass, 0) >= budget:
                return False
            self._hedge_out[klass] = self._hedge_out.get(klass, 0) + 1
            return True

    def _release_hedge(self, klass: Optional[str]) -> None:
        if klass is None or klass not in self.hedge_budgets:
            return
        with self._hedge_lock:
            n = self._hedge_out.get(klass, 0)
            if n > 0:
                self._hedge_out[klass] = n - 1

    def hedges_outstanding(self, klass: str) -> int:
        with self._hedge_lock:
            return self._hedge_out.get(klass, 0)

    def set_hedge_budget(self, klass: str, budget: int) -> None:
        """Adjust one class's concurrent-hedge token budget at runtime —
        the SLO governor's resilience lever (docs/PERF.md §5): a
        decode-path p99 violation buys the decode class more concurrent
        hedges, and the governor decays the budget back once the target
        is met.  Outstanding tokens are untouched: a shrink simply
        denies NEW hedges until enough in-flight ones release."""
        if budget < 0:
            raise ValueError(f"budget must be >= 0, got {budget}")
        with self._hedge_lock:
            self.hedge_budgets[klass] = int(budget)

    # -- delegation --------------------------------------------------------

    def open(self, path, **kw) -> int:
        return self._engine.open(path, **kw)

    def close(self, fh: int) -> None:
        # lost hedges / cancelled stuck reads on this file must be out
        # of flight before the fd goes away (a recycled fd number would
        # hand their late completion someone else's file)
        self._reap_zombies(fh=fh, block=True)
        self._engine.close(fh)

    def __getattr__(self, name):
        return getattr(self._engine, name)

    def __enter__(self):
        return self

    def close_all(self) -> None:
        # completed zombies release cleanly; a genuinely wedged one is
        # left to the engine's own teardown drain (which must wait for
        # the kernel anyway before unmapping the pool)
        self._reap_zombies()
        self._engine.close_all()

    def __exit__(self, *exc):
        self.close_all()

    # -- reads -------------------------------------------------------------

    def _defer_release(self, fh: int, pending) -> None:
        """Release ``pending`` without ever blocking: immediately when
        its I/O already landed, else parked (tagged with its fh) for the
        next reap — ``close(fh)`` force-releases its stragglers so the
        fd never closes under an in-flight read."""
        if pending.is_ready():
            pending.release()
        else:
            with self._zombie_lock:
                self._zombies.append((fh, pending))

    def _reap_zombies(self, fh: Optional[int] = None,
                      block: bool = False) -> None:
        """Release parked attempts that have landed; ``fh``+``block``
        restricts to that file's zombies and waits for them (the
        close-time invariant: no read may be in flight on a closing fd)."""
        with self._zombie_lock:
            zombies, self._zombies = self._zombies, []
        survivors = []
        for zfh, p in zombies:
            if block and (fh is None or zfh == fh):
                p.release()               # waits if still in flight
            elif p.is_ready():
                p.release()
            else:
                survivors.append((zfh, p))
        if survivors:
            with self._zombie_lock:
                self._zombies.extend(survivors)

    def submit_read(self, fh: int, offset: int, length: int,
                    klass: Optional[str] = None) -> ResilientRead:
        self._reap_zombies()   # lost hedges hand buffers back here
        pending = self._engine.submit_read(fh, offset, length,
                                           klass=klass)
        # size AFTER submit: the C engine re-fstats the file at every
        # submit, so this reflects writes since open() (a size cached at
        # open time would make short-read detection silently inert on
        # grow-after-open files like the offload stores' backing files)
        try:
            size = self._engine.file_size(fh)
        except OSError:
            size = 0
        expected = min(length, max(0, size - offset))
        return ResilientRead(self, fh, offset, length, pending, expected,
                             klass=klass)

    def submit_readv(self, reads, klass: Optional[str] = None) -> list:
        """Batch-aware vectored submission: the whole batch goes down
        in ONE wrapped-engine call (keeping the syscall amortization),
        but every extent comes back as its OWN ResilientRead — a
        failed/short/stuck span retries, hedges, and cancels alone;
        the rest of the batch is never resubmitted.  ``klass`` flows
        down to the scheduler AND selects the per-class retry/hedge
        budgets each ResilientRead runs under."""
        from nvme_strom_tpu.io.plan import submit_spans
        self._reap_zombies()   # lost hedges hand buffers back here
        reads = list(reads)
        pendings = submit_spans(self._engine, reads, klass=klass)
        sizes: dict = {}
        out = []
        for (fh, offset, length), pending in zip(reads, pendings):
            size = sizes.get(fh)
            if size is None:
                try:
                    size = self._engine.file_size(fh)
                except OSError:
                    size = 0
                sizes[fh] = size
            expected = min(length, max(0, size - offset))
            out.append(ResilientRead(self, fh, offset, length, pending,
                                     expected, klass=klass))
        return out

    def read(self, fh: int, offset: int, length: int) -> np.ndarray:
        """Synchronous owning-array read through the recovery path."""
        with self.submit_read(fh, offset, length) as p:
            out = p.wait().copy()
        self.stats.add(bounce_bytes=int(out.nbytes))
        return out

    # -- writes ------------------------------------------------------------

    def submit_write(self, fh: int, offset: int, data) -> ResilientWrite:
        """Recoverable write: failed attempts resubmit with backoff,
        short writes resubmit the remaining span, and exhaustion raises
        WriteError with the per-attempt history — the write mirror of
        submit_read's retry half (hedging deliberately excluded: racing
        duplicate writes of one range can interleave torn content)."""
        arr = np.ascontiguousarray(np.asarray(data)) \
            .view(np.uint8).reshape(-1)
        pending = self._engine.submit_write(fh, offset, arr)
        return ResilientWrite(self, fh, offset, arr, pending)

    # -- policy helpers ----------------------------------------------------

    def _hedge_after(self, klass: Optional[str] = None) -> Optional[float]:
        """Seconds after which an in-flight read of ``klass`` earns a
        hedge; None disables hedging (per-class config, or the
        histogram is still cold)."""
        cfg = self.config_for(klass)
        if not cfg.hedging:
            return None
        if cfg.hedge_after_s > 0:
            return cfg.hedge_after_s
        now = time.monotonic()
        computed_at, cached = self._hedge_cache.get(klass, (-1.0, None))
        if now - computed_at < 1.0:
            return cached
        try:
            pct = self._engine.latency_percentiles(
                "read", ps=(cfg.hedge_percentile,))
        except (OSError, AttributeError):
            return None
        ns = pct.get(cfg.hedge_percentile, 0)
        # None while no read has completed — nothing to derive from
        val = (max(cfg.hedge_min_s, ns / 1e9 * cfg.hedge_multiplier)
               if ns else None)
        self._hedge_cache[klass] = (now, val)
        return val

    def _trace(self, name: str, t0_ns: int, ctx=None, **args) -> None:
        from nvme_strom_tpu.utils.trace import NO_CONTEXT
        tracer = getattr(self._engine, "tracer", None)
        if tracer is None or not tracer.enabled:
            return
        if ctx is not None and ctx is not NO_CONTEXT:
            ctx = ctx.child()   # ctx is the PARENT here (the submit-
            #                     time context the read captured)
        elif ctx is None:
            # a recovery span may fire from a wait() on another
            # request's thread: never auto-adopt that thread's context
            ctx = NO_CONTEXT
        tracer.add_span(name, int(t0_ns), time.monotonic_ns(),
                        category="strom.resilient", ctx=ctx, **args)
