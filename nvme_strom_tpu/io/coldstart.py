"""Elastic cold-start coordinator: the boot-phase state machine behind
serve-while-restoring (docs/RESILIENCE.md "Elastic cold-start").

A replica that crashes, restarts, or scales out should take traffic in
seconds.  With ``STROM_COLDSTART=1`` the serving stack starts before its
weights are resident: requests that touch a missing tensor demand-fault
it at ``decode`` class (ahead of everything else the QoS scheduler
holds), the bulk of the checkpoint streams in behind them at ``restore``
class, and warm-state manifests — the ``.kvman.json`` KV prefix index
plus the ``.warmhints.json`` hostcache hint list (io/warmup.py) — are
prefetched at ``prefetch`` class.  This module owns the small state
machine that ties those lanes together and makes the progression
observable:

    cold ──serving started──▶ faulting ──weights resident──▶ warming
                                                            │
                                            warmup drained  ▼
                                                          steady

* ``cold``     — process up, server not yet accepting work.
* ``faulting`` — serving; any request may demand-fault weights.  The
  coldstart_stall flight-recorder trigger is armed only here: if the
  demand-fault p99 exceeds ``ColdStartConfig.fault_slo_ms`` the
  coordinator dumps ``reason=coldstart_stall`` with the boot phase and
  the scheduler's per-class backlog in the extra payload.
* ``warming``  — all weights resident; background warmup thunks (KV
  page re-reads, hostcache hint prefetch) drain at ``prefetch`` class.
* ``steady``   — warmup drained; the replica is indistinguishable from
  one that never restarted.

The phase is exported as the ``boot_phase`` gauge through StromStats →
strom_stat/strom-top/debugsrv ``/health``, and a supervisor
degraded-mode listener counts brown-outs that land mid-cold-start
(``coldstart_brownouts``) — the evidence that a ring failure during the
restore stream was absorbed, not surfaced.

Locking: ``coldstart.ColdStartCoordinator._lock`` is a leaf-facing
coordinator lock (group ``coldstart`` in analysis/lock_order.conf).
Engine work — flight dumps, scheduler introspection, warmup I/O — runs
OUTSIDE the lock; only phase/word-size state mutates under it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from nvme_strom_tpu.utils.config import ColdStartConfig
from nvme_strom_tpu.utils.lockwitness import make_lock

#: boot phases in order; index = numeric gauge code
PHASES = ("cold", "faulting", "warming", "steady")


class ColdStartCoordinator:
    """Tracks one replica's boot progression and arms the stall dump.

    Thread-safe; every serving/weights/warmup actor calls in from its
    own thread.  All note_* methods are cheap and safe to call with the
    feature off (they no-op once ``steady`` is reached).
    """

    def __init__(self, engine=None,
                 cfg: Optional[ColdStartConfig] = None) -> None:
        self.cfg = cfg or ColdStartConfig()
        self.engine = engine
        self._lock = make_lock("coldstart.ColdStartCoordinator._lock")
        self._phase = "cold"
        self._t0 = time.monotonic()
        self._t_phase: Dict[str, float] = {"cold": 0.0}
        # rolling demand-fault latencies (ms), bounded by fault_window
        self._fault_ms: List[float] = []
        # warmup thunks registered before warming; drained by _warm_run
        self._warmups: List[Callable[[], None]] = []
        self._warm_thread: Optional[threading.Thread] = None
        self._degraded_seen = False
        if engine is not None:
            sup = getattr(engine, "supervisor", None)
            if sup is not None and hasattr(sup, "add_degraded_listener"):
                sup.add_degraded_listener(self._on_degraded)

    # -- phase machine -----------------------------------------------------

    @property
    def phase(self) -> str:
        with self._lock:
            return self._phase

    def phase_times(self) -> Dict[str, float]:
        """Seconds-from-boot each phase was entered (diagnostics)."""
        with self._lock:
            return dict(self._t_phase)

    def _advance(self, new: str) -> bool:
        """Move forward only — a late note from a slow thread never
        rewinds the machine.  Returns True on a real transition."""
        with self._lock:
            if PHASES.index(new) <= PHASES.index(self._phase):
                return False
            self._phase = new
            self._t_phase[new] = round(time.monotonic() - self._t0, 6)
        self._export_gauge()
        return True

    def _export_gauge(self) -> None:
        stats = getattr(self.engine, "stats", None)
        if stats is not None:
            ph = self.phase
            stats.set_gauges(boot_phase=ph,
                             boot_phase_code=PHASES.index(ph))

    def note_serving_started(self) -> None:
        """The server is accepting submissions (weights may be cold)."""
        self._advance("faulting")

    def note_weights_resident(self) -> None:
        """Every tensor is device-resident (bulk restore + demand
        faults have fully met); kick the background warmup drain."""
        if not self._advance("warming"):
            return
        with self._lock:
            thunks, self._warmups = self._warmups, []
        if not thunks:
            self._advance("steady")
            return
        t = threading.Thread(target=self._warm_run, args=(thunks,),
                             name="strom-coldstart-warmup", daemon=True)
        with self._lock:
            self._warm_thread = t
        t.start()

    def add_warmup(self, fn: Callable[[], None]) -> None:
        """Register a warming-phase thunk (KV page re-read, hostcache
        hint prefetch).  If warming already started, run inline — the
        caller is late, not wrong."""
        with self._lock:
            if self._phase in ("cold", "faulting"):
                self._warmups.append(fn)
                return
        try:
            fn()
        except Exception:
            pass

    def _warm_run(self, thunks: List[Callable[[], None]]) -> None:
        for fn in thunks:
            try:
                fn()
            except Exception:
                # warmup is best-effort by definition: a failed hint
                # prefetch costs future cache hits, never correctness
                pass
        self._advance("steady")

    def consume_handoff(self, base: str, server=None,
                        checkpoint=None) -> Optional[dict]:
        """Boot from a retiring replica's handoff bundle
        (io/handoff.py, docs/RESILIENCE.md "Drain & handoff"):
        exported sessions re-admit first at decode class, the shipped
        hot set pre-faults ahead of the bulk stream, and warm-hint
        replays queue on THIS coordinator's warming phase at prefetch
        class.  A torn/stale/missing bundle returns None and this boot
        proceeds as the plain elastic cold start it already is —
        brown-out, never black-out."""
        from nvme_strom_tpu.io.handoff import consume_bundle
        return consume_bundle(base, engine=self.engine, server=server,
                              coordinator=self, checkpoint=checkpoint)

    def wait_steady(self, timeout: Optional[float] = None) -> bool:
        """Block until the warmup drain finishes (tests/benches)."""
        with self._lock:
            t = self._warm_thread
        if t is not None:
            t.join(timeout)
        return self.phase == "steady"

    # -- stall trigger -----------------------------------------------------

    def note_fault_ms(self, ms: float) -> None:
        """Record one demand-fault service time; during the faulting
        phase a rolling-p99 SLO violation trips the flight recorder."""
        slo = self.cfg.fault_slo_ms
        with self._lock:
            if self._phase != "faulting":
                return
            self._fault_ms.append(float(ms))
            if len(self._fault_ms) > self.cfg.fault_window:
                del self._fault_ms[:-self.cfg.fault_window]
            if slo <= 0.0 or len(self._fault_ms) < 8:
                return
            window = sorted(self._fault_ms)
            p99 = window[min(len(window) - 1,
                             int(0.99 * len(window)))]
            if p99 <= slo:
                return
            degraded = self._degraded_seen
        self._stall_dump(p99, degraded)

    def _stall_dump(self, p99_ms: float, degraded: bool) -> None:
        flight = getattr(self.engine, "flight", None)
        if flight is None:
            return
        sched = getattr(self.engine, "scheduler", None)
        backlog = sched.backlog() if sched is not None else {}
        path = flight.dump("coldstart_stall", extra={
            "boot_phase": self.phase,
            "fault_p99_ms": round(p99_ms, 3),
            "fault_slo_ms": self.cfg.fault_slo_ms,
            "backlog": backlog,
            "browned_out": degraded,
        })
        stats = getattr(self.engine, "stats", None)
        if path is not None and stats is not None:
            stats.add(coldstart_stall_dumps=1)

    # -- supervisor listener ------------------------------------------------

    def _on_degraded(self, on: bool) -> None:
        if not on:
            return
        count = False
        with self._lock:
            self._degraded_seen = True
            count = self._phase != "steady"
        if count:
            stats = getattr(self.engine, "stats", None)
            if stats is not None:
                stats.add(coldstart_brownouts=1)
