"""Scatter-read byte store: serve reads from ICI-gathered shares.

The read-once/scatter restore (ops/ici.py, docs/PERF.md §7) splits a
file set into per-host contiguous byte shares, reads each share from
NVMe exactly once per mesh, and all-gathers the shares over the
interconnect.  This module is the serving half: the partition rule
(:func:`partition_files`), the gathered-byte index (:class:`ScatterStore`)
and a delegating engine front-end (:class:`ScatterServeEngine`) that
satisfies any read of the scattered files from the store — so consumers
built on ``plan_and_submit``/``submit_readv`` (checkpoint restore,
weight streaming) run UNCHANGED and bit-identical, they just stop
touching flash for bytes the mesh already moved.

Reads of files outside the scattered set — or ranges past a file's
partitioned size (a file grown after manifest build) — delegate to the
wrapped engine verbatim; everything else (``stats``, ``config``,
``supervisor``, the tracer) delegates too, so breakers, the scheduler
and the ledger see the same engine they always governed.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class ShareManifest:
    """Per-host partition of a file set into contiguous byte shares.

    Each file splits into ``n_hosts`` contiguous spans on ``unit_bytes``
    boundaries (balanced to within one unit), so every host's share of
    every file coalesces into large aligned reads and the union of all
    shares covers every byte exactly once.

    ``units``       (file_idx, offset, length, host, row_pos) — row_pos
                    is the span's byte position inside its host's packed
                    share row (spans pack in file order).
    ``host_bytes``  total share bytes per host — the per-host NVMe bill
                    the read-once property is measured against
                    (≤ ceil(total/n) + one unit per file).
    """

    n_hosts: int
    unit_bytes: int
    sizes: Tuple[int, ...]
    units: Tuple[Tuple[int, int, int, int, int], ...]
    host_bytes: Tuple[int, ...]

    @property
    def total_bytes(self) -> int:
        return sum(self.sizes)

    def units_for(self, host: int) -> List[Tuple[int, int, int]]:
        """Host ``host``'s ordered (file_idx, offset, length) spans."""
        return [(fi, off, ln) for fi, off, ln, h, _ in self.units
                if h == host]


def partition_files(sizes: Sequence[int], n_hosts: int,
                    unit_bytes: int) -> ShareManifest:
    """Partition files of ``sizes`` bytes into ``n_hosts`` contiguous
    per-file shares on ``unit_bytes`` boundaries."""
    if n_hosts < 1:
        raise ValueError(f"n_hosts must be >= 1, got {n_hosts}")
    if unit_bytes < 1:
        raise ValueError(f"unit_bytes must be >= 1, got {unit_bytes}")
    units: List[Tuple[int, int, int, int, int]] = []
    host_bytes = [0] * n_hosts
    per_host: List[List[Tuple[int, int, int]]] = [[] for _ in range(n_hosts)]
    for fi, size in enumerate(sizes):
        if size < 0:
            raise ValueError(f"file {fi}: negative size {size}")
        nunits = -(-size // unit_bytes) if size else 0
        q, r = divmod(nunits, n_hosts)
        start_u = 0
        for h in range(n_hosts):
            take = q + (1 if h < r else 0)
            off = start_u * unit_bytes
            end = min(size, (start_u + take) * unit_bytes)
            start_u += take
            if end <= off:
                continue
            per_host[h].append((fi, off, end - off))
    for h in range(n_hosts):
        pos = 0
        for fi, off, ln in per_host[h]:
            units.append((fi, off, ln, h, pos))
            pos += ln
        host_bytes[h] = pos
    return ShareManifest(n_hosts=n_hosts, unit_bytes=unit_bytes,
                         sizes=tuple(int(s) for s in sizes),
                         units=tuple(units),
                         host_bytes=tuple(host_bytes))


class ScatterStore:
    """Gathered share rows indexed for (path, offset, length) lookup.

    ``rows`` is the (n_hosts, row_bytes) uint8 array out of
    :meth:`IciExchange.all_gather`; the manifest says which slice of
    which row holds each file span.  ``view()`` is zero-copy when the
    request falls inside one span and assembles across span boundaries
    otherwise (a copy, like any coalesce join).

    ``host_bytes_read`` records the bytes each LOCAL (or emulated) host
    actually pulled off NVMe for its share — the per-host evidence the
    read-once tests assert against.
    """

    def __init__(self, paths: Sequence[str], manifest: ShareManifest,
                 rows: np.ndarray,
                 host_bytes_read: Optional[Dict[int, int]] = None):
        self.manifest = manifest
        self.rows = rows
        self.host_bytes_read = dict(host_bytes_read or {})
        self.paths = [os.path.realpath(str(p)) for p in paths]
        self._by_path: Dict[str, int] = {
            p: i for i, p in enumerate(self.paths)}
        # per file: (offset, end, host, row_pos) spans sorted by offset
        self._spans: List[List[Tuple[int, int, int, int]]] = [
            [] for _ in self.paths]
        for fi, off, ln, h, pos in manifest.units:
            self._spans[fi].append((off, off + ln, h, pos))
        for spans in self._spans:
            spans.sort()

    def covers(self, path: str, offset: int, length: int) -> bool:
        fi = self._by_path.get(os.path.realpath(str(path)))
        return (fi is not None and offset >= 0
                and offset + length <= self.manifest.sizes[fi])

    def view(self, path: str, offset: int, length: int
             ) -> Optional[np.ndarray]:
        """The bytes of ``path[offset:offset+length]``, or None when the
        range is not fully inside the scattered file set."""
        fi = self._by_path.get(os.path.realpath(str(path)))
        if fi is None or offset < 0 or length < 0 \
                or offset + length > self.manifest.sizes[fi]:
            return None
        if length == 0:
            return np.empty(0, dtype=np.uint8)
        pieces: List[np.ndarray] = []
        need_lo, need_hi = offset, offset + length
        for lo, hi, h, pos in self._spans[fi]:
            if hi <= need_lo or lo >= need_hi:
                continue
            a, b = max(lo, need_lo), min(hi, need_hi)
            pieces.append(self.rows[h][pos + a - lo: pos + b - lo])
        if sum(p.nbytes for p in pieces) != length:
            return None             # partition hole: never by construction
        if len(pieces) == 1:
            return pieces[0]
        return np.concatenate(pieces)


class StoreRead:
    """PendingRead-shaped completion over store bytes (already resident:
    ready immediately, release is a no-op beyond idempotence — the store
    owns the memory for the serve-engine's lifetime)."""

    __slots__ = ("_view", "fh", "offset", "length", "_released")
    was_fallback = False

    def __init__(self, view: np.ndarray, fh: int, offset: int):
        self._view = view
        self.fh = fh
        self.offset = offset
        self.length = int(view.nbytes)
        self._released = False

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        return self._view

    def is_ready(self) -> bool:
        return True

    def release(self) -> None:
        self._released = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class ScatterServeEngine:
    """Engine front-end serving scattered-file reads from a
    :class:`ScatterStore`, delegating everything else.

    Sits where a consumer's engine handle goes: ``open`` tracks which
    file handles name scattered files, ``submit_read``/``submit_readv``
    satisfy covered spans from the store (uncovered spans ride the
    wrapped engine as ONE vectored batch, order preserved),
    ``close``/``close_all`` drop the handle tracking before delegating,
    and every other attribute — ``stats``, ``config``, ``supervisor``,
    ``tracer``, ``n_buffers`` — resolves on the wrapped engine, so the
    QoS scheduler, breakers and ledger govern exactly the engine they
    always did."""

    def __init__(self, engine, store: ScatterStore):
        self._engine = engine
        self.scatter_store = store
        self._paths: Dict[int, str] = {}
        self._lock = threading.Lock()

    # -- handle tracking ----------------------------------------------

    def open(self, path, *args, **kwargs) -> int:
        fh = self._engine.open(path, *args, **kwargs)
        with self._lock:
            self._paths[fh] = os.path.realpath(str(path))
        return fh

    def close(self, fh: int) -> None:
        with self._lock:
            self._paths.pop(fh, None)
        self._engine.close(fh)

    def close_all(self) -> None:
        # intercepted (not left to __getattr__ delegation) so the fh→
        # path map empties with the handles: a later reuse of the same
        # fh integer for a DIFFERENT file must ride the wrapped engine,
        # not be served stale scattered-file bytes.  Handles closed
        # directly on the wrapped engine (code holding the inner
        # handle) cannot be tracked — keep opens/closes on the wrapper.
        with self._lock:
            self._paths.clear()
        self._engine.close_all()

    # -- the serving read path ----------------------------------------

    def _store_view(self, fh: int, offset: int,
                    length: int) -> Optional[np.ndarray]:
        with self._lock:
            path = self._paths.get(fh)
        if path is None:
            return None
        return self.scatter_store.view(path, offset, length)

    def submit_read(self, fh: int, offset: int, length: int,
                    *args, **kwargs):
        v = self._store_view(fh, offset, length)
        if v is not None:
            return StoreRead(v, fh, offset)
        return self._engine.submit_read(fh, offset, length,
                                        *args, **kwargs)

    def submit_readv(self, reads, klass: Optional[str] = None,
                     **kwargs) -> list:
        reads = list(reads)
        out: List[object] = [None] * len(reads)
        miss_idx: List[int] = []
        for i, (fh, off, ln) in enumerate(reads):
            v = self._store_view(fh, off, ln)
            if v is not None:
                out[i] = StoreRead(v, fh, off)
            else:
                miss_idx.append(i)
        if miss_idx:
            spans = [reads[i] for i in miss_idx]
            try:
                if klass is not None:
                    pend = self._engine.submit_readv(spans, klass=klass,
                                                     **kwargs)
                else:
                    pend = self._engine.submit_readv(spans, **kwargs)
            except BaseException:
                for p in out:
                    if p is not None:
                        p.release()
                raise
            for i, p in zip(miss_idx, pend):
                out[i] = p
        return out

    # -- everything else is the wrapped engine -------------------------

    def __getattr__(self, name):
        return getattr(self._engine, name)
