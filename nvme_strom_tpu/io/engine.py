"""ctypes wrapper over the strom-io C++ engine (csrc/strom_io.{h,cc}).

This is the userspace library layer of the stack — the analogue of the thin
wrappers PG-Strom keeps around the reference's ioctl ABI (SURVEY.md §1 L2/L4).
Python never touches payload bytes: reads complete into engine-owned locked
buffers, exposed here as zero-copy numpy views via ``np.ctypeslib.as_array``.
"""

from __future__ import annotations

import bisect
import ctypes
import errno
import os
import subprocess
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

import numpy as np

from nvme_strom_tpu.utils.config import EngineConfig
from nvme_strom_tpu.utils.lockwitness import make_lock
from nvme_strom_tpu.utils.stats import StromStats, global_stats
from nvme_strom_tpu.utils.trace import NO_CONTEXT

_CSRC = Path(__file__).resolve().parents[2] / "csrc"
_LIB_PATH = _CSRC / "libstrom_io.so"
_lib_lock = make_lock("engine._lib_lock")
_lib: Optional[ctypes.CDLL] = None


class _FileInfo(ctypes.Structure):
    _fields_ = [
        ("size", ctypes.c_int64),
        ("supports_direct", ctypes.c_int32),
        ("block_size", ctypes.c_int32),
        ("fs_magic", ctypes.c_uint64),
    ]


_MAX_RAID_MEMBERS = 16


class _DeviceInfo(ctypes.Structure):
    _fields_ = [
        ("device", ctypes.c_char * 64),
        ("is_nvme", ctypes.c_int32),
        ("is_raid", ctypes.c_int32),
        ("raid_level", ctypes.c_int32),
        ("n_members", ctypes.c_int32),
        ("rotational", ctypes.c_int32),
        ("nvme_backed", ctypes.c_int32),
        ("members", (ctypes.c_char * 64) * _MAX_RAID_MEMBERS),
    ]


class _Extent(ctypes.Structure):
    _fields_ = [
        ("logical", ctypes.c_uint64),
        ("physical", ctypes.c_uint64),
        ("length", ctypes.c_uint64),
        ("flags", ctypes.c_uint32),
        ("pad", ctypes.c_uint32),
    ]


class _PoolInfo(ctypes.Structure):
    _fields_ = [
        ("n_buffers", ctypes.c_uint32),
        ("free_buffers", ctypes.c_uint32),
        ("buf_bytes", ctypes.c_uint64),
        ("pool_bytes", ctypes.c_uint64),
        ("locked", ctypes.c_int32),
        ("queue_depth", ctypes.c_int32),
        ("in_flight", ctypes.c_uint32),
        ("deferred", ctypes.c_uint32),
        ("fixed_bufs", ctypes.c_int32),
        ("pad", ctypes.c_uint32),
        ("pool_base", ctypes.c_uint64),
    ]


class _StatsBlk(ctypes.Structure):
    _fields_ = [(n, ctypes.c_uint64) for n in (
        "bytes_direct", "bytes_fallback", "bounce_bytes",
        "bytes_written_direct", "requests_submitted", "requests_completed",
        "requests_failed", "retries", "bytes_resident",
        "submit_batches", "submit_syscalls_saved", "submit_enters")]


class _RdExt(ctypes.Structure):
    _fields_ = [
        ("fh", ctypes.c_int32),
        ("pad", ctypes.c_uint32),
        ("offset", ctypes.c_uint64),
        ("length", ctypes.c_uint64),
    ]


class _Completion(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.POINTER(ctypes.c_uint8)),
        ("len", ctypes.c_uint64),
        ("status", ctypes.c_int32),
        ("was_fallback", ctypes.c_int32),
        ("submit_ns", ctypes.c_uint64),
        ("complete_ns", ctypes.c_uint64),
    ]


_LAT_BUCKETS = 64
_MAX_RINGS = 64    # STROM_MAX_RINGS: request ids carry 6 ring bits


class _RingInfo(ctypes.Structure):
    _fields_ = [
        ("ring_id", ctypes.c_uint32),
        ("n_buffers", ctypes.c_uint32),
        ("free_buffers", ctypes.c_uint32),
        ("deferred", ctypes.c_uint32),
        ("submitted", ctypes.c_uint64),
        ("completed", ctypes.c_uint64),
        ("inflight_io", ctypes.c_uint32),
        ("backend_uring", ctypes.c_int32),
        # failure-domain health (io/health.py): real-error completions
        # (cancels excluded), hot restarts survived, parked backlog,
        # stall-injection state, and the age of the oldest completion
        # a backend still owes — the reap-side stall signal
        ("failed", ctypes.c_uint64),
        ("restarts", ctypes.c_uint64),
        ("parked", ctypes.c_uint32),
        ("stalled", ctypes.c_int32),
        ("oldest_inflight_ns", ctypes.c_uint64),
        # zero-copy submission state (PR 12): fixed-buffer registration,
        # registered-file slot table, SQPOLL mode — per-ring gauges so a
        # silently-unregistered pool is visible instead of just slow
        ("fixed_bufs", ctypes.c_int32),
        ("reg_files", ctypes.c_int32),
        ("sqpoll", ctypes.c_int32),
    ]


def _nvme_hw_queues() -> int:
    """Largest hardware-queue count across visible NVMe namespaces
    (/sys/block/nvme*/mq has one directory per hw queue); 0 unknown."""
    best = 0
    try:
        for d in os.listdir("/sys/block"):
            if d.startswith("nvme"):
                try:
                    best = max(best, len(os.listdir(f"/sys/block/{d}/mq")))
                except OSError:
                    pass
    except OSError:
        pass
    return best


def auto_ring_count() -> int:
    """Default ring count: CPU topology capped by the NVMe device's
    hardware queue count, rounded down to a power of two (divides the
    default queue depths/pools evenly), ceiling 8.  The caller further
    caps by what the configured pool/queue depth can feed."""
    cpus = os.cpu_count() or 1
    n = max(1, min(8, cpus // 4))
    mq = _nvme_hw_queues()
    if mq:
        n = max(1, min(n, mq))
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _CSRC.is_dir():
            raise ImportError(
                f"C++ engine sources not found at {_CSRC} — "
                "nvme_strom_tpu must run from a source checkout "
                "(`pip install -e .` or sys.path), not a plain wheel: "
                "the engine builds csrc/ against the running kernel's "
                "io_uring support on first import")
        src_mtime = max((_CSRC / n).stat().st_mtime
                        for n in ("strom_io.cc", "strom_io.h"))
        if not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < src_mtime:
            subprocess.run(["make", "-C", str(_CSRC)], check=True,
                           capture_output=True)
        lib = ctypes.CDLL(str(_LIB_PATH), use_errno=True)
        lib.strom_engine_create.restype = ctypes.c_void_p
        lib.strom_engine_create.argtypes = [
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_uint32, ctypes.c_int, ctypes.c_int]
        lib.strom_engine_create_rings.restype = ctypes.c_void_p
        lib.strom_engine_create_rings.argtypes = [
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int, ctypes.c_int]
        lib.strom_engine_create_prealloc.restype = ctypes.c_void_p
        lib.strom_engine_create_prealloc.argtypes = [
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_uint64]
        lib.strom_engine_pool_bytes.restype = ctypes.c_uint64
        lib.strom_engine_pool_bytes.argtypes = [
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_uint32]
        # strom_arena_* is OWNED by io/arena.py (its private handle) —
        # binding it here too was exactly the double-bind shape
        # strom-lint's abi pass forbids (one owning site per symbol)
        lib.strom_ring_count.restype = ctypes.c_int
        lib.strom_ring_count.argtypes = [ctypes.c_void_p]
        lib.strom_get_ring_info.restype = ctypes.c_int
        lib.strom_get_ring_info.argtypes = [ctypes.c_void_p,
                                            ctypes.c_uint32,
                                            ctypes.POINTER(_RingInfo)]
        lib.strom_ring_inflight.restype = ctypes.c_int64
        lib.strom_ring_inflight.argtypes = [ctypes.c_void_p,
                                            ctypes.c_uint32]
        lib.strom_ring_restart.restype = ctypes.c_int64
        lib.strom_ring_restart.argtypes = [ctypes.c_void_p,
                                           ctypes.c_uint32,
                                           ctypes.c_uint64]
        lib.strom_set_ring_stall.restype = ctypes.c_int
        lib.strom_set_ring_stall.argtypes = [ctypes.c_void_p,
                                             ctypes.c_uint32,
                                             ctypes.c_int]
        lib.strom_read_buffered.restype = ctypes.c_int64
        lib.strom_read_buffered.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_void_p]
        lib.strom_submit_read_ring.restype = ctypes.c_int64
        lib.strom_submit_read_ring.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_uint64]
        lib.strom_submit_readv_ring.restype = ctypes.c_int
        lib.strom_submit_readv_ring.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.POINTER(_RdExt),
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_int64)]
        lib.strom_engine_destroy.restype = None
        lib.strom_engine_destroy.argtypes = [ctypes.c_void_p]
        lib.strom_check_file.restype = ctypes.c_int
        lib.strom_check_file.argtypes = [ctypes.c_char_p,
                                         ctypes.POINTER(_FileInfo)]
        lib.strom_resolve_device.restype = ctypes.c_int
        lib.strom_resolve_device.argtypes = [ctypes.c_char_p,
                                             ctypes.POINTER(_DeviceInfo)]
        lib.strom_file_extents.restype = ctypes.c_int
        lib.strom_file_extents.argtypes = [ctypes.c_char_p,
                                           ctypes.POINTER(_Extent),
                                           ctypes.c_uint32]
        lib.strom_stripe_attr.argtypes = [
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint64)]
        lib.strom_stripe_attr.restype = None
        lib.strom_get_pool_info.restype = None
        lib.strom_get_pool_info.argtypes = [ctypes.c_void_p,
                                            ctypes.POINTER(_PoolInfo)]
        lib.strom_get_latency.restype = None
        lib.strom_get_latency.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.strom_open.restype = ctypes.c_int
        lib.strom_open.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
        lib.strom_close.restype = ctypes.c_int
        lib.strom_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.strom_file_size.restype = ctypes.c_int64
        lib.strom_file_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.strom_file_is_direct.restype = ctypes.c_int
        lib.strom_file_is_direct.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.strom_file_ident.restype = ctypes.c_int
        lib.strom_file_ident.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                         ctypes.POINTER(ctypes.c_uint64)]
        lib.strom_submit_read.restype = ctypes.c_int64
        lib.strom_submit_read.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.c_uint64, ctypes.c_uint64]
        lib.strom_submit_readv.restype = ctypes.c_int
        lib.strom_submit_readv.argtypes = [ctypes.c_void_p,
                                           ctypes.POINTER(_RdExt),
                                           ctypes.c_uint32,
                                           ctypes.POINTER(ctypes.c_int64)]
        lib.strom_submit_write.restype = ctypes.c_int64
        lib.strom_submit_write.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                           ctypes.c_uint64, ctypes.c_void_p,
                                           ctypes.c_uint64]
        lib.strom_submit_write_ring.restype = ctypes.c_int64
        lib.strom_submit_write_ring.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64]
        lib.strom_wait.restype = ctypes.c_int
        lib.strom_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                   ctypes.POINTER(_Completion)]
        lib.strom_wait_timeout.restype = ctypes.c_int
        lib.strom_wait_timeout.argtypes = [ctypes.c_void_p,
                                           ctypes.c_int64,
                                           ctypes.POINTER(_Completion),
                                           ctypes.c_uint64]
        lib.strom_release.restype = ctypes.c_int
        lib.strom_release.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.strom_get_stats.restype = None
        lib.strom_get_stats.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(_StatsBlk)]
        lib.strom_drain_stats.restype = None
        lib.strom_drain_stats.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(_StatsBlk)]
        lib.strom_reset_stats.restype = None
        lib.strom_reset_stats.argtypes = [ctypes.c_void_p]
        lib.strom_backend_is_uring.restype = ctypes.c_int
        lib.strom_backend_is_uring.argtypes = [ctypes.c_void_p]
        lib.strom_tar_index.restype = ctypes.c_int64
        lib.strom_tar_index.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.strom_tar_index_free.restype = None
        lib.strom_tar_index_free.argtypes = [
            ctypes.POINTER(ctypes.c_uint8)]
        _lib = lib
        return lib


@dataclass(frozen=True)
class FileInfo:
    """Result of the CHECK_FILE-analogue eligibility probe (SURVEY.md §3.3)."""
    size: int
    supports_direct: bool
    block_size: int
    fs_magic: int


@dataclass(frozen=True)
class DeviceInfo:
    """Backing block-device topology — the blockdev half of the reference's
    CHECK_FILE verdict (SURVEY.md §3.3: fs must sit on NVMe, or md-raid0
    whose members are all NVMe). ``device == ""`` means no backing blockdev
    is visible (overlayfs/tmpfs/network fs)."""
    device: str
    is_nvme: bool
    is_raid: bool
    raid_level: int       # numeric md level (0 == raid0); -1 unknown
    rotational: int       # -1 unknown
    nvme_backed: bool     # NVMe, or raid0 striped over all-NVMe members
    members: tuple[str, ...]


def check_file(path: os.PathLike | str) -> FileInfo:
    lib = _load_lib()
    info = _FileInfo()
    rc = lib.strom_check_file(str(path).encode(), ctypes.byref(info))
    if rc < 0:
        raise OSError(-rc, os.strerror(-rc), str(path))
    return FileInfo(size=info.size, supports_direct=bool(info.supports_direct),
                    block_size=info.block_size, fs_magic=info.fs_magic)


def resolve_device(path: os.PathLike | str) -> DeviceInfo:
    """sysfs walk: st_dev → /sys/dev/block → partition→parent → md members."""
    lib = _load_lib()
    info = _DeviceInfo()
    rc = lib.strom_resolve_device(str(path).encode(), ctypes.byref(info))
    if rc < 0:
        raise OSError(-rc, os.strerror(-rc), str(path))
    members = tuple(info.members[i].value.decode()
                    for i in range(min(info.n_members, _MAX_RAID_MEMBERS)))
    return DeviceInfo(device=info.device.decode(),
                      is_nvme=bool(info.is_nvme), is_raid=bool(info.is_raid),
                      raid_level=info.raid_level, rotational=info.rotational,
                      nvme_backed=bool(info.nvme_backed), members=members)


def tar_index(path: os.PathLike | str) -> list:
    """Native tar header walk: [(member name str, data offset, size)]
    for every regular file, in archive order.

    The C side (strom_tar_index) understands ustar name+prefix, GNU
    longname and pax path=/size= overrides — the formats Python's
    tarfile emits — and validates header checksums, failing loudly
    (ValueError) on malformed archives instead of returning a partial
    index.  Valid-but-unimplemented features (global pax path=/size=
    overrides, names past the 4096 cap) raise NotImplementedError so
    formats/wds.py can fall back to tarfile for those archives only.  ~5x the Python-loop indexing rate (measured: 20k members
    in ~100ms vs ~490ms warm-cache); formats/wds.py uses it when the
    library is built and falls back to tarfile otherwise."""
    lib = _load_lib()
    buf = ctypes.POINTER(ctypes.c_uint8)()
    nbytes = ctypes.c_uint64()
    n = lib.strom_tar_index(os.fsencode(path), ctypes.byref(buf),
                            ctypes.byref(nbytes))
    if n < 0:
        import errno as _errno
        if -n == _errno.ENOTSUP:
            # valid archive, feature this walker doesn't implement
            # (global pax path=/size= overrides, names beyond the 4096
            # cap): a DIFFERENT type so callers can fall back to
            # tarfile, while genuine corruption stays a loud ValueError
            raise NotImplementedError(
                f"{path}: tar feature unsupported by the native walker")
        raise ValueError(f"{path}: tar index failed "
                         f"({_errno.errorcode.get(-n, -n)})")
    try:
        raw = ctypes.string_at(buf, nbytes.value) if nbytes.value else b""
    finally:
        if buf:
            lib.strom_tar_index_free(buf)
    out = []
    pos = 0
    import struct as _struct
    for _ in range(n):
        off, size, nl = _struct.unpack_from("<QQI", raw, pos)
        pos += 20
        name = raw[pos:pos + nl].decode("utf-8", errors="surrogateescape")
        pos += nl
        out.append((name, off, size))
    return out


def stripe_attr(phys_off: int, length: int, chunk: int,
                n_members: int) -> list:
    """Per-member byte attribution of physical span [phys_off,
    phys_off+length) on an md-raid0 of ``n_members`` devices with
    stripe ``chunk`` (C closed-form; see strom_stripe_attr)."""
    lib = _load_lib()
    out = (ctypes.c_uint64 * n_members)()
    lib.strom_stripe_attr(phys_off, length, chunk, n_members, out)
    return list(out)


def md_chunk_bytes(device: str) -> int:
    """Stripe chunk of an md device from sysfs (bytes); 0 if unknown."""
    try:
        with open(f"/sys/block/{device}/md/chunk_size") as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return 0


EXTENT_SYNTHETIC = 0x80000000


@dataclass(frozen=True)
class Extent:
    """One file extent — the analogue of the reference's extent-walk output
    (file offsets resolved toward physical LBAs, SURVEY.md §3.1).
    ``synthetic`` extents come from filesystems without FIEMAP: the range is
    readable but not physically addressable."""
    logical: int
    physical: int
    length: int
    flags: int

    @property
    def synthetic(self) -> bool:
        return bool(self.flags & EXTENT_SYNTHETIC)


def file_extents(path: os.PathLike | str, max_extents: int = 1024
                 ) -> list[Extent]:
    """Complete extent map of `path`. Grows the buffer on -E2BIG so a
    heavily fragmented file never yields a silently truncated map."""
    lib = _load_lib()
    while True:
        arr = (_Extent * max_extents)()
        n = lib.strom_file_extents(str(path).encode(), arr, max_extents)
        if n == -errno.E2BIG and max_extents < (1 << 22):
            max_extents *= 4
            continue
        if n < 0:
            raise OSError(-n, os.strerror(-n), str(path))
        return [Extent(logical=e.logical, physical=e.physical,
                       length=e.length, flags=e.flags) for e in arr[:n]]


def file_eligible(path: os.PathLike | str) -> tuple[bool, FileInfo, DeviceInfo]:
    """The complete CHECK_FILE analogue: O_DIRECT works AND the file sits on
    NVMe (or md-raid0 over all-NVMe). Consumers use a False verdict the way
    the reference's callers use EINVAL/ENOTSUP — fall back to buffered
    reads (SURVEY.md §3.3)."""
    fi = check_file(path)
    di = resolve_device(path)
    return bool(fi.supports_direct and di.nvme_backed), fi, di


class PendingRead:
    """An in-flight read — MEMCPY_SSD2GPU's async DMA task id (SURVEY §3.1).

    ``wait()`` returns a zero-copy numpy view into the engine buffer; the
    view is valid until ``release()``.
    """

    def __init__(self, engine: "StromEngine", req_id: int, length: int,
                 fh: int = -1, offset: int = -1):
        self._engine = engine
        self._req_id = req_id
        self._length = length
        #: submit-time identity, carried so short-read/error reports can
        #: name the exact range (wait_exact, ReadError history)
        self.fh = fh
        self.offset = offset
        self._released = False
        self._view: Optional[np.ndarray] = None
        self._error: Optional[OSError] = None
        self.was_fallback = False

    @property
    def length(self) -> int:
        """Bytes REQUESTED at submit (the completed view may be shorter
        only at EOF — consumers whose plans never cross EOF treat a
        shorter view as a short read and recover or raise)."""
        return self._length

    @property
    def ring(self) -> int:
        """The submission ring this request rode (request ids carry
        their ring in the low STROM_RING_ID_BITS bits) — how the
        supervision layer (io/health.py) attributes a failed attempt
        to its failure domain."""
        return int(self._req_id) & (_MAX_RINGS - 1)

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block for the completed staging view.

        ``timeout`` (seconds): bounded wait — raises TimeoutError if
        the request is still in flight after the deadline, WITHOUT
        releasing it (hang detection: the caller can diagnose, retry
        the wait, or ``release()`` to abort; the buffer stays a live
        DMA target until then).

        The still-live contract after a TimeoutError, explicitly:

        - retrying ``wait()`` on the same request is always valid and
          returns the completed payload once the I/O lands;
        - ``release()`` is the CANCEL path: it blocks until the request
          is out of flight (the staging buffer is a live DMA target and
          cannot be recycled under the kernel), then frees it — after
          which a fresh ``submit_read`` of the same range is the
          cancel-then-retry recovery ``io/resilient.py`` builds on
          (tested in tests/test_engine.py
          ``test_wait_timeout_cancel_then_retry``).
        """
        if self._view is not None:
            return self._view
        if self._error is not None:     # error found by an is_ready probe
            raise self._error
        comp = _Completion()
        rc = _wait_for_completion(self._engine, self._req_id, comp,
                                  timeout, "read")
        if rc < 0:
            self.release()
            e = OSError(-rc, os.strerror(-rc))
            # the C engine already counted this completion in its
            # per-ring failed counter: the supervision layer must not
            # count it a second time via note_error (io/health.py —
            # the breaker budgets would silently halve for exactly the
            # real device errors they are calibrated against)
            e.engine_counted = True
            flight = self._engine.flight
            if flight is not None:
                flight.record("read", getattr(self, "op_klass", None),
                              self.ring, self.fh, self.offset, 0, 0,
                              "error", err=-rc)
            raise e
        self.was_fallback = bool(comp.was_fallback)
        tracer = self._engine.tracer
        if tracer is not None and tracer.enabled:
            tracer.add_span(
                "strom.read.fallback" if comp.was_fallback else "strom.read",
                int(comp.submit_ns), int(comp.complete_ns),
                ctx=getattr(self, "trace_ctx", NO_CONTEXT),
                bytes=int(comp.len))
        flight = self._engine.flight
        if flight is not None:
            flight.record(
                "read", getattr(self, "op_klass", None), self.ring,
                self.fh, self.offset, int(comp.len),
                max(0, int(comp.complete_ns - comp.submit_ns)) // 1000,
                "fallback" if comp.was_fallback else "ok")
        # completion reaping doubles as the ring time-in-state sampling
        # point (obs/ledger.py; time-gated inside — one monotonic read
        # per completed op on the fast path)
        self._engine._sample_ring_states()
        n = int(comp.len)
        if n == 0:
            self._view = np.empty(0, dtype=np.uint8)
        else:
            self._view = np.ctypeslib.as_array(comp.data, shape=(n,))
        return self._view

    def is_ready(self) -> bool:
        """Non-blocking completion probe: True once ``wait()`` would
        return without blocking — including completed-with-error reads,
        whose OSError is cached here and raised by the caller's
        ``wait()`` (a bool probe must not throw or release as a side
        effect).  Pipelines use this to promote read-complete batches
        to the transfer stage while younger reads stay in flight (the
        read-side analogue of ``DeviceStream``'s ``drain="ready"``)."""
        if (self._view is not None or self._error is not None
                or self._released):
            return True
        try:
            self.wait(timeout=0.0)
            return True
        except TimeoutError:
            return False
        except OSError as e:
            self._error = e
            return True

    def release(self) -> None:
        if self._released:
            return
        rc = self._engine._lib.strom_release(self._engine._h, self._req_id)
        if rc == -errno.EBUSY:
            # Still in flight: the staging buffer is a live DMA target and
            # must not be recycled yet — wait for completion, then free.
            self._engine._lib.strom_wait(self._engine._h, self._req_id, None)
            self._engine._lib.strom_release(self._engine._h, self._req_id)
        self._released = True
        self._view = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


def wait_exact(pending, timeout: Optional[float] = None) -> np.ndarray:
    """``pending.wait(timeout)`` + strict length verification.

    For consumers whose read plans never cross EOF (index-derived
    ranges: the loader's sample/record plans, checkpoint tiles, weight
    slices, offload slots) a completed view shorter than the submit
    request can only mean file truncation or a device short read — and
    accepting it silently yields garbage-tailed tensors.  One helper so
    every consumer enforces the invariant identically instead of
    hand-rolling the check (works on PendingRead, FaultyRead, and
    ResilientRead alike via their ``length`` property).  TimeoutError
    passes through with the request still live (the ``wait`` contract);
    the short-read OSError releases the request first.
    """
    view = pending.wait(timeout)
    if view.nbytes != pending.length:
        pending.release()
        fh = getattr(pending, "fh", None)
        offset = getattr(pending, "offset", None)
        where = ("" if fh is None or fh < 0
                 else f" (fh={fh} offset={offset})")
        raise OSError(errno.EIO,
                      f"short read: got {view.nbytes} of "
                      f"{pending.length} expected bytes{where}")
    return view


def _wait_for_completion(engine: "StromEngine", req_id: int,
                         comp, timeout: Optional[float],
                         what: str) -> int:
    """strom_wait / strom_wait_timeout dispatch shared by reads and
    writes.  Raises TimeoutError with the request STILL LIVE (retry the
    wait or release() to abort)."""
    if timeout is None:
        return engine._lib.strom_wait(engine._h, req_id,
                                      ctypes.byref(comp))
    if timeout < 0:
        raise ValueError(f"timeout must be >= 0, got {timeout}")
    # cap at chrono's int64 nanoseconds — anything longer is forever
    ns = min(int(timeout * 1e9), (1 << 63) - 1)
    rc = engine._lib.strom_wait_timeout(engine._h, req_id,
                                        ctypes.byref(comp), ns)
    if rc == -errno.ETIMEDOUT:
        raise TimeoutError(f"{what} {req_id} still in flight after "
                           f"{timeout}s")
    return rc


class PendingWrite:
    def __init__(self, engine: "StromEngine", req_id: int,
                 keepalive: Optional[np.ndarray],
                 fh: int = -1, offset: int = -1):
        self._engine = engine
        self._req_id = req_id
        self._keepalive = keepalive  # zero-copy source must outlive the I/O
        #: submit-time identity + size, carried so short-write/error
        #: reports (and the resilient write-retry mirror) can name the
        #: exact range without re-deriving it
        self.fh = fh
        self.offset = offset
        self.length = keepalive.nbytes if keepalive is not None else 0
        self._released = False

    @property
    def ring(self) -> int:
        """Submission ring (failure-domain attribution, PendingRead
        parity)."""
        return int(self._req_id) & (_MAX_RINGS - 1)

    def release(self) -> None:
        """Abort/free path (e.g. after a wait timeout): blocks until
        the write is out of flight, then frees the request — the
        source buffer and any bounce staging return to the pool."""
        if self._released:
            return
        rc = self._engine._lib.strom_release(self._engine._h,
                                             self._req_id)
        if rc == -errno.EBUSY:
            self._engine._lib.strom_wait(self._engine._h, self._req_id,
                                         None)
            self._engine._lib.strom_release(self._engine._h,
                                            self._req_id)
        self._released = True
        self._keepalive = None
        # the abandoned write may still have (partially) landed
        self._engine._hostcache_write_done(self.fh, self.offset,
                                           self.length)

    def wait(self, timeout: Optional[float] = None) -> int:
        comp = _Completion()
        rc = _wait_for_completion(self._engine, self._req_id, comp,
                                  timeout, "write")
        n = int(comp.len)
        self._engine._lib.strom_release(self._engine._h, self._req_id)
        self._released = True
        self._keepalive = None
        # completion-side staleness guard (the submit-side bump alone
        # leaves a hole: a read admitted AFTER submit can complete with
        # pre-write bytes while the write is still in flight, and would
        # otherwise install them as a resident line)
        self._engine._hostcache_write_done(self.fh, self.offset,
                                           self.length)
        flight = self._engine.flight
        if rc < 0:
            e = OSError(-rc, os.strerror(-rc))
            e.engine_counted = True   # see PendingRead.wait: the C
            #                           ring counter has this failure
            if flight is not None:
                flight.record("write", getattr(self, "op_klass", None),
                              self.ring, self.fh, self.offset, 0, 0,
                              "error", err=-rc)
            raise e
        tracer = self._engine.tracer
        if tracer is not None and tracer.enabled:
            tracer.add_span("strom.write", int(comp.submit_ns),
                            int(comp.complete_ns),
                            ctx=getattr(self, "trace_ctx", NO_CONTEXT),
                            bytes=n)
        if flight is not None:
            flight.record(
                "write", getattr(self, "op_klass", None), self.ring,
                self.fh, self.offset, n,
                max(0, int(comp.complete_ns - comp.submit_ns)) // 1000,
                "ok")
        return n


class StromEngine:
    """The userspace handle to the strom-io engine.

    One engine owns N submission rings (``EngineConfig.n_rings``; each
    an io_uring or worker pool reaping its own completions) over ONE
    locked staging pool — the MAP_GPU_MEMORY analogue, created once and
    reused for every transfer, deliberately global: buffers freed on
    any ring recycle to the oldest deferred request engine-wide, so
    ring pinning can never deadlock on pool pressure.  A sharded engine
    also owns the QoS scheduler that maps latency classes onto its
    rings (io/sched.py).  ``n_rings=1`` is exactly the pre-sharding
    engine: no scheduler, one ring, one pool.
    """

    def __init__(self, config: Optional[EngineConfig] = None,
                 stats: Optional[StromStats] = None,
                 tracer: Optional["Tracer"] = None):
        from nvme_strom_tpu.utils.trace import global_tracer
        self.config = config or EngineConfig()
        self.stats = stats if stats is not None else global_stats
        self.tracer = tracer if tracer is not None else global_tracer
        if self.tracer is not None and self.tracer.stats is None:
            # drop accounting must land in the block THIS engine
            # exports, or trace_spans_dropped can never reach the
            # strom_stat/watchdog warnings for private-stats engines
            # (first engine wins on a shared tracer)
            self.tracer.stats = self.stats
        self._lib = _load_lib()
        c = self.config
        n_buffers = max(
            2, min(64, c.buffer_pool_bytes // max(1, c.chunk_bytes)))
        # Ring count: explicit n_rings, or auto from CPU/NVMe topology —
        # capped by what the CONFIGURED engine can feed (each ring needs
        # >= 2 staging buffers and >= 1 queue slot, so a deliberately
        # tiny engine stays single-ring and keeps its exact pre-sharding
        # deferral behavior).
        n_rings = c.n_rings if c.n_rings > 0 else auto_ring_count()
        n_rings = max(1, min(n_rings, _MAX_RINGS, n_buffers // 2,
                             c.queue_depth))
        qd_ring = max(1, c.queue_depth // n_rings)
        bufs_ring = max(2, n_buffers // n_rings)
        # Unified pinned arena (io/arena.py, docs/PERF.md §6): carve the
        # staging pool out of the ONE process reservation so staging,
        # cache lines and bridge slabs share a single mapping + lock
        # policy.  Arena off/exhausted → the engine maps its own pool,
        # the exact pre-arena path (arena_fallbacks counts exhaustion).
        self._pool_slab = None
        from nvme_strom_tpu.io import arena as _arena
        pool_bytes = int(self._lib.strom_engine_pool_bytes(
            n_rings, bufs_ring, c.chunk_bytes, c.alignment))
        slab = (_arena.carve_or_none(pool_bytes, "staging",
                                     stats=self.stats,
                                     lock=c.lock_buffers)
                if pool_bytes else None)
        if slab is not None:
            self._h = self._lib.strom_engine_create_prealloc(
                n_rings, qd_ring, bufs_ring, c.chunk_bytes, c.alignment,
                1 if c.use_io_uring else 0, 1 if c.lock_buffers else 0,
                slab.addr, slab.nbytes)
            if not self._h:
                slab.release()
                slab = None
        if slab is None:
            self._h = self._lib.strom_engine_create_rings(
                n_rings, qd_ring, bufs_ring, c.chunk_bytes, c.alignment,
                1 if c.use_io_uring else 0, 1 if c.lock_buffers else 0)
        self._pool_slab = slab
        if not self._h:
            raise OSError(ctypes.get_errno(),
                          "strom_engine_create failed: "
                          + os.strerror(ctypes.get_errno()))
        self.n_rings = n_rings
        self.n_buffers = bufs_ring * n_rings
        self._qd_ring = qd_ring
        self._open_fhs: set[int] = set()
        self._last_lat_read: list[int] = [0] * _LAT_BUCKETS
        self._stripe: dict = {}   # fh → (chunk, members, extents)
        # fh → (dev, ino, mtime_ns, size): the stable file identity the
        # pinned-host tier keys its lines by (io/hostcache.py) — a file
        # modified between opens gets a new key, so stale lines never hit
        self._file_keys: dict = {}
        self._closed = False
        # failure-domain supervision (io/health.py): per-ring breakers,
        # hot restart, degraded buffered fallback.  STROM_BREAKER=0
        # removes the layer entirely (None = the exact pre-supervision
        # engine; every hook below is a cheap None check).
        self.supervisor = None
        from nvme_strom_tpu.utils.config import BreakerConfig
        bcfg = BreakerConfig()
        if bcfg.enabled:
            from nvme_strom_tpu.io.health import EngineSupervisor
            self.supervisor = EngineSupervisor(self, bcfg)
        # flight recorder (io/flightrec.py, docs/OBSERVABILITY.md):
        # always-on bounded ring of recent op records, dumped by the
        # health/SLO/watchdog triggers.  STROM_FLIGHT=0 removes it
        # (None = the exact pre-recorder wait path).
        self.flight = None
        from nvme_strom_tpu.utils.config import FlightConfig
        fcfg = FlightConfig()
        if fcfg.enabled:
            from nvme_strom_tpu.io.flightrec import FlightRecorder
            self.flight = FlightRecorder(fcfg, self.stats)
        # critical-path attribution (obs/attrib.py, STROM_ATTRIB=1):
        # the process collector rides this engine's tracer as a span
        # sink — span emission turns on (sink-only: nothing accumulates
        # in memory) and serving folds per-request trees at retire.
        # None (the default) is the exact pre-attribution engine.
        from nvme_strom_tpu.obs.attrib import attach as _attach_attrib
        self._attrib = _attach_attrib(self.tracer, self.stats)
        if self._attrib is not None and self.flight is not None:
            # every post-mortem dump opens with where recent requests'
            # time went
            self.flight.attrib = self._attrib
        # per-ring time-in-state ledger (obs/ledger.py): cumulative
        # busy/idle/stalled/restarting seconds, sampled at completion
        # reaping (time-gated below) and exported at every stats sync
        from nvme_strom_tpu.obs.ledger import RingTimeLedger
        self.ring_ledger = RingTimeLedger(n_rings)
        self._ring_sample_next = 0.0
        self._ring_counter_live = False
        # live debug endpoint (obs/debugsrv.py, STROM_DEBUG_PORT): one
        # loopback HTTP server per process serving /metrics /attrib
        # /ledger /flight /health /locks; off by default (None)
        from nvme_strom_tpu.obs.debugsrv import maybe_start_debug_server
        self._debug_srv = maybe_start_debug_server(self.stats,
                                                   engine=self)
        # opt-in OpenMetrics textfile writer (STROM_METRICS_FILE):
        # started once per process with the first engine's stats block.
        # When the writer observes THIS engine's block, its periodic
        # snapshots drain the C counters through sync_stats (detached
        # at close_all so a snapshot can never race engine teardown).
        from nvme_strom_tpu.utils.stats import maybe_start_metrics_writer
        self._metrics_writer = maybe_start_metrics_writer(self.stats)
        if (self._metrics_writer is not None
                and self._metrics_writer.stats is self.stats):
            self._metrics_writer.set_sync(self.sync_stats)
        else:
            self._metrics_writer = None
        # per-ring registration/SQPOLL gauge cache (refreshed only at
        # create and ring restart; sync_stats exports it without the
        # per-sync ring_info walk)
        self._zc_gauges = None
        self._refresh_zc_gauges()
        self.scheduler = None
        if n_rings > 1:
            from nvme_strom_tpu.utils.config import SchedConfig
            scfg = SchedConfig()
            if scfg.enabled:
                from nvme_strom_tpu.io.sched import (QoSScheduler,
                                                     default_policies)
                cap = scfg.max_inflight_per_ring or qd_ring
                self._ring_cap = max(1, cap)
                self.scheduler = QoSScheduler(
                    submit_ring=self._submit_readv_ring,
                    ring_free=self._ring_free_slots,
                    policies=default_policies(scfg.class_weights),
                    aging_rounds=scfg.aging_rounds,
                    stats=self.stats,
                    ring_cap=self._ring_cap,
                    tracer=self.tracer)

    # -- file handles ------------------------------------------------------

    def open(self, path: os.PathLike | str, writable: bool = False,
             force_buffered: bool = False) -> int:
        flags = (1 if writable else 0) | (2 if force_buffered else 0)
        fh = self._lib.strom_open(self._h, str(path).encode(), flags)
        if fh < 0:
            raise OSError(-fh, os.strerror(-fh), str(path))
        self._open_fhs.add(fh)
        # identity via fstat on the engine's OWN descriptor, never the
        # path: a rename racing the open (the checkpoint commit window)
        # could otherwise key one inode's cached bytes under another
        # file's identity
        ident = (ctypes.c_uint64 * 4)()
        if self._lib.strom_file_ident(self._h, fh, ident) == 0:
            self._file_keys[fh] = tuple(int(x) for x in ident)
        if self.config.stripe_accounting:
            self._setup_stripe(fh, path, writable=writable)
        return fh

    def file_key(self, fh: int) -> Optional[tuple]:
        """Stable identity of the file behind ``fh`` — what the
        pinned-host tier (io/hostcache.py) keys cache lines by; None
        when unknown (the tier then skips this fh)."""
        return self._file_keys.get(fh)

    def _setup_stripe(self, fh: int, path, writable: bool = False) -> None:
        """Per-member attribution geometry for this file (SURVEY.md §6:
        the reference's striped claim implies knowing which member
        served which byte).  Real geometry comes from the backing
        md-raid0 (sysfs chunk + member walk); STROM_STRIPE_SIM=
        "<chunk_kib>:<n>" imposes synthetic geometry on any device so
        the attribution path is exercisable without raid hardware.
        Synthetic (FIEMAP-less) extents attribute by logical offset —
        best effort, flagged by the extent itself."""
        sim = os.environ.get("STROM_STRIPE_SIM")
        if sim:
            try:
                chunk_kib, n = sim.split(":")
                chunk = int(chunk_kib) << 10
                members = tuple(f"sim{i}" for i in range(int(n)))
                if chunk <= 0 or not members:
                    raise ValueError
            except ValueError:
                raise ValueError(
                    f"STROM_STRIPE_SIM={sim!r}: expected "
                    "'<chunk_kib>:<n_members>' with positive integers")
            # simulated geometry attributes by LOGICAL offset (one
            # unbounded pseudo extent with physical == logical):
            # deterministic regardless of fs placement, and valid for
            # GROWING files too (the write path)
            extents = [Extent(0, 0, 1 << 62, 0)]
            self._stripe[fh] = (chunk, members, extents, [0])
            return
        if writable:
            # a real-raid extent map of a growing file is a moving
            # target — write attribution is sim-geometry only
            return
        else:
            info = resolve_device(path)
            if not (info.is_raid and info.raid_level == 0
                    and len(info.members) > 1):
                return
            chunk = md_chunk_bytes(info.device)
            if chunk <= 0:
                return
            members = info.members
            extents = sorted(file_extents(path),
                             key=lambda e: e.logical)
        self._stripe[fh] = (chunk, members, extents,
                            [e.logical for e in extents])

    def _attr_stripe(self, fh: int, offset: int, length: int) -> None:
        st = self._stripe.get(fh)
        if st is None:
            return
        chunk, members, extents, logicals = st
        lib = self._lib
        buf = (ctypes.c_uint64 * len(members))()
        # extents are sorted by logical: bisect to the first overlap and
        # stop past the range (fragmented files can map to thousands of
        # extents; a full scan per submit would dominate the hot path)
        i = bisect.bisect_right(logicals, offset) - 1
        for e in extents[max(i, 0):]:
            if e.logical >= offset + length:
                break
            lo = max(offset, e.logical)
            hi = min(offset + length, e.logical + e.length)
            if lo >= hi:
                continue
            phys = e.physical + (lo - e.logical)
            lib.strom_stripe_attr(phys, hi - lo, chunk, len(members),
                                  buf)
        self.stats.add_member_bytes(members, list(buf))

    def close(self, fh: int) -> None:
        self._lib.strom_close(self._h, fh)
        self._open_fhs.discard(fh)
        self._stripe.pop(fh, None)
        self._file_keys.pop(fh, None)

    def file_size(self, fh: int) -> int:
        n = self._lib.strom_file_size(self._h, fh)
        if n < 0:
            raise OSError(-n, os.strerror(-n))
        return n

    def file_is_direct(self, fh: int) -> bool:
        return self._lib.strom_file_is_direct(self._h, fh) == 1

    # -- rings -------------------------------------------------------------

    def ring_info(self, ring: int) -> dict:
        """One ring's occupancy/counters (strom_get_ring_info)."""
        info = _RingInfo()
        rc = self._lib.strom_get_ring_info(self._h, ring,
                                           ctypes.byref(info))
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return {n: int(getattr(info, n)) for n, _ in _RingInfo._fields_}

    def ring_depths(self) -> list:
        """Per-ring in-flight I/O (submitted - completed) via the
        lock-free depth-only C path — the scheduler's admission polls
        this at dispatch frequency, so it must never contend with the
        pool mutex the data path is hammering (strom_ring_inflight, not
        the full strom_get_ring_info)."""
        return [max(0, int(self._lib.strom_ring_inflight(self._h, r)))
                for r in range(self.n_rings)]

    def _ring_free_slots(self) -> list:
        cap = getattr(self, "_ring_cap", self._qd_ring)
        free = [max(0, cap - d) for d in self.ring_depths()]
        if self.supervisor is not None:
            # the scheduler's admission poll doubles as the supervision
            # heartbeat (time-gated inside), and tripped rings report
            # zero headroom so new batches route around them
            self.supervisor.tick()
            free = self.supervisor.mask_free_slots(free)
        return free

    def _sample_ring_states(self) -> None:
        """Time-gated per-ring time-in-state sample (obs/ledger.py):
        charges the elapsed interval to each ring's current state
        (busy/idle/stalled) from the lock-free depth counters and the
        supervisor's breaker verdicts.  Called from completion reaping
        and stat syncs; ~10 Hz cap keeps it off the hot path."""
        now = time.monotonic()
        if now < self._ring_sample_next or self._closed:
            return
        self._ring_sample_next = now + 0.1
        states = (self.supervisor.ring_states()
                  if self.supervisor is not None else None)
        try:
            self.ring_ledger.sample(self.ring_depths(), states, now=now)
        except OSError:
            pass

    def _refresh_zc_gauges(self) -> None:
        """Snapshot the per-ring registration/SQPOLL state (changes only
        at engine create and ring restart — the two callers)."""
        try:
            ri = [self.ring_info(r) for r in range(self.n_rings)]
            self._zc_gauges = dict(
                ring_fixed_bufs=[r["fixed_bufs"] for r in ri],
                ring_reg_files=[r["reg_files"] for r in ri],
                ring_sqpoll=[r["sqpoll"] for r in ri],
                pool_arena=1 if self._pool_slab is not None else 0)
        except OSError:
            self._zc_gauges = None

    def ring_restart(self, ring: int, drain_timeout_s: float = 0.5) -> int:
        """Hot-restart one ring (``strom_ring_restart``): cancel its
        stall-parked backlog (-ECANCELED — the waiters' retry loop is
        the requeue path), drain dispatched I/O bounded, rebuild the
        uring, resume.  Returns the number of requests cancelled for
        requeue; raises TimeoutError when in-flight I/O would not
        drain (the ring resumes untouched — fall back to degraded
        reads), OSError otherwise."""
        ns = max(1, int(drain_timeout_s * 1e9))
        t0 = time.monotonic()
        rc = self._lib.strom_ring_restart(self._h, ring, ns)
        # the restart window is charged explicitly: it is a rare,
        # bounded interval the ~10 Hz state sampler would mostly miss
        self.ring_ledger.note_restart(ring, time.monotonic() - t0)
        if rc == -errno.ETIMEDOUT:
            raise TimeoutError(
                f"ring {ring}: in-flight I/O did not drain within "
                f"{drain_timeout_s}s; restart aborted")
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        # the rebuilt uring re-registered buffers/files and re-armed
        # SQPOLL (or fell back to the worker pool): refresh the cached
        # registration gauges sync_stats exports
        self._refresh_zc_gauges()
        return int(rc)

    def set_ring_stall(self, ring: int, on: bool = True) -> None:
        """Arm/disarm the C-level ring-stall injection (chaos/tests):
        while armed the ring parks every dispatch — completions never
        arrive, exactly what a wedged uring looks like.  Disarm
        dispatches the backlog; ``ring_restart`` cancels it instead."""
        rc = self._lib.strom_set_ring_stall(self._h, ring,
                                            1 if on else 0)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def read_buffered(self, fh: int, offset: int, length: int
                      ) -> np.ndarray:
        """Degraded-mode primitive: one synchronous buffered ``pread``
        into a caller-owned array — no ring, no staging pool (counted
        fallback + bounce).  Returns the bytes actually read (short
        only at EOF)."""
        arr = np.empty(max(0, length), dtype=np.uint8)
        if length <= 0:
            return arr
        n = self._lib.strom_read_buffered(
            self._h, fh, offset, length,
            arr.ctypes.data_as(ctypes.c_void_p))
        if n < 0:
            raise OSError(-n, os.strerror(-n))
        return arr[:int(n)]

    # -- reads -------------------------------------------------------------

    def submit_read(self, fh: int, offset: int, length: int,
                    klass: Optional[str] = None,
                    ring: Optional[int] = None) -> PendingRead:
        """Scalar read.  Scalar submissions route round-robin across
        rings (``ring`` pins one) and never queue at the scheduler:
        they are the retry/hedge/probe path, where added queueing delay
        would fight the recovery that issued them.  ``klass`` is
        accepted for API symmetry (wrappers use it for per-class
        budgets) and stamped onto the pending for flight-recorder
        attribution; it does not affect scalar routing."""
        if length > self.config.chunk_bytes:
            raise ValueError(
                f"read length {length} exceeds chunk_bytes "
                f"{self.config.chunk_bytes}; split the range")
        if ring is None and self.supervisor is not None:
            # route around rings with an open breaker (None = all
            # trusted, keep the C round-robin): this is what lands a
            # requeued extent's resubmission on a HEALTHY ring
            ring = self.supervisor.pick_ring()
        if ring is None:
            rid = self._lib.strom_submit_read(self._h, fh, offset, length)
        else:
            rid = self._lib.strom_submit_read_ring(self._h, ring, fh,
                                                   offset, length)
        if rid < 0:
            raise OSError(-rid, os.strerror(-rid))
        if self._stripe:
            self._attr_stripe(fh, offset, length)
        pending = PendingRead(self, rid, length, fh=fh, offset=offset)
        if klass is not None:
            pending.op_klass = klass
        if self.tracer is not None and self.tracer.enabled:
            # causal attachment (docs/OBSERVABILITY.md): the completion
            # span may be waited on another thread — carry the child
            # context explicitly instead of relying on the contextvar
            from nvme_strom_tpu.utils.trace import attach_context
            pending.trace_ctx = attach_context()
        return pending

    def _submit_readv_ring(self, reads, ring: Optional[int]) -> list:
        """Raw vectored submission to one ring (or C round-robin when
        ``ring`` is None) — the scheduler's dispatch callback; no
        scheduler re-entry."""
        reads = list(reads)
        n = len(reads)
        exts = (_RdExt * n)()
        for i, (fh, offset, length) in enumerate(reads):
            exts[i].fh = fh
            exts[i].offset = offset
            exts[i].length = length
        rids = (ctypes.c_int64 * n)()
        if ring is None and self.supervisor is not None:
            # scheduler-less batches (single ring, STROM_SCHED=0) still
            # avoid rings with an open breaker
            ring = self.supervisor.pick_ring()
        if ring is None:
            rc = self._lib.strom_submit_readv(self._h, exts, n, rids)
        else:
            rc = self._lib.strom_submit_readv_ring(self._h, ring, exts,
                                                   n, rids)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        if self._stripe:
            for fh, offset, length in reads:
                self._attr_stripe(fh, offset, length)
        out = [PendingRead(self, int(rids[i]), reads[i][2],
                           fh=reads[i][0], offset=reads[i][1])
               for i in range(n)]
        if self.tracer is not None and self.tracer.enabled:
            from nvme_strom_tpu.utils.trace import attach_context
            for p in out:
                p.trace_ctx = attach_context()
        return out

    def submit_readv(self, reads, klass: Optional[str] = None,
                     ring: Optional[int] = None) -> list:
        """Vectored submission: one C call, one io_uring doorbell for the
        whole batch (``strom_submit_readv``).

        ``reads``: sequence of ``(fh, offset, length)``.  Returns one
        PendingRead per input extent, in order — each waits/releases
        exactly like a ``submit_read`` result.  Validation is atomic:
        on ValueError/OSError nothing was submitted.  This is the L2
        boundary the extent-coalescing planner (io/plan.py) submits
        through; calling it directly is fine for pre-split ranges.

        ``klass``: the batch's latency class.  On a sharded engine the
        QoS scheduler (io/sched.py) gates dispatch — the call may block
        behind higher classes under contention, exactly the admission
        control that protects decode-critical reads.  ``ring`` pins a
        ring and bypasses the scheduler (the scheduler's own dispatch
        path; also handy in tests).  Single-ring engines have no
        scheduler: every batch submits immediately, the pre-sharding
        behavior.
        """
        reads = list(reads)
        if not reads:
            return []
        chunk = self.config.chunk_bytes
        for fh, offset, length in reads:
            if length > chunk:
                raise ValueError(
                    f"read length {length} exceeds chunk_bytes "
                    f"{chunk}; split the range (io/plan.py does)")
        if self.scheduler is not None and ring is None:
            return self.scheduler.submit(reads, klass)
        out = self._submit_readv_ring(reads, ring)
        if klass is not None:
            for p in out:
                p.op_klass = klass   # flight-recorder attribution
        return out

    def read(self, fh: int, offset: int, length: int) -> np.ndarray:
        """Synchronous convenience read returning an *owning* array.

        The copy out of the staging buffer is counted as bounce bytes — use
        ``submit_read`` + the JAX bridge for the zero-copy path.
        """
        with self.submit_read(fh, offset, length) as p:
            out = p.wait().copy()
        self.stats.add(bounce_bytes=int(out.nbytes))
        return out

    # -- writes ------------------------------------------------------------

    def submit_write(self, fh: int, offset: int,
                     data: np.ndarray) -> PendingWrite:
        arr = np.ascontiguousarray(data).view(np.uint8).reshape(-1)
        ptr = arr.ctypes.data_as(ctypes.c_void_p)
        ring = (self.supervisor.pick_ring()
                if self.supervisor is not None else None)
        if ring is None:
            rid = self._lib.strom_submit_write(self._h, fh, offset, ptr,
                                               arr.nbytes)
        else:
            # checkpoint/KV writes route around rings with an open
            # breaker exactly like scalar reads do — a ResilientWrite
            # retry must never resubmit into the condemned domain
            rid = self._lib.strom_submit_write_ring(
                self._h, ring, fh, offset, ptr, arr.nbytes)
        if rid < 0:
            raise OSError(-rid, os.strerror(-rid))
        if self._stripe:
            self._attr_stripe(fh, offset, arr.nbytes)
        # staleness guard, submit side: a cached line overlapping a
        # write must never serve the pre-write bytes (kv/optimizer slot
        # rewrites read their pages back through the same planner);
        # PendingWrite invalidates AGAIN at completion — see wait()
        self._hostcache_write_done(fh, offset, arr.nbytes)
        pending = PendingWrite(self, rid, arr, fh=fh, offset=offset)
        if self.tracer is not None and self.tracer.enabled:
            from nvme_strom_tpu.utils.trace import attach_context
            pending.trace_ctx = attach_context()
        return pending

    def _hostcache_write_done(self, fh: int, offset: int,
                              length: int) -> None:
        """Drop host-tier lines overlapping a write and bump the file's
        invalidation epoch (voiding in-flight admitted fills) — called
        at write submit AND completion so no read/write interleaving
        can persist pre-write bytes in the tier."""
        fkey = self._file_keys.get(fh)
        if fkey is not None and length > 0:
            from nvme_strom_tpu.io.hostcache import notify_write
            notify_write(fkey, offset, length, stats=self.stats)

    # -- stats / lifecycle -------------------------------------------------

    def latency_histogram(self) -> dict:
        """Per-request submit→complete latency, log2-ns buckets: entry i of
        each list counts SUCCESSFUL requests whose latency fell in
        [2^i, 2^(i+1)) ns (failures are excluded — they complete near-
        instantly and would drag the percentiles down; count them via
        requests_failed).  The per-request upgrade over the reference's
        aggregate-only STAT_INFO counters (SURVEY.md §5 Tracing)."""
        rd = (ctypes.c_uint64 * _LAT_BUCKETS)()
        wr = (ctypes.c_uint64 * _LAT_BUCKETS)()
        self._lib.strom_get_latency(self._h, rd, wr)
        return {"read": [int(x) for x in rd], "write": [int(x) for x in wr]}

    def latency_percentiles(self, kind: str = "read",
                            ps=(50, 90, 99)) -> dict:
        """Approximate percentiles (ns) from the log2 histogram."""
        from nvme_strom_tpu.utils.stats import percentiles_from_log2_hist
        return percentiles_from_log2_hist(self.latency_histogram()[kind], ps)

    def pool_info(self) -> dict:
        """Staging-pool occupancy — LIST/INFO_GPU_MEMORY analogue
        (SURVEY.md §2 "GPU memory mapper")."""
        info = _PoolInfo()
        self._lib.strom_get_pool_info(self._h, ctypes.byref(info))
        return {n: int(getattr(info, n)) for n, _ in _PoolInfo._fields_}

    def engine_stats(self) -> dict:
        blk = _StatsBlk()
        self._lib.strom_get_stats(self._h, ctypes.byref(blk))
        return {n: int(getattr(blk, n)) for n, _ in _StatsBlk._fields_}

    def sync_stats(self) -> dict:
        """Atomically drain engine counters into the Python StromStats block
        (per-counter exchange in C — no increment can fall between read and
        reset)."""
        blk = _StatsBlk()
        self._lib.strom_drain_stats(self._h, ctypes.byref(blk))
        snap = {n: int(getattr(blk, n)) for n, _ in _StatsBlk._fields_}
        self.stats.merge_engine(snap)
        # Interval percentiles (diff vs the previous sync), matching the
        # per-interval semantics of the drained counters — a cumulative
        # histogram would bury a fresh latency regression under hours of
        # old samples.
        from nvme_strom_tpu.utils.stats import percentiles_from_log2_hist
        cur = self.latency_histogram()["read"]
        interval = [max(0, c - p)  # a reset_stats between syncs clamps to 0
                    for c, p in zip(cur, self._last_lat_read)]
        self._last_lat_read = cur
        pct = percentiles_from_log2_hist(interval, ps=(50, 99))
        if any(pct.values()):
            self.stats.set_gauges(lat_read_p50_us=pct[50] / 1000.0,
                                  lat_read_p99_us=pct[99] / 1000.0)
        if self.n_rings > 1:
            # instantaneous per-ring queue depth: the scheduler block in
            # strom_stat/watchdog reads these next to the sched counters
            self.stats.set_gauges(ring_depths=self.ring_depths())
        # ring time-in-state accounting (obs/ledger.py): sample at the
        # sync boundary too (an idle engine still accumulates idle
        # time), then publish the ring_state_s gauge every exporter
        # rides — and a Perfetto counter track when a trace is live, so
        # per-ring in-flight lands on the spans' own timeline
        self._sample_ring_states()
        self.ring_ledger.export(self.stats)
        if (self.n_rings > 1 and self.tracer is not None
                and getattr(self.tracer, "exports", False)):
            try:
                depths = self.ring_depths()
                # emit while I/O is in flight, plus ONE trailing all-
                # zero sample so the Perfetto track returns to zero —
                # and an idle engine's stat syncs add no events at all
                # (tests pin exact span counts around idle syncs)
                live = any(depths)
                if live or self._ring_counter_live:
                    self.tracer.add_counter(
                        "strom.ring.inflight",
                        {str(i): d for i, d in enumerate(depths)})
                self._ring_counter_live = live
            except OSError:
                pass
        # zero-copy submission state (docs/PERF.md §6): per-ring
        # fixed-buffer / registered-file / SQPOLL gauges, so a try_register
        # that silently soft-failed (old kernel, RLIMIT_MEMLOCK) shows in
        # strom_stat's engine block instead of only as missing throughput.
        # Served from the cache refreshed at create/restart — this state
        # only changes then, and the full strom_get_ring_info walk holds
        # each ring mutex over its request map, too heavy for a path the
        # watchdog and metrics writer hit at stat frequency.
        if self._zc_gauges is not None:
            self.stats.set_gauges(**self._zc_gauges)
        if self.supervisor is not None:
            # a stat sync is a natural supervision heartbeat, and the
            # health gauges (ring_health / engine_degraded) ride the
            # same export the counters do
            self.supervisor.tick()
        self.stats.maybe_export()  # keep strom_stat --watch observers live
        return snap

    @property
    def backend(self) -> str:
        return "io_uring" if self._lib.strom_backend_is_uring(self._h) \
            else "threadpool"

    def close_all(self) -> None:
        if self._closed:
            return
        if self._metrics_writer is not None:
            # detach BEFORE teardown (blocks on any in-flight periodic
            # drain, so no snapshot can touch the dying handle) — but
            # compare-and-clear: a later engine on the same shared
            # stats block may have installed ITS hook over ours
            self._metrics_writer.detach_sync(self.sync_stats)
            self._metrics_writer = None
        if self._debug_srv is not None:
            # the debug server outlives engines (process-wide); just
            # stop routing live-engine queries at this dying handle
            self._debug_srv.detach_engine(self)
            self._debug_srv = None
        if self.supervisor is not None:
            # release landed probe zombies and stop supervising before
            # the C handle dies under a tick's ring poll
            self.supervisor.close()
        if self.scheduler is not None:
            # wake any thread still blocked in a grant loop BEFORE the C
            # handle dies under its capacity poll (it raises ECANCELED)
            self.scheduler.close()
        self.sync_stats()  # drains counters and exports the final snapshot
        self._lib.strom_engine_destroy(self._h)
        self._closed = True
        if self._pool_slab is not None:
            # the staging carve returns to the arena only AFTER destroy
            # drained every in-flight DMA targeting it
            self._pool_slab.release()
            self._pool_slab = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close_all()

    def __del__(self):
        try:
            if not getattr(self, "_closed", True):
                self._lib.strom_engine_destroy(self._h)
                self._closed = True
                slab = getattr(self, "_pool_slab", None)
                if slab is not None:
                    slab.release()
                    self._pool_slab = None
        except Exception:
            pass
