"""QoS-aware I/O scheduler — latency classes over the multi-ring engine.

Under production mixed traffic every consumer used to funnel through ONE
io_uring ring: a scrub or bulk-prefetch storm queued ahead of
decode-critical KV reads and the p99 the serving path promised was gone.
The engine now shards into N rings over one global staging pool
(``strom_engine_create_rings``, ``EngineConfig.n_rings``); this module
decides WHICH planned batch goes to WHICH ring, and WHEN:

  classes     every planned batch carries a latency class —
              ``decode`` > ``restore`` > ``prefetch`` > ``scan`` >
              ``scrub`` (priority order).  Consumers tag their traffic at the
              ``io/plan.py`` boundary (``plan_and_submit(...,
              klass=...)``); untagged batches ride the default
              ``prefetch`` class so the fair-share always sees the
              whole load.
  fair-share  each dispatch round credits every backlogged class its
              WEIGHT in batches (deficit round-robin, at most one
              round of banking), then serves classes in priority
              order — under contention class shares converge to the
              weight ratio, while an idle system dispatches everything
              immediately.
  aging       a batch stuck longer than ``aging_rounds`` dispatch
              rounds is promoted ahead of every weight/priority
              consideration: the starvation bound.  Even a weight-0
              class completes within K rounds of queueing
              (tests/test_sched.py proves it).
  admission   a ring accepts a batch while its in-flight I/O
              (submitted - COMPLETED, lock-free C counters) is under
              the per-ring budget; batches pick the least-loaded
              eligible ring.  Completion — not release — frees
              capacity, so a consumer sitting on completed views can
              never wedge admission (deadlock-free by construction).

Dispatch is split grant/execute: the scheduler lock covers only the
ADMISSION DECISION (which batch, which ring, when), and each owner
thread performs its own engine submission outside the lock — concurrent
submitters overlap exactly as they would with no scheduler, so the QoS
layer adds ordering, never serialization.  ``submit()`` blocks until
the caller's batch is granted, and the blocked thread helps run grant
rounds, so higher-priority batches queued by other threads are granted
first — exactly the admission control that keeps a scrub storm out of
the decode class's way.  Per-class hedge/retry budgets live in
``io/resilient.py`` (``ResilientEngine(class_configs=...)``) keyed by
the same class names.

Every decision is accounted: ``StromStats.sched_*`` counters, per-class
dispatch/queue-wait tallies (``class_stats`` in the export), and
per-ring depth gauges — rendered by ``strom_stat``'s scheduler block,
watchdog dumps, and bench.py's mixed-workload scenario.

Failure domains (io/health.py, docs/RESILIENCE.md): the ``ring_free``
callback the engine binds here is supervision-aware — a ring whose
circuit breaker is OPEN reports zero admission headroom, so every
queued batch routes to healthy rings until the hot restart brings the
ring back half-open; the admission poll doubles as the supervision
heartbeat (time-gated ``tick`` inside the callback).  The scheduler
itself never sees an all-masked ring set: the device-level breaker
(whose open state diverts traffic to the degraded buffered path at the
planner boundary, above this layer) is decided atomically with the
last ring trip.
"""

from __future__ import annotations

import errno
import threading
import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional, Sequence

from nvme_strom_tpu.io.tenants import current_tenant
from nvme_strom_tpu.utils.lockwitness import make_condition, make_lock

#: priority order, highest first — the serving decode path outranks
#: checkpoint/weight restore, which outranks loader prefetch, which
#: outranks analytics scans (sql/), which outrank background scrub
CLASS_ORDER = ("decode", "restore", "prefetch", "scan", "scrub")

#: class every untagged batch rides (bulk by assumption)
DEFAULT_CLASS = "prefetch"


@dataclass(frozen=True)
class ClassPolicy:
    """One latency class's scheduling + resilience-budget policy.

    ``weight``: fair-share credits per dispatch round (batches).
    ``hedge_budget``: max CONCURRENT hedged duplicate reads this class
    may hold (io/resilient.py enforces it — a scrub storm exhausting
    its own budget can never eat the decode class's hedges).
    ``max_retries``: per-class override of ResilientConfig.max_retries
    (None = inherit the engine-wide value).
    """

    name: str
    priority: int          # position in CLASS_ORDER; lower serves first
    weight: float = 1.0
    hedge_budget: int = 4
    max_retries: Optional[int] = None

    def __post_init__(self):
        if self.weight < 0:
            raise ValueError(f"weight ({self.weight}) must be >= 0")
        if self.hedge_budget < 0:
            raise ValueError("hedge_budget must be >= 0")


def default_policies(weights: str = "") -> Dict[str, ClassPolicy]:
    """The five stock policies; ``weights`` ("decode=8,scrub=1")
    overrides weights per class (SchedConfig.class_weights).

    ``scan`` is the analytics class (sql/ Direct SQL scans — partition-
    parallel workers all submit here): same weight as prefetch so a
    table scan and a loader share bulk bandwidth evenly, but BELOW it
    in priority — an aggressor scan drains after serving-adjacent
    prefetch, and far after decode (tests/test_sql_scan.py proves the
    decode-under-scan-storm bound)."""
    pol = {
        "decode": ClassPolicy("decode", 0, weight=8.0, hedge_budget=8),
        "restore": ClassPolicy("restore", 1, weight=4.0, hedge_budget=4),
        "prefetch": ClassPolicy("prefetch", 2, weight=2.0, hedge_budget=2),
        "scan": ClassPolicy("scan", 3, weight=2.0, hedge_budget=2),
        "scrub": ClassPolicy("scrub", 4, weight=1.0, hedge_budget=1),
    }
    for part in filter(None, (s.strip() for s in weights.split(","))):
        name, eq, val = part.partition("=")
        name = name.strip()
        if not eq or name not in pol:
            raise ValueError(
                f"STROM_CLASS_WEIGHTS entry {part!r}: expected "
                f"<class>=<weight> with class in {CLASS_ORDER}")
        pol[name] = replace(pol[name], weight=float(val))
    return pol


class _Batch:
    """One planned batch queued for a dispatch grant."""

    __slots__ = ("spans", "klass", "rounds", "granted", "ring",
                 "promoted", "t_enq", "t_enq_ns", "ctx", "tenant")

    def __init__(self, spans, klass: str, ctx=None):
        self.spans = spans
        self.klass = klass
        self.rounds = 0          # dispatch rounds survived ungranted
        self.granted = False     # admission decision made
        self.ring: Optional[int] = None
        self.promoted = False    # granted via the aging bound
        self.t_enq = time.monotonic()
        self.t_enq_ns = time.monotonic_ns()
        #: requester's TraceContext, captured at enqueue — the grant may
        #: run on ANOTHER thread's dispatch round, so the queue-wait
        #: span carries its causal identity explicitly
        self.ctx = ctx
        #: owning Tenant, captured from the tenant contextvar exactly
        #: like the trace context (None outside any tenant scope — the
        #: whole hierarchical layer below then stays inert)
        self.tenant = current_tenant()


class QoSScheduler:
    """Weighted fair-share + aging dispatcher over N rings.

    ``submit_ring(spans, ring) -> pendings`` performs the actual engine
    submission (StromEngine binds its ring-pinned vectored submit);
    ``ring_free() -> [free slots per ring]`` reports admission headroom.
    Both are injectable, so the dispatch logic is testable with no
    hardware and no engine (tests/test_sched.py drives ``step()``
    directly).
    """

    #: helper-drain poll slice while waiting for ring capacity — I/O
    #: completion frees capacity asynchronously and is not signalled
    _POLL_S = 0.002

    def __init__(self, submit_ring: Callable[[Sequence, int], list],
                 ring_free: Callable[[], List[int]],
                 policies: Optional[Dict[str, ClassPolicy]] = None,
                 aging_rounds: int = 16, stats=None,
                 ring_cap: Optional[int] = None, tracer=None):
        if aging_rounds < 1:
            raise ValueError("aging_rounds must be >= 1")
        self._submit_ring = submit_ring
        self._ring_free = ring_free
        self.policies = policies or default_policies()
        self.aging_rounds = aging_rounds
        self.stats = stats
        #: span sink for queue-wait attribution (strom.sched.queue);
        #: None = no tracing overhead on dispatch
        self.tracer = tracer
        #: per-ring admission budget (what a fully idle ring reports
        #: free) — lets the urgent-ring rule tell "ring 0 is idle" from
        #: "every ring is equally saturated"
        self.ring_cap = ring_cap
        self._order = sorted(self.policies,
                             key=lambda k: self.policies[k].priority)
        self._queues: Dict[str, deque] = {k: deque() for k in self._order}
        self._deficit: Dict[str, float] = {k: 0.0 for k in self._order}
        # hierarchical fair-share inner level (class × tenant): per
        # class, each tenant's accumulated grant cost (1/effective
        # weight per grant — lowest bank serves next).  Empty, and the
        # pick short-circuits to exact FIFO, until the first batch that
        # actually carries a tenant flips _tenant_seen.
        self._tenant_credit: Dict[str, Dict] = {}
        self._tenant_seen = False
        self._granted_out: Dict[int, int] = {}  # ring -> spans granted,
        #                                         not yet engine-submitted
        self._closed = False
        self._lock = make_lock("sched.QoSScheduler._lock")
        self._cv = make_condition("sched.QoSScheduler._cv", self._lock)
        # counters mirrored into StromStats when one is attached
        self.dispatches = 0
        self.promotions = 0
        self.enqueued = 0
        # Perfetto counter-track sampling gate (docs/OBSERVABILITY.md):
        # per-class queue depth lands on the trace timeline at most
        # every 20 ms, so a hot dispatch loop never floods the file
        self._next_counter_t = 0.0

    # -- public API --------------------------------------------------------

    def enqueue(self, spans: Sequence, klass: Optional[str] = None
                ) -> _Batch:
        """Queue one planned batch for a grant WITHOUT waiting (tests
        drive ``step()`` against this; ``submit()`` is the blocking
        production path)."""
        if klass not in self.policies:
            klass = DEFAULT_CLASS
        # NO_CONTEXT, not None, when untraced/out-of-scope: the grant
        # may run on ANOTHER request's thread, and ctx=None at emit
        # would auto-adopt that request's context (mis-attribution)
        from nvme_strom_tpu.utils.trace import NO_CONTEXT, attach_context
        ctx = NO_CONTEXT
        if self.tracer is not None and self.tracer.enabled:
            ctx = attach_context()
        b = _Batch(list(spans), klass, ctx=ctx)
        with self._cv:
            if self._closed:
                raise OSError(errno.ECANCELED,
                              "engine closing: scheduler shut down")
            self._queues[klass].append(b)
            if b.tenant is not None:
                self._tenant_seen = True
            self.enqueued += 1
            if self.stats is not None:
                self.stats.add(sched_enqueued=1)
        return b

    def submit(self, spans: Sequence, klass: Optional[str] = None) -> list:
        """Queue one planned batch under ``klass``, block until the
        scheduler GRANTS it a ring, then perform the engine submission
        — outside the scheduler lock, so concurrent submitters overlap
        exactly as they would with no scheduler (the lock covers only
        the admission decision).  Returns the engine pendings aligned
        with ``spans``; raises whatever the engine submission raised."""
        b = self.enqueue(spans, klass)
        with self._cv:
            while not b.granted:
                if self._closed:
                    # engine teardown: wake OUT of the grant loop before
                    # the C handle dies under the capacity poll
                    try:
                        self._queues[b.klass].remove(b)
                    except ValueError:
                        pass
                    raise OSError(errno.ECANCELED,
                                  "engine closing: batch never granted")
                self._drain_locked()
                if b.granted:
                    break
                # capacity frees when in-flight I/O completes (lock-free
                # C counters, not signalled): poll in short slices; a
                # grant by another thread's round notifies immediately
                self._cv.wait(timeout=self._POLL_S)
        try:
            out = self._submit_ring(b.spans, b.ring)
            for p in out:
                try:
                    p.op_klass = b.klass   # flight-recorder attribution
                except AttributeError:
                    break   # injected test double without a __dict__
            return out
        finally:
            self.ack_submitted(b)

    def ack_submitted(self, b: _Batch) -> None:
        """Hand a granted batch's capacity charge over to the engine's
        own in-flight counters (call once the engine submission landed
        — ``submit()`` does; tests driving ``enqueue``/``step`` call it
        explicitly)."""
        with self._cv:
            if b.ring is not None:
                self._granted_out[b.ring] = \
                    self._granted_out.get(b.ring, 0) - max(1, len(b.spans))
            self._cv.notify_all()

    def close(self) -> None:
        """Quiesce before engine teardown: every thread blocked in
        ``submit()``'s grant loop wakes and raises ECANCELED instead of
        polling ring state on a handle about to be destroyed.  Further
        submissions are refused.  StromEngine.close_all calls this
        first."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def step(self) -> bool:
        """Run ONE dispatch round (test/diagnostic hook); True if any
        batch was granted a ring."""
        with self._cv:
            return self._dispatch_round_locked()

    def queued(self) -> Dict[str, int]:
        """Per-class queued batch counts (diagnostics)."""
        with self._lock:
            return {k: len(q) for k, q in self._queues.items()}

    def backlog(self) -> Dict[str, Dict[str, float]]:
        """Per-class queue depth with span counts and oldest wait — the
        richer sibling of queued(), built for post-mortem payloads (the
        coldstart_stall flight dump records which lane was starving)."""
        now = time.monotonic()
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for k, q in self._queues.items():
                if not q:
                    continue
                out[k] = {
                    "batches": len(q),
                    "spans": sum(len(b.spans) for b in q),
                    "oldest_wait_s": round(now - q[0].t_enq, 6),
                }
        return out

    def set_weight(self, klass: str, weight: float) -> None:
        """Adjust one class's fair-share weight at runtime — the SLO
        governor's scheduler lever (docs/PERF.md §5): a decode-path p99
        violation temporarily raises the decode class's share, and the
        governor lowers it back when the target is met again.  Priority
        order and the aging bound are untouched, so the starvation
        guarantee survives any weight setting (weight 0 included)."""
        with self._lock:
            p = self.policies.get(klass)
            if p is None:
                raise KeyError(f"unknown class {klass!r} "
                               f"(have {sorted(self.policies)})")
            self.policies[klass] = replace(p, weight=float(weight))

    # -- dispatch core -----------------------------------------------------

    def _pick_index_locked(self, klass: str, q: deque) -> int:
        """Hierarchical DRR, inner (tenant) level: among ONE class's
        queued batches, grant the tenant with the lowest accumulated
        cost bank next (each grant costs 1/effective_weight, so under
        contention tenants split the class's grants by weight ratio —
        the same deficit discipline the outer class level uses).  Ties
        break FIFO; batches outside any tenant scope ride a pseudo
        tenant of weight 1.  Returns the queue index to grant.  With no
        tenant ever seen (STROM_TENANTS=0) this is index 0 — the exact
        pre-tenant FIFO.  The aging pass never calls this: a batch past
        the starvation bound outranks tenant fairness too, which is
        precisely what keeps the proven bound intact at any weight."""
        if not self._tenant_seen or len(q) <= 1:
            return 0
        credits = self._tenant_credit.setdefault(klass, {})
        first: Dict = {}           # tenant id -> its oldest batch index
        for i, b in enumerate(q):
            tid = b.tenant.id if b.tenant is not None else None
            if tid not in first:
                first[tid] = i
        for tid in list(credits):
            if tid not in first:   # departed: a returning tenant must
                del credits[tid]   # not owe (or own) history-old bank
        pick = min(first,
                   key=lambda tid: (credits.get(tid, 0.0), first[tid]))
        return first[pick]

    def _charge_tenant_locked(self, b: _Batch) -> None:
        """Bank one grant's cost against the batch's tenant (called for
        EVERY grant, aged promotions included, so the banks stay an
        honest record of service consumed)."""
        if not self._tenant_seen:
            return
        credits = self._tenant_credit.setdefault(b.klass, {})
        tid = b.tenant.id if b.tenant is not None else None
        w = b.tenant.effective_weight if b.tenant is not None else 1.0
        credits[tid] = credits.get(tid, 0.0) + 1.0 / max(w, 1e-9)
        if len(credits) > 1:
            # floor-normalize so banks measure RELATIVE debt and never
            # grow without bound over a long run
            base = min(credits.values())
            if base > 0:
                for t in credits:
                    credits[t] -= base

    def _drain_locked(self) -> None:
        while any(self._queues.values()):
            if not self._dispatch_round_locked():
                break

    def _dispatch_round_locked(self) -> bool:
        """One dispatch round: aging promotions first, then weighted
        fair-share in priority order, against the rings' current
        admission headroom.  Ages every still-queued batch.  Returns
        True if anything was granted (a False round does NOT age — a
        zero-capacity poll must not burn the starvation budget)."""
        try:
            slots = list(self._ring_free())
        except Exception:
            slots = []
        if not slots:
            return False
        for r, g in self._granted_out.items():
            # granted-but-not-yet-submitted batches already own slots
            if 0 <= r < len(slots):
                slots[r] -= g
        progress = False
        # 0) the TOP class is latency-critical and never admission-
        #    queued: admission control exists to bound BULK traffic
        #    ahead of it, so decode grants immediately to the least-
        #    loaded ring whatever the depths (strict priority over the
        #    fair-shared classes below; its only queueing is the C
        #    ring itself, which the bulk caps keep shallow)
        top_q = self._queues[self._order[0]]
        while top_q:
            # tenant-fair grant ORDER (the ring each batch lands on and
            # the class's unconditional admission are unchanged)
            i = self._pick_index_locked(self._order[0], top_q)
            b = top_q[i]
            # prefer the urgent ring (bulk avoids it, so it is almost
            # always shallow — landing decode anywhere else risks
            # queueing its small reads behind a bulk batch's service
            # tail); spill to the least-loaded ring only when ring 0
            # itself is backed up
            if slots[0] > 0:
                r = 0
            else:
                r = max(range(len(slots)), key=lambda j: slots[j])
            slots[r] -= max(1, len(b.spans))
            del top_q[i]
            self._dispatch_one(b, r)
            progress = True
        if not any(s > 0 for s in slots):
            return progress

        cap = self.ring_cap if self.ring_cap is not None \
            else (max(slots) if slots else 0)
        # Bulk headroom reserve only exists when a ring HAS more than one
        # slot: with cap == 1 (qd_ring=1 topologies, STROM_SCHED_INFLIGHT=1)
        # a reserve of 1 would make every bulk class ungrantable except
        # via aging — the work-conserving guarantee must hold at any cap.
        bulk_reserve = 1 if cap > 1 else 0

        def pick_ring(n_spans: int, reserve: int = 0) -> Optional[int]:
            # least-loaded eligible ring; a whole batch lands on ONE
            # ring (one doorbell), so charge its span count there.
            # ``reserve``: slots a LOWER-priority class must leave free
            # on every ring — the headroom that keeps a bulk storm from
            # filling all admission slots ahead of a decode burst (only
            # the top class and aged promotions may consume it).
            # Ring 0 is the URGENT ring (NVMe WRR-with-urgent-class
            # arbitration): bulk classes treat it as a LAST RESORT —
            # eligible only when no other ring has headroom AND ring 0
            # is completely idle (work-conserving: an engine with no
            # latency-critical traffic still uses every ring) — so an
            # active decode stream owns a ring's worth of service
            # capacity instead of intermittently queueing behind a
            # bulk batch that grabbed the idle urgent ring first.
            lo = 0 if (reserve == 0 or len(slots) == 1) else 1
            r = max(range(lo, len(slots)), key=lambda i: slots[i])
            if slots[r] <= reserve:
                if lo == 1 and slots[0] >= cap and cap > reserve:
                    r = 0       # bulk's last resort: the idle urgent ring
                else:
                    return None
            slots[r] -= max(1, n_spans)
            return r

        # 1) aging: a batch past the starvation bound outranks all
        #    weights, priorities, and the reserve
        for klass in self._order:
            q = self._queues[klass]
            while q and q[0].rounds >= self.aging_rounds:
                r = pick_ring(len(q[0].spans))
                if r is None:
                    break
                self._dispatch_one(q.popleft(), r, promoted=True)
                progress = True
        # 2) weighted fair-share: credit each backlogged class its
        #    weight (one round of banking max), serve in priority order
        for klass in self._order:
            if self._queues[klass]:
                w = self.policies[klass].weight
                self._deficit[klass] = min(self._deficit[klass] + w, 2 * w)
        top = self._order[0]
        for klass in self._order:
            q = self._queues[klass]
            reserve = 0 if klass == top else bulk_reserve
            while q and self._deficit[klass] >= 1.0:
                i = self._pick_index_locked(klass, q)
                b = q[i]
                r = pick_ring(len(b.spans), reserve)
                if r is None:
                    break
                del q[i]
                self._dispatch_one(b, r)
                self._deficit[klass] -= 1.0
                progress = True
            if not q:
                self._deficit[klass] = 0.0  # no banking while idle
        # 3) age the survivors of a round that had capacity
        for q in self._queues.values():
            for b in q:
                b.rounds += 1
        if self.tracer is not None and self.tracer.exports:
            now = time.monotonic()
            if now >= self._next_counter_t:
                # per-class queue depth as a Perfetto counter track:
                # the sched spans' queue waits get their denominator on
                # the same timeline (docs/OBSERVABILITY.md)
                self._next_counter_t = now + 0.02
                self.tracer.add_counter(
                    "strom.sched.queue_depth",
                    {k: len(q) for k, q in self._queues.items()})
        return progress

    def _dispatch_one(self, b: _Batch, ring: int,
                      promoted: bool = False) -> None:
        """Grant ``b`` ring admission (the owner thread performs the
        actual engine submission outside the lock)."""
        b.ring = ring
        b.promoted = promoted
        b.granted = True
        self._charge_tenant_locked(b)
        self._granted_out[ring] = (self._granted_out.get(ring, 0)
                                   + max(1, len(b.spans)))
        self.dispatches += 1
        if promoted:
            self.promotions += 1
        if self.tracer is not None and self.tracer.enabled:
            # the scheduler-queue wait this batch paid, causally under
            # the requester's span (b.ctx captured at enqueue)
            self.tracer.add_span(
                "strom.sched.queue", b.t_enq_ns, time.monotonic_ns(),
                category="strom.sched", ctx=b.ctx, klass=b.klass,
                ring=ring, spans=len(b.spans), promoted=promoted)
        if self.stats is not None:
            wait_s = time.monotonic() - b.t_enq
            self.stats.add(sched_dispatches=1,
                           **({"sched_promotions": 1} if promoted else {}))
            self.stats.add_class_stat(
                b.klass, dispatches=1, spans=len(b.spans),
                **({"promotions": 1} if promoted else {}))
            self.stats.class_stat_gauges(b.klass, queue_wait_s=wait_s)
            if b.tenant is not None:
                self.stats.add_tenant_stat(
                    b.tenant.id, dispatches=1, spans=len(b.spans),
                    **({"promotions": 1} if promoted else {}))
        self._cv.notify_all()
