"""Tiered pinned-host DRAM cache between NVMe and HBM.

Repeat traffic — hot weight shards re-streamed per serving replica, hot
KV prefixes, hot SQL partitions — used to re-pay full SSD latency on
every read even though the engine already probes page-cache residency.
This module promotes that probe into a MANAGED tier (the LMB
CXL-linked-buffer pattern, PAPERS.md): an mlock'd host-DRAM arena of
fixed-size cache lines serving repeat reads at link speed instead of
SSD speed, shared by every consumer as ONE memory budget
(``STROM_HOSTCACHE_MB``; 0 — the default — disables the tier and the
submit path is bit-for-bit the pre-cache code).

  lines      fixed-size, keyed by ``(file_key, aligned_offset)`` where
             ``file_key`` is the file's (dev, inode, mtime_ns, size)
             identity captured at ``StromEngine.open`` — a file
             modified between opens gets a NEW key, so stale lines can
             never serve (they age out of the budget instead).  The
             line size adopts the ledger-tuned chunk
             (``utils.tuning.tuned_chunk_bytes``) unless pinned by
             ``STROM_HOSTCACHE_LINE_BYTES``.  A line may hold a VALID
             PREFIX shorter than the line (EOF tails, partial fills) —
             hits are served only inside the valid prefix.
  admission  frequency-based, via a ghost list (second-chance sketch):
             a line key is admitted only when it was ALREADY missed
             recently — one-shot streaming scans never pollute the
             tier, while the second touch of a hot span promotes it.
             Fill happens on the miss read's completion (``wait``),
             copying the staging view into the line via the native
             ``strom_hostcache_copy`` helper so the staging buffer
             recycles immediately.
  quotas     class-aware: each QoS class (io/sched.py) owns a
             weight-derived share of the budget
             (``STROM_HOSTCACHE_CLASS_QUOTAS``, defaulting to the
             scheduler's stock class weights).  Borrowing free space is
             allowed (work-conserving); under pressure, eviction
             reclaims from OVER-QUOTA classes first with the same
             deficit-round-robin machinery as ``io/sched.py`` —
             inverse-weight credits, one round of banking, lowest
             priority served first — then a second-chance clock inside
             the chosen class.  Pinned lines (outstanding views) are
             never reclaimed.
  integrity  every fill stamps the line's CRC32C (PR 5 machinery,
             ``utils/checksum.py``); hits verify behind the same
             ``STROM_VERIFY`` gate, and a mismatched line drops itself
             and heals through the normal miss path — host-DRAM
             corruption of a resident line can never serve silently.

Integration lives at the ``io/plan.py`` boundary (``plan_and_submit``
splits extents into hit spans served here and miss spans submitted
through the QoS scheduler as today; ``submit_spans_tiered`` does the
whole-span equivalent for ``DeviceStream.stream_ranges``), so all five
read consumers get the tier transparently.  Hit spans NEVER enter
``FaultyEngine``/``ResilientEngine`` — a DRAM read needs no retry or
hedge budget.  Every decision is counted (``StromStats.cache_*``,
``bytes_served_cache``, per-class hit rates in ``class_stats``) and
rendered by ``strom_stat``'s "host cache" block, watchdog dumps, and
``bench.py``'s ``hostcache`` scenario.
"""

from __future__ import annotations

import ctypes
import os
import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from nvme_strom_tpu.utils.lockwitness import make_lock, make_rlock
from nvme_strom_tpu.io.tenants import current_tenant
from nvme_strom_tpu.io.sched import CLASS_ORDER, DEFAULT_CLASS, \
    default_policies
from nvme_strom_tpu.utils.config import HostCacheConfig

#: line-key type: ((dev, ino, mtime_ns, size), line_offset)
LineKey = Tuple[tuple, int]


_hc_lib = None        # bound private CDLL handle (None until first bind)
_hc_lib_lock = make_lock("hostcache._hc_lib_lock")


def _hostcache_lib():
    """The module's ONE owning bind site for the ``strom_hostcache_*``
    symbols (strom-lint abi: single-bind ownership — the pre-PR-13
    shape bound ``strom_hostcache_copy`` at two sites).  Private CDLL
    handle: ctypes caches one function object per CDLL instance, so
    sharing ``_load_lib()``'s handle would let another module's
    ``argtypes`` assignment silently retype ours.  None when the
    library cannot build (trimmed installs) — NOT cached, so a later
    arena retries once the build becomes possible (the pre-PR-13
    per-arena cadence)."""
    global _hc_lib
    with _hc_lib_lock:
        if _hc_lib is None:
            try:
                from nvme_strom_tpu.io.engine import _load_lib
                lib = ctypes.CDLL(_load_lib()._name)
                lib.strom_hostcache_arena_create.restype = ctypes.c_void_p
                lib.strom_hostcache_arena_create.argtypes = [
                    ctypes.c_uint64, ctypes.c_int,
                    ctypes.POINTER(ctypes.c_int32)]
                lib.strom_hostcache_arena_destroy.restype = None
                lib.strom_hostcache_arena_destroy.argtypes = [
                    ctypes.c_void_p, ctypes.c_uint64]
                lib.strom_hostcache_copy.restype = None
                lib.strom_hostcache_copy.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
                _hc_lib = lib
            except Exception:
                return None
        return _hc_lib


def _scheduler_weights() -> Dict[str, float]:
    """The QoS scheduler's EFFECTIVE class weights — including a user's
    ``STROM_CLASS_WEIGHTS`` override — so 'quota default = scheduler
    weights' holds by construction, not only for the stock values."""
    weights = os.environ.get("STROM_CLASS_WEIGHTS", "")
    try:
        policies = default_policies(weights)
    except ValueError:
        policies = default_policies()
    return {k: p.weight for k, p in policies.items()}


class _Arena:
    """The pinned backing store: one anonymous mapping, pre-faulted and
    (best-effort) mlock'd by the native helper
    (``strom_hostcache_arena_create``); a plain numpy buffer when the
    library cannot build (trimmed installs) — unpinned but functional."""

    def __init__(self, nbytes: int, lock_pages: bool):
        self.nbytes = nbytes
        self.locked = False
        self._base: Optional[int] = None
        self._lib = None
        self._slab = None
        # Unified arena first (io/arena.py, docs/PERF.md §6): cache
        # lines share ONE reservation with staging pools and bridge
        # slabs instead of owning a second mapping.  The carve is
        # mlock'd (pages fault in then) under the same STROM_MLOCK
        # policy; carve refused/arena off → the private pre-arena
        # mapping below, bit-for-bit.
        try:
            from nvme_strom_tpu.io import arena as _arena
            from nvme_strom_tpu.utils.stats import global_stats
            # the tier is built engine-agnostically, so a refused carve
            # lands in the process-global block — starvation of the
            # LARGEST intended arena consumer must not be silent
            slab = _arena.carve_or_none(nbytes, "hostcache",
                                        stats=global_stats,
                                        lock=lock_pages)
        except Exception:
            slab = None
        if slab is not None:
            self._slab = slab
            self._base = slab.addr
            self.view = slab.view
            self.locked = bool(slab.locked)   # THIS carve's mlock verdict
            # numpy-backed fallback when the lib can't build: copy_in's
            # _lib-is-None branch serves fills — unpinned but
            # functional, the documented degradation
            self._lib = _hostcache_lib()
            return
        try:
            lib = _hostcache_lib()
            if lib is None:
                raise OSError("libstrom_io unavailable")
            locked = ctypes.c_int32(0)
            base = lib.strom_hostcache_arena_create(
                nbytes, 1 if lock_pages else 0, ctypes.byref(locked))
            if base:
                self._base = int(base)
                self._lib = lib
                self.locked = bool(locked.value)
                self.view = np.ctypeslib.as_array(
                    ctypes.cast(base, ctypes.POINTER(ctypes.c_uint8)),
                    shape=(nbytes,))
        except Exception:
            self._base = None
        if self._base is None:
            self.view = np.zeros(nbytes, dtype=np.uint8)

    def copy_in(self, dst_off: int, src: np.ndarray) -> None:
        """Fill primitive: staging view → line bytes.  The native path
        memcpys with the GIL dropped; either way the source buffer is
        free to recycle the moment this returns."""
        n = src.nbytes
        if n == 0:
            return
        if self._lib is not None:
            src = np.ascontiguousarray(src)
            self._lib.strom_hostcache_copy(
                self._base + dst_off, src.ctypes.data, n)
        else:
            self.view[dst_off:dst_off + n] = src.reshape(-1)

    def close(self) -> None:
        if self._slab is not None:
            self.view = None
            self._base = None
            self._slab.release()   # the carve recycles; the arena lives
            self._slab = None
            return
        if self._base is not None:
            self.view = None
            self._lib.strom_hostcache_arena_destroy(self._base,
                                                    self.nbytes)
            self._base = None


class _Line:
    """One resident cache line (a valid PREFIX of ``line_bytes``)."""

    __slots__ = ("key", "slot", "valid", "klass", "crc", "pins", "ref",
                 "dead", "sticky", "hits", "tenant")

    def __init__(self, key: LineKey, slot: int, klass: str):
        self.key = key
        self.slot = slot
        self.valid = 0        # valid bytes from the line start
        self.klass = klass
        t = current_tenant()
        #: owning tenant ID, stamped from the fill thread's tenant
        #: scope (None outside any scope — the whole per-tenant quota
        #: layer stays inert then); the line counts against this
        #: owner's residency quota until it leaves the map
        self.tenant = t.id if t is not None else None
        self.crc: Optional[int] = None
        self.pins = 0         # outstanding hit views
        self.ref = False      # second-chance bit
        self.hits = 0         # lifetime hit count: a line evicted at 0
        #                       was filled from NVMe for nothing — the
        #                       ledger's evicted-before-reuse waste class
        self.dead = False     # invalidated while pinned: slot freed on
        #                       last unpin, mapping already gone
        self.sticky = False   # hot-pinned (docs/PERF.md §5): eviction
        #                       skips it while its class is WITHIN quota
        #                       — a KV-prefix page stays resident through
        #                       the decode quota instead of rotating out
        #                       under bulk pressure; over-quota sticky
        #                       lines pay like everyone else, and writes
        #                       still invalidate them


class CacheHitRead:
    """Pending-/SpanView-shaped zero-copy view over a resident line.

    ``wait()`` returns a numpy slice of the pinned arena (no copy, no
    I/O, no engine, no retry/hedge); the line stays pinned — ineligible
    for eviction — until ``release()``."""

    __slots__ = ("_cache", "_line", "_lo", "_hi", "fh", "offset",
                 "_released")

    was_fallback = False

    def __init__(self, cache: "HostCache", line: _Line, lo: int, hi: int,
                 fh: int, offset: int):
        self._cache = cache
        self._line = line
        self._lo = lo
        self._hi = hi
        self.fh = fh
        self.offset = offset
        self._released = False

    @property
    def length(self) -> int:
        return self._hi - self._lo

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        del timeout   # always ready: the bytes are resident by contract
        return self._cache.line_view(self._line, self._lo, self._hi)

    def is_ready(self) -> bool:
        return True

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._cache.unpin(self._line)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class _FillOnWait:
    """Wrap a miss span's pending read: on the first successful
    ``wait()``, copy the admitted line-aligned portions of the completed
    view into the cache (the fill-on-miss half of the tier), then hand
    the view through untouched.  A cache failure never fails the read."""

    __slots__ = ("_pending", "_cache", "_fkey", "_off", "_keys",
                 "_klass", "_stats", "_filled", "_sticky", "_tracer",
                 "_ctx")

    def __init__(self, pending, cache: "HostCache", fkey: tuple,
                 span_off: int, keys: Dict[LineKey, int], klass, stats,
                 sticky: bool = False, tracer=None):
        self._pending = pending
        self._cache = cache
        self._fkey = fkey
        self._off = span_off
        self._keys = keys
        self._klass = klass
        self._stats = stats
        self._filled = False
        self._sticky = sticky
        #: fill-span sink + causal identity, captured at construction —
        #: the fill runs at wait() time, possibly on another thread
        self._tracer = tracer if (tracer is not None
                                  and tracer.enabled) else None
        self._ctx = None
        if self._tracer is not None:
            from nvme_strom_tpu.utils.trace import attach_context
            self._ctx = attach_context()

    @property
    def length(self) -> int:
        return self._pending.length

    @property
    def fh(self) -> int:
        return self._pending.fh

    @property
    def offset(self) -> int:
        return self._pending.offset

    @property
    def was_fallback(self) -> bool:
        return bool(getattr(self._pending, "was_fallback", False))

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        view = self._pending.wait(timeout)
        if not self._filled:
            self._filled = True
            import time as _time
            t0 = _time.monotonic_ns()
            try:
                self._cache.fill_from_view(self._fkey, self._off, view,
                                           self._keys, self._klass,
                                           self._stats,
                                           sticky=self._sticky)
            except Exception:
                pass   # the tier is an accelerator, never a failure mode
            if self._tracer is not None:
                self._tracer.add_span(
                    "strom.cache.fill", t0, _time.monotonic_ns(),
                    category="strom.cache", ctx=self._ctx,
                    lines=len(self._keys), bytes=int(view.nbytes),
                    klass=self._klass)
        return view

    def is_ready(self) -> bool:
        return self._pending.is_ready()

    def release(self) -> None:
        self._pending.release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class HostCache:
    """The managed tier: line map + ghost-list admission + class quotas
    over one pinned arena.  Thread-safe; one instance per process
    (module singleton via :func:`get_cache`), shared by every engine —
    the ONE memory budget ROADMAP item 5 asks for."""

    def __init__(self, line_bytes: int, budget_bytes: int,
                 quotas: Optional[Dict[str, float]] = None,
                 ghost_factor: int = 4, lock_arena: bool = True,
                 verify=None):
        if line_bytes <= 0:
            raise ValueError("line_bytes must be > 0")
        self.line_bytes = line_bytes
        self.capacity = max(1, budget_bytes // line_bytes)
        self.arena = _Arena(self.capacity * line_bytes, lock_arena)
        if quotas is None:
            quotas = _scheduler_weights()
        total_w = sum(quotas.values()) or 1.0
        #: soft per-class residency quota in SLOTS (borrowing free space
        #: is allowed; pressure reclaims over-quota classes first)
        self.quota_slots: Dict[str, float] = {
            k: self.capacity * w / total_w for k, w in quotas.items()}
        # eviction DRR credits mirror io/sched.py's deficit machinery
        # with INVERSE weights: the class the scheduler protects most
        # (decode) pays for pressure last
        max_w = max(quotas.values()) or 1.0
        self._evict_w = {k: max_w / w if w > 0 else max_w * 2
                         for k, w in quotas.items()}
        self._evict_deficit = {k: 0.0 for k in quotas}
        self._rev_order = [k for k in reversed(CLASS_ORDER) if k in quotas]
        for k in quotas:
            if k not in self._rev_order:
                self._rev_order.insert(0, k)
        self._lock = make_rlock("hostcache.HostCache._lock")
        self._lines: Dict[LineKey, _Line] = {}
        self._free: List[int] = list(range(self.capacity))
        self._ghost: "OrderedDict[LineKey, None]" = OrderedDict()
        self._ghost_cap = max(self.capacity * ghost_factor, 16)
        self._clock: Dict[str, deque] = {k: deque() for k in quotas}
        self._class_slots: Dict[str, int] = {k: 0 for k in quotas}
        # per-tenant residency (multi-tenant isolation, orthogonal to
        # the class axis): resident slots per owning tenant id, and
        # each tenant's declared quota fraction (0 = fair share, 1/N of
        # the tenants seen).  Both stay empty — and every tenant branch
        # below short-circuits — until a fill runs inside a tenant
        # scope (STROM_TENANTS=1 serving traffic).
        self._tenant_slots: Dict[str, int] = {}
        self._tenant_quota_frac: Dict[str, float] = {}
        # per-LINE invalidation epoch: a fill whose admission verdict
        # predates a write OVERLAPPING THAT LINE is refused, so a read
        # racing a write can never install pre-write bytes — while
        # writes to other offsets of the same file (kv_offload pages
        # out one slot while decode reads another back) leave in-flight
        # fills untouched.  LRU-bounded WITH A FLOOR: keys absent from
        # the map read as ``_epoch_floor``, which rises to the largest
        # epoch ever evicted from the map — so losing a write's entry
        # can only REFUSE fills (floor > admission epoch), never let a
        # pre-write fill slip back in as epoch 0.
        self._key_epoch: "OrderedDict[LineKey, int]" = OrderedDict()
        self._key_epoch_cap = max(4 * self.capacity, 4096)
        self._epoch_floor = 0
        self._write_seq = 0
        self.bytes_resident = 0    # sum of resident lines' valid bytes
        if verify is None:
            from nvme_strom_tpu.utils.checksum import VerifyPolicy
            verify = VerifyPolicy()
        self._verify = verify

    # -- introspection -----------------------------------------------------

    def counters(self) -> dict:
        with self._lock:
            return {
                "lines_resident": len(self._lines),
                "bytes_resident": self.bytes_resident,
                "capacity_lines": self.capacity,
                "line_bytes": self.line_bytes,
                "arena_locked": self.arena.locked,
                "class_slots": dict(self._class_slots),
                "tenant_slots": dict(self._tenant_slots),
            }

    def resident_spans(self, fkey: tuple) -> list:
        """Merged ``(offset, length)`` spans of one file currently
        resident in the cache, largest-first — the raw material for a
        ``.warmhints.json`` warmup manifest (io/warmup.py): the next
        boot prefetches exactly these byte ranges at ``prefetch`` class
        and lands at today's hit rate instead of re-learning it."""
        with self._lock:
            raw = sorted((key[1], line.valid)
                         for key, line in self._lines.items()
                         if key[0] == fkey and line.valid > 0
                         and not line.dead)
        merged: list = []
        for off, ln in raw:
            if merged and off <= merged[-1][0] + merged[-1][1]:
                last_off, last_len = merged[-1]
                merged[-1] = (last_off,
                              max(last_len, off + ln - last_off))
            else:
                merged.append((off, ln))
        merged.sort(key=lambda s: (-s[1], s[0]))
        return merged

    def _klass(self, klass: Optional[str]) -> str:
        return klass if klass in self.quota_slots else DEFAULT_CLASS

    def _epoch_of(self, key: LineKey) -> int:
        """A line key's invalidation epoch (lock held): its map entry,
        or the fail-closed floor for keys the bounded map has dropped."""
        return self._key_epoch.get(key, self._epoch_floor)

    # -- hit serving -------------------------------------------------------

    def line_view(self, line: _Line, lo: int, hi: int) -> np.ndarray:
        base = line.slot * self.line_bytes
        view = self.arena.view[base + lo:base + hi]
        # engine staging views are private to one request; a line is
        # SHARED persistent state serving every future hit — hand out
        # read-only slices so an in-place consumer mutation (harmless
        # on the engine path) cannot silently corrupt the resident copy
        view.flags.writeable = False
        return view

    def unpin(self, line: _Line) -> None:
        with self._lock:
            self.unpin_locked(line)

    def unpin_locked(self, line: _Line) -> None:
        line.pins -= 1
        if line.pins <= 0 and line.dead:
            self._free.append(line.slot)
            line.dead = False       # slot handed back exactly once

    def _verify_ok(self, line: _Line, stats) -> bool:
        """STROM_VERIFY gate over the resident prefix; a mismatched line
        drops itself (heals through the miss path) and counts
        checksum_failures — corruption never serves silently.

        The CRC pass runs under the cache lock (the probe loops hold
        it): with ``STROM_VERIFY=full`` and large lines this serializes
        concurrent probes behind line-sized checksum work — the same
        deliberate throughput-for-integrity trade ``full`` makes on the
        engine read path; ``sample`` (every Nth span) amortizes it to
        noise and is the recommended steady-state mode."""
        if line.crc is None or not self._verify.want():
            return True
        from nvme_strom_tpu.utils.checksum import crc32c
        got = crc32c(self.line_view(line, 0, line.valid))
        if stats is not None:
            stats.add(bytes_verified=int(line.valid))
        if got == line.crc:
            return True
        if stats is not None:
            stats.add(checksum_failures=1)
        self._drop_line(line, stats, counter="cache_invalidations")
        return False

    # -- probe (the planner boundary) --------------------------------------

    def probe_range(self, fkey: tuple, off: int, length: int,
                    klass: Optional[str], stats=None, hot: bool = False
                    ) -> Tuple[List[tuple], Dict[LineKey, int]]:
        """Split ``[off, off+length)`` into hit and miss segments.

        Returns ``(segments, admitted)``: segments are ordered
        ``("hit", abs_off, ln, line)`` — the line PINNED, one segment
        per line so every hit view is a zero-copy arena slice — and
        ``("miss", abs_off, ln)`` runs (contiguous missed bytes merged);
        ``admitted`` maps each line key the caller should fill from the
        miss reads' completions (the ghost-list verdict) to the file's
        invalidation epoch at verdict time — a fill is refused if a
        write bumps the epoch in between.

        ``hot`` marks the range latency-critical repeat traffic (KV
        prefix pages): missed lines are admitted on FIRST touch (the
        ghost gate exists to filter one-shot scans, which a declared-hot
        range is not) and resident lines turn sticky — protected from
        eviction while their class stays within quota."""
        kl = self._klass(klass)
        lb = self.line_bytes
        segments: List[tuple] = []
        admitted: Dict[LineKey, int] = {}
        hits = misses = served = 0
        with self._lock:
            pos, end = off, off + length
            m_lo: Optional[int] = None     # open miss RUN (segments
            #                                merge; misses count lines)
            while pos < end:
                lo = pos - pos % lb
                take_end = min(end, lo + lb)
                line = self._lines.get((fkey, lo))
                ok = (line is not None and take_end - lo <= line.valid
                      and self._verify_ok(line, stats))
                if ok:
                    if m_lo is not None:
                        segments.append(("miss", m_lo, pos - m_lo))
                        m_lo = None
                    line.pins += 1
                    line.ref = True
                    line.hits += 1
                    if hot:
                        line.sticky = True
                    segments.append(("hit", pos, take_end - pos, line))
                    hits += 1
                    served += take_end - pos
                else:
                    # count misses PER LINE, the same unit as hits, so
                    # hit rate = hits/(hits+misses) is a line fraction
                    misses += 1
                    if m_lo is None:
                        m_lo = pos
                    if pos == lo:
                        if (fkey, lo) in self._lines:
                            # resident but too short for this request:
                            # the line already proved hot — admit the
                            # fill directly so the longer read EXTENDS
                            # the prefix instead of missing forever
                            admitted[(fkey, lo)] = \
                                self._epoch_of((fkey, lo))
                        else:
                            self._admit_or_note((fkey, lo), admitted,
                                                stats, hot=hot)
                pos = take_end
            if m_lo is not None:
                segments.append(("miss", m_lo, end - m_lo))
        if stats is not None and (hits or misses):
            stats.add(cache_hits=hits, cache_misses=misses,
                      bytes_served_cache=served)
            stats.add_class_stat(kl, cache_hits=hits, cache_misses=misses,
                                 bytes_served_cache=served)
        return segments, admitted

    def probe_span(self, fkey: tuple, off: int, length: int,
                   klass: Optional[str], stats=None, hot: bool = False
                   ) -> Tuple[Optional[_Line], Dict[LineKey, int]]:
        """Whole-span variant for vectored refill paths
        (``DeviceStream.stream_ranges``): a span is a hit only when it
        fits inside ONE line's valid prefix (anything else would need a
        concatenating copy to serve — against the zero-copy contract);
        otherwise the fillable line starts inside the span are run
        through admission and the span submits as a normal miss."""
        kl = self._klass(klass)
        lb = self.line_bytes
        admitted: Dict[LineKey, int] = {}
        with self._lock:
            lo = off - off % lb
            line = self._lines.get((fkey, lo))
            if (line is not None and off + length <= lo + line.valid
                    and self._verify_ok(line, stats)):
                line.pins += 1
                line.ref = True
                line.hits += 1
                if hot:
                    line.sticky = True
                if stats is not None:
                    stats.add(cache_hits=1, bytes_served_cache=length)
                    stats.add_class_stat(kl, cache_hits=1,
                                         bytes_served_cache=length)
                return line, admitted
            # admission only when a future IDENTICAL read could hit:
            # a stream-path hit must fit in ONE line and fills cover a
            # line from its start, so only a line-aligned span within
            # one line earns fills — a cross-line or mid-line span
            # passes through untouched (filling its lines would squat
            # the budget serving nothing; the PLANNER path's partial-
            # hit splitting is where unaligned repeat traffic caches)
            if off % lb == 0 and length <= lb:
                key = (fkey, off)
                if key in self._lines:
                    # too-short resident prefix: admit the extension
                    admitted[key] = self._epoch_of(key)
                else:
                    self._admit_or_note(key, admitted, stats, hot=hot)
        if stats is not None:
            # per-line units, matching probe_range's hits
            n_lines = (off + length - 1) // lb - lo // lb + 1
            stats.add(cache_misses=n_lines)
            stats.add_class_stat(kl, cache_misses=n_lines)
        return None, admitted

    def _admit_or_note(self, key: LineKey, admitted: Dict[LineKey, int],
                       stats, hot: bool = False) -> None:
        """The ghost-list second-chance verdict (lock held): admit a
        missed line only if it was ALREADY missed recently — the first
        touch of a streaming scan is refused (counted) and remembered.
        An admitted key carries the file's current invalidation epoch,
        so a write landing between verdict and fill voids the fill.
        ``hot`` skips the ghost gate entirely: a declared-hot range
        (KV prefix restore) is repeat traffic by contract, so the first
        touch admits."""
        if hot or key in self._ghost:
            self._ghost.pop(key, None)
            admitted[key] = self._epoch_of(key)
            return
        self._ghost[key] = None
        while len(self._ghost) > self._ghost_cap:
            self._ghost.popitem(last=False)
        if stats is not None:
            stats.add(cache_admission_rejections=1)

    # -- fill (miss completions) -------------------------------------------

    def fill_from_view(self, fkey: tuple, span_off: int,
                       view: np.ndarray, keys: Dict[LineKey, int],
                       klass: Optional[str], stats=None,
                       sticky: bool = False) -> None:
        """Copy the admitted line-aligned portions of a completed span
        read into lines.  ``view`` may be short (EOF) — each line holds
        whatever prefix the read actually covered.  ``keys`` carries
        each key's admission-time epoch (see :meth:`probe_range`)."""
        n = view.nbytes
        for key, epoch in keys.items():
            line_off = key[1]
            rel = line_off - span_off
            if rel < 0 or rel >= n:
                continue   # admitted under another span of the batch
            self.fill(fkey, line_off,
                      view[rel:rel + min(self.line_bytes, n - rel)],
                      klass, stats, epoch=epoch, sticky=sticky)

    def fill(self, fkey: tuple, line_off: int, payload: np.ndarray,
             klass: Optional[str], stats=None,
             epoch: Optional[int] = None, sticky: bool = False) -> bool:
        """Install ``payload`` (a prefix of the line at ``line_off``) —
        allocating a slot, evicting under the class-quota policy when
        the arena is full.  False when the fill was skipped (already
        resident with as much data, pinned, nothing evictable, or the
        file was written since the admission verdict — ``epoch``).

        The line-sized memcpy (and CRC pass when verification is on)
        runs OUTSIDE the cache lock: the line is reserved under the
        lock with ``valid = 0`` and a pin, so concurrent probes miss
        it, eviction skips it, and an invalidation racing the copy
        marks it dead (abandoned below) — fills from N miss threads
        overlap instead of serializing behind one memcpy."""
        kl = self._klass(klass)
        valid = int(payload.nbytes)
        if valid <= 0 or valid > self.line_bytes:
            return False
        with self._lock:
            key = (fkey, line_off)
            if (epoch is not None
                    and self._epoch_of((fkey, line_off)) != epoch):
                if stats is not None:   # written since admission:
                    stats.add(cache_fill_failures=1)   # stale payload
                return False
            line = self._lines.get(key)
            if line is not None:
                if line.valid >= valid or line.pins > 0:
                    return False
                self.bytes_resident -= line.valid
                line.valid = 0          # probes miss while we rewrite
            else:
                if self._free:
                    slot = self._free.pop()
                else:
                    slot = self._evict_one(kl, stats)
                    if slot is None:
                        if stats is not None:
                            stats.add(cache_fill_failures=1)
                        return False
                line = _Line(key, slot, kl)
                self._lines[key] = line
                self._ghost.pop(key, None)
                self._class_slots[kl] = self._class_slots.get(kl, 0) + 1
                self._clock.setdefault(kl, deque()).append(key)
                if line.tenant is not None:
                    self._note_tenant_fill_locked(line, stats)
            if sticky:
                line.sticky = True
            line.pins += 1              # copy in progress: unevictable
        try:
            self.arena.copy_in(line.slot * self.line_bytes, payload)
            crc = None
            if self._verify.enabled:
                from nvme_strom_tpu.utils.checksum import crc32c
                crc = crc32c(payload)
        except BaseException:
            with self._lock:
                self.unpin_locked(line)
            raise
        with self._lock:
            self.unpin_locked(line)
            if line.dead or self._lines.get(key) is not line:
                return False            # invalidated mid-copy: abandon
            line.valid = valid
            line.crc = crc
            self.bytes_resident += valid
            if stats is not None:
                stats.add(cache_admissions=1)
                stats.set_gauges(cache_bytes_resident=self.bytes_resident,
                                 cache_lines_resident=len(self._lines))
        return True

    # -- eviction (class quotas, DRR + second chance) ----------------------

    def _over_quota(self, klass: str) -> bool:
        return self._class_slots.get(klass, 0) > \
            self.quota_slots.get(klass, 0.0)

    # -- per-tenant residency quotas (multi-tenant isolation) --------------

    def _note_tenant_fill_locked(self, line: _Line, stats) -> None:
        """Charge a new line to its owner's residency count; landing
        past the quota while free space existed is BORROWING (allowed,
        counted — pressure reclaims it first)."""
        tid = line.tenant
        t = current_tenant()
        if t is not None and t.id == tid:
            self._tenant_quota_frac[tid] = t.quota_frac
        else:
            self._tenant_quota_frac.setdefault(tid, 0.0)
        self._tenant_slots[tid] = self._tenant_slots.get(tid, 0) + 1
        if self._tenant_over(tid) and stats is not None:
            stats.add(tenant_borrows=1)
            stats.add_tenant_stat(tid, borrows=1)

    def _tenant_quota_slots(self, tid: str) -> float:
        """One tenant's residency quota in slots: its declared fraction
        of the arena, or — fraction 0 — a fair share (1/N of the
        tenants currently resident)."""
        frac = self._tenant_quota_frac.get(tid, 0.0)
        if frac <= 0.0:
            frac = 1.0 / max(1, len(self._tenant_slots))
        return frac * self.capacity

    def _tenant_over(self, tid: Optional[str]) -> bool:
        if tid is None or not self._tenant_slots:
            return False
        return self._tenant_slots.get(tid, 0) > \
            self._tenant_quota_slots(tid)

    def _tenant_drop_locked(self, line: _Line) -> None:
        """Refund a departing line's residency charge (lock held)."""
        tid = line.tenant
        if tid is None:
            return
        n = self._tenant_slots.get(tid, 0) - 1
        if n > 0:
            self._tenant_slots[tid] = n
        else:
            # last resident line gone: forget the tenant entirely so
            # fair-share fractions track tenants actually resident
            self._tenant_slots.pop(tid, None)
            self._tenant_quota_frac.pop(tid, None)

    def _tenant_evict_locked(self, stats) -> Optional[int]:
        """Quota pre-pass: before any class pays, reclaim from the MOST
        over-quota tenant (largest slot excess) — the borrowing that
        storm bought is the first residency pressure takes back, so one
        tenant's storm cannot evict another's hot set.  Prefers lines
        the second-chance bit marks cold; sticky does not protect an
        over-quota tenant's lines (mirroring the over-quota class
        rule).  None when no tenant is over quota."""
        over = [tid for tid in self._tenant_slots
                if self._tenant_over(tid)]
        if not over:
            return None
        over.sort(key=lambda tid: (self._tenant_slots.get(tid, 0)
                                   - self._tenant_quota_slots(tid)),
                  reverse=True)
        for tid in over:
            best = None
            for line in self._lines.values():
                if line.tenant != tid or line.pins > 0:
                    continue
                if not line.ref:
                    best = line
                    break
                if best is None:
                    best = line
            if best is None:
                continue                    # everything pinned: next
            del self._lines[best.key]
            self._class_slots[best.klass] -= 1
            self.bytes_resident -= best.valid
            self._tenant_drop_locked(best)
            if stats is not None:
                stats.add(cache_evictions=1, tenant_quota_evictions=1)
                stats.add_tenant_stat(tid, quota_evictions=1)
                if best.hits == 0 and best.valid:
                    from nvme_strom_tpu.obs.ledger import charge_waste
                    charge_waste(stats, "evicted_unused", best.valid)
                stats.set_gauges(
                    cache_bytes_resident=self.bytes_resident,
                    cache_lines_resident=len(self._lines))
            return best.slot
        return None

    def _evict_one(self, incoming: str, stats) -> Optional[int]:
        """Reclaim one slot (lock held).  Candidate classes: over-quota
        first; then — when none is over quota OR every over-quota line
        turned out pinned — every class with resident lines (the
        fallback must not be skipped just because the over-quota class
        is momentarily unevictable).  Among candidates the
        deficit-round-robin credits (inverse scheduler weights, one
        round of banking, lowest priority first) pick the payer; a
        second-chance clock inside the class picks the line, skipping
        pinned and recently-referenced lines."""
        if self._tenant_slots:
            # tenant-quota pre-pass: over-quota tenants' borrowing pays
            # for pressure before any class-level candidate does
            slot = self._tenant_evict_locked(stats)
            if slot is not None:
                return slot
        over = [k for k in self._rev_order
                if self._over_quota(k) and self._clock.get(k)]
        every = [k for k in self._rev_order if self._clock.get(k)]
        for cands in (over, every):
            cands = list(cands)
            while cands:
                for k in cands:
                    w = self._evict_w.get(k, 1.0)
                    self._evict_deficit[k] = min(
                        self._evict_deficit[k] + w, 2 * w)
                cands.sort(key=lambda k: -self._evict_deficit[k])
                for k in list(cands):
                    if self._evict_deficit[k] < 1.0:
                        continue
                    slot = self._clock_evict(k, stats)
                    if slot is not None:
                        self._evict_deficit[k] -= 1.0
                        return slot
                    cands.remove(k)   # nothing evictable here right now
        return None

    def _clock_evict(self, klass: str, stats) -> Optional[int]:
        """Second-chance sweep of one class's clock (lock held)."""
        q = self._clock.get(klass)
        if not q:
            return None
        for _ in range(2 * len(q)):
            key = q[0]
            line = self._lines.get(key)
            if line is None or line.klass != klass:
                q.popleft()            # stale clock entry
                if not q:
                    return None
                continue
            if line.pins > 0:
                q.rotate(-1)
                continue
            if line.sticky and not self._over_quota(klass) \
                    and not self._tenant_over(line.tenant):
                # hot-pinned within quota (docs/PERF.md §5): the decode
                # class's KV-prefix residency survives bulk churn; an
                # over-quota class's — or over-quota TENANT's — sticky
                # lines pay normally, so the pin can never wedge the
                # shared budget
                q.rotate(-1)
                continue
            if line.ref:
                line.ref = False       # second chance
                q.rotate(-1)
                continue
            q.popleft()
            del self._lines[key]
            self._class_slots[klass] -= 1
            self.bytes_resident -= line.valid
            self._tenant_drop_locked(line)
            if stats is not None:
                stats.add(cache_evictions=1)
                if line.hits == 0 and line.valid:
                    # filled from NVMe, never served a hit: the fill's
                    # bandwidth bought nothing (ledger waste class —
                    # growth means the ghost gate or quotas are wrong)
                    from nvme_strom_tpu.obs.ledger import charge_waste
                    charge_waste(stats, "evicted_unused", line.valid)
                stats.set_gauges(cache_bytes_resident=self.bytes_resident,
                                 cache_lines_resident=len(self._lines))
            return line.slot
        return None

    # -- invalidation (engine writes) --------------------------------------

    def _drop_line(self, line: _Line, stats,
                   counter: str = "cache_invalidations") -> None:
        """Remove a line from the map NOW (no new hits); its slot frees
        immediately when unpinned, else on the last unpin (outstanding
        views keep serving the old bytes — same contract as a read
        racing a write on the file itself).  Lock held."""
        if self._lines.get(line.key) is not line:
            return
        del self._lines[line.key]
        self._class_slots[line.klass] -= 1
        self.bytes_resident -= line.valid
        self._tenant_drop_locked(line)
        if line.pins > 0:
            line.dead = True
        else:
            self._free.append(line.slot)
        # stale clock entries are normally reaped lazily by eviction
        # sweeps; a rewrite-heavy workload with no eviction pressure
        # would grow the deque forever, so compact when it runs well
        # past the class's resident population
        q = self._clock.get(line.klass)
        if q is not None and len(q) > \
                2 * max(1, self._class_slots.get(line.klass, 0)) + 16:
            self._clock[line.klass] = deque(
                k for k in q
                if self._lines.get(k) is not None
                and self._lines[k].klass == line.klass)
        if stats is not None:
            stats.add(**{counter: 1})
            stats.set_gauges(cache_bytes_resident=self.bytes_resident,
                             cache_lines_resident=len(self._lines))

    def invalidate(self, fkey: tuple, offset: int, length: int,
                   stats=None) -> int:
        """Drop every line overlapping a written range (the staleness
        guard ``StromEngine.submit_write`` calls); returns lines
        dropped."""
        if length <= 0:
            return 0
        lb = self.line_bytes
        first = offset - offset % lb
        n = 0
        with self._lock:
            self._write_seq += 1
            for line_off in range(first, offset + length, lb):
                key = (fkey, line_off)
                # epoch bump: any fill admitted before this write —
                # even one whose read is still in flight — is now
                # void; fills of OTHER lines are untouched
                self._key_epoch[key] = self._write_seq
                self._key_epoch.move_to_end(key)
                line = self._lines.get(key)
                if line is not None:
                    self._drop_line(line, stats)
                    n += 1
                self._ghost.pop(key, None)
            while len(self._key_epoch) > self._key_epoch_cap:
                _k, ev = self._key_epoch.popitem(last=False)
                # fail CLOSED: an evicted entry's epoch becomes the
                # floor every absent key reads, so a fill admitted
                # before the evicted write can never pass as epoch 0
                self._epoch_floor = max(self._epoch_floor, ev)
        return n

    def clear(self) -> None:
        """Drop every unpinned line (tests/bench)."""
        with self._lock:
            for line in list(self._lines.values()):
                self._drop_line(line, None)
            self._ghost.clear()

    def close(self) -> None:
        """Unmap the arena.  The hit-view contract mirrors the engine's
        staging views: a view is valid until ITS release and no longer
        after the tier is torn down — callers release before
        reset()/configure(), exactly as they release before
        ``close_all()``."""
        with self._lock:
            self._lines.clear()
            self._ghost.clear()
            self._tenant_slots.clear()
            self._tenant_quota_frac.clear()
            self.bytes_resident = 0
        self.arena.close()


# --------------------------------------------------------------------------
# module singleton — the ONE shared budget
# --------------------------------------------------------------------------

_singleton_lock = make_lock("hostcache._singleton_lock")
_cache: Optional[HostCache] = None
_cache_init = False


def parse_class_quotas(spec: str) -> Optional[Dict[str, float]]:
    """Parse/validate ``STROM_HOSTCACHE_CLASS_QUOTAS`` — THE one
    implementation of the ``decode=8,restore=4`` grammar
    (``HostCacheConfig.__post_init__`` validates through it too, so
    a malformed value fails loudly at construction)."""
    if not spec:
        return None
    out: Dict[str, float] = {}
    for part in filter(None, (s.strip() for s in spec.split(","))):
        name, eq, val = part.partition("=")
        name = name.strip()
        try:
            weight = float(val)
        except ValueError:
            weight = -1.0
        if not eq or name not in CLASS_ORDER or weight < 0:
            raise ValueError(
                f"STROM_HOSTCACHE_CLASS_QUOTAS entry {part!r}: expected "
                f"<class>=<non-negative weight> with class in "
                f"{CLASS_ORDER}")
        out[name] = weight
    # unnamed classes keep the scheduler's effective relative weights
    # (STROM_CLASS_WEIGHTS included) so every class retains SOME quota
    # (a zero-quota class could never cache at all)
    for k, w in _scheduler_weights().items():
        out.setdefault(k, w)
    return out


def _default_line_bytes(engine) -> int:
    """Auto line size: the ledger-tuned chunk of the first engine that
    touches the tier, rounded down to a power of two (cheap aligned
    arithmetic), floored at 64 KiB so a tiny probe engine cannot shred
    the arena into confetti lines."""
    try:
        from nvme_strom_tpu.utils.tuning import tuned_chunk_bytes
        ck = int(tuned_chunk_bytes(engine))
    except Exception:
        ck = 4 << 20
    p = 4096
    while p * 2 <= ck:
        p *= 2
    return max(p, 64 << 10)


def _build_locked(cfg: HostCacheConfig, engine) -> None:
    """Swap the singleton in (``_singleton_lock`` held).  On a build
    error nothing is marked initialized, so every later caller raises
    the SAME loud error instead of one crash followed by a silently
    tier-off process."""
    global _cache, _cache_init
    if _cache is not None:
        _cache.close()
        _cache = None
    new = None
    if cfg.budget_mb > 0:
        line = cfg.line_bytes or _default_line_bytes(engine)
        budget = cfg.budget_mb << 20
        if budget < line:
            # a non-zero budget means the user WANTS the tier: shrink
            # the line to fit (largest power of two ≤ budget; the
            # config floor keeps budgets ≥ 1 MiB ≥ the 4 KiB minimum)
            # instead of silently disabling
            line = 4096
            while line * 2 <= budget:
                line *= 2
        new = HostCache(
            line_bytes=line, budget_bytes=budget,
            quotas=parse_class_quotas(cfg.class_quotas),
            ghost_factor=cfg.ghost_factor,
            lock_arena=cfg.lock_arena)
    _cache = new
    _cache_init = True


def configure(config: Optional[HostCacheConfig] = None,
              engine=None) -> Optional[HostCache]:
    """(Re)build the process-wide tier from ``config`` (default: the
    env-derived :class:`HostCacheConfig`).  Returns the cache, or None
    when the budget disables the tier."""
    with _singleton_lock:
        _build_locked(config or HostCacheConfig(), engine)
        return _cache


def reset() -> None:
    """Tear the singleton down; the next :func:`get_cache` re-reads the
    environment (tests and bench toggle the tier this way)."""
    global _cache, _cache_init
    with _singleton_lock:
        if _cache is not None:
            _cache.close()
        _cache = None
        _cache_init = False


def get_cache(engine=None) -> Optional[HostCache]:
    """The process-wide tier, built lazily from the environment on first
    use; None when ``STROM_HOSTCACHE_MB`` is unset/0 (the default) —
    callers then take their exact pre-cache path.  Double-checked under
    the lock: two racing first callers must not build twice (the loser
    would munmap an arena the winner is serving hits from)."""
    if _cache_init:
        return _cache
    with _singleton_lock:
        if not _cache_init:
            _build_locked(HostCacheConfig(), engine)
        return _cache


def file_key_of(engine, fh: int) -> Optional[tuple]:
    """The engine's stable file identity for ``fh`` (None for engines
    without the mapping — stub/foreign wrappers simply skip the tier)."""
    fn = getattr(engine, "file_key", None)
    if fn is None:
        return None
    try:
        return fn(fh)
    except Exception:
        return None


def notify_write(fkey: Optional[tuple], offset: int, length: int,
                 stats=None) -> None:
    """Write-path staleness guard: drop cached lines overlapping an
    engine write (``StromEngine.submit_write`` calls this for every
    write on a mapped fh).  No-op while the tier is off."""
    c = _cache
    if c is not None and fkey is not None:
        c.invalidate(fkey, offset, length, stats=stats)


def spoil_span(engine, fh: int, offset: int, length: int,
               stats=None) -> None:
    """Heal-path hook: a consumer-level checksum just failed on this
    span, so any line filled from that (possibly transiently corrupt)
    read must not serve the re-read — or any future read.  The PR 5
    're-read once, then the damage path' protocol calls this before its
    re-read; without it a corrupt FILL would satisfy the heal from DRAM
    and convert a transient flip into a permanent-looking corruption
    (or, under sampled verification, serve it silently).  No-op while
    the tier is off."""
    c = _cache
    if c is None:
        return
    fkey = file_key_of(engine, fh)
    if fkey is not None:
        c.invalidate(fkey, offset, length, stats=stats)


def spoil_path(path, offset: int, length: int, stats=None) -> None:
    """:func:`spoil_span` for callers holding a path instead of an open
    engine fh (checkpoint tile heals): the stat-derived identity equals
    the engine's fstat key while the file is unmodified — exactly the
    window in which a stale line could exist."""
    c = _cache
    if c is None:
        return
    try:
        st = os.stat(path)
    except OSError:
        return
    c.invalidate((st.st_dev, st.st_ino, st.st_mtime_ns, st.st_size),
                 offset, length, stats=stats)
