"""Failure-domain supervision — ring health, circuit breakers, hot ring
restart, and the degraded buffered mode (docs/RESILIENCE.md "Failure
domains").

PRs 1/5 made a single bad read or write survivable; this layer makes a
whole FAILURE DOMAIN survivable: a wedged io_uring ring, an NVMe device
throwing an EIO storm, a hung kernel worker.  Below the per-request
retry loop nothing used to notice those — every consumer pinned to the
sick ring just stalled.  The supervisor watches the domains and applies
escalating policy:

  health      per-ring rolling error windows, fed from BOTH sides of
              the stack: the engine's lock-free ring counters
              (``strom_ring_info.failed``, polled) and the resilience
              layer's per-attempt failures (``note_error`` — a
              Python-level fault plan never touches the C counters,
              yet must trip the same breakers).  A reap-side stall
              detector reads ``oldest_inflight_ns``: a completion that
              never arrives shows up as an age that only grows.
  breaker     one circuit breaker per ring (closed → open → half-open
              → closed) plus one device-level breaker.  A tripped ring
              reports zero admission headroom to the QoS scheduler
              (io/sched.py) — new batches route to healthy rings — and
              scalar submissions (retries, hedges) round-robin over the
              healthy set only.
  restart     a tripped ring is HOT-RESTARTED (``strom_ring_restart``):
              stall-parked requests cancel ``-ECANCELED`` — their
              waiters' retry (ResilientRead) resubmits them onto
              healthy rings, so consumers see one longer wait, never
              an error — dispatched I/O drains bounded, and the uring
              is rebuilt.  The restarted ring serves half-open until a
              clean interval closes its breaker.
  degraded    when every ring (or the device behind them) is unhealthy
              the engine browns out instead of blacking out:
              ``plan_and_submit``/``submit_spans`` serve plain
              synchronous ``pread``s (``strom_read_buffered`` — no
              O_DIRECT, no uring, no staging pool) at reduced
              bandwidth, while one half-open PROBE per interval rides
              the real path; a probe success restores it.  Serving
              (models/serving.py) sheds new prefill admissions while
              degraded, and the SLO governor stops boosting hedges
              into the sick device.

Everything is deterministic and hardware-free to drive: the C stall
injection (``STROM_FAULT_RING_STALL_*`` / ``strom_set_ring_stall``)
wedges a ring on demand, the Python fault plan's ``estorm`` kind
(io/faults.py) models a bounded EIO storm, and ``tick(force=True)``
runs a supervision round on the caller's thread — no background
threads anywhere (tests/test_health.py, ``-m chaos``).

Every action is accounted: ``breaker_trips`` / ``ring_restarts`` /
``extents_requeued`` / ``degraded_reads`` / ``degraded_bytes`` /
``degraded_probes`` counters and the ``ring_health`` /
``engine_degraded`` gauges flow through StromStats → ``strom_stat``'s
health block → watchdog dumps → bench.py JSON.
"""

from __future__ import annotations

import errno
import threading
import time
from collections import deque
from typing import List, Optional, Sequence

import numpy as np

from nvme_strom_tpu.utils.config import BreakerConfig
from nvme_strom_tpu.utils.lockwitness import make_rlock

#: breaker states (the ``ring_health`` gauge renders these)
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

#: max bytes of one half-open probe read — enough to prove the path,
#: cheap enough to lose
_PROBE_BYTES = 64 << 10

#: min interval between polled supervision rounds (C counter reads);
#: ``tick(force=True)`` bypasses it (tests, explicit supervision)
_TICK_S = 0.25


class _Window:
    """Rolling event counter: ``add`` stamps now, ``count`` forgets
    everything older than ``span_s``.  Tiny (error paths only — the
    hot path never touches it)."""

    __slots__ = ("span_s", "_events")

    def __init__(self, span_s: float):
        self.span_s = span_s
        self._events: deque = deque()

    def add(self, n: int = 1, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        # prune on ADD too: while a breaker is open/degraded nothing
        # evaluates count(), yet note_error keeps appending — a days-
        # long outage with a retrying writer must not grow this without
        # bound
        horizon = now - self.span_s
        ev = self._events
        while ev and ev[0][0] < horizon:
            ev.popleft()
        ev.append((now, n))

    def count(self, now: Optional[float] = None) -> int:
        now = time.monotonic() if now is None else now
        horizon = now - self.span_s
        ev = self._events
        while ev and ev[0][0] < horizon:
            ev.popleft()
        return sum(n for _, n in ev)

    def clear(self) -> None:
        self._events.clear()


class _RingBreaker:
    """One ring's breaker + health window."""

    __slots__ = ("state", "window", "opened_at", "half_open_at",
                 "last_restart", "last_failed")

    def __init__(self, window_s: float):
        self.state = CLOSED
        self.window = _Window(window_s)
        self.opened_at = 0.0
        self.half_open_at = 0.0
        self.last_restart = -1e9   # first restart is never backoff-gated
        self.last_failed = 0       # C failed-counter watermark


class DegradedRead:
    """Pending-shaped degraded-mode read: one plain synchronous
    ``pread`` (``strom_read_buffered``) on ``wait()`` — no ring, no
    uring, no staging buffer, no retry/hedge machinery.  This is the
    brown-out path: reduced bandwidth, but alive while every fast
    domain is sick.  EOF tails surface as a short view, exactly like
    an engine read (``wait_exact`` raises identically)."""

    __slots__ = ("_engine", "fh", "offset", "_length", "_stats",
                 "_view", "_released", "_ctx")

    #: the payload rode the page cache — fallback semantics, honestly
    was_fallback = True

    def __init__(self, base_engine, fh: int, offset: int, length: int,
                 stats=None):
        self._engine = base_engine
        self.fh = fh
        self.offset = offset
        self._length = length
        self._stats = stats
        self._view: Optional[np.ndarray] = None
        self._released = False
        #: causal identity, captured at construction (the pread runs at
        #: wait() time, possibly on another thread) — degraded service
        #: must stay visible in a request's trace tree, and an
        #: out-of-scope read must stay OUT of whatever request happens
        #: to be current on the waiting thread (NO_CONTEXT default)
        from nvme_strom_tpu.utils.trace import NO_CONTEXT, attach_context
        self._ctx = NO_CONTEXT
        tracer = getattr(base_engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            self._ctx = attach_context()

    @property
    def length(self) -> int:
        return self._length

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        del timeout   # synchronous: the pread happens here, bounded by I/O
        if self._view is None:
            t0 = time.monotonic_ns()
            self._view = self._engine.read_buffered(
                self.fh, self.offset, self._length)
            t1 = time.monotonic_ns()
            if self._stats is not None:
                self._stats.add(degraded_bytes=int(self._view.nbytes))
                # delivered, but through the page-cache brown-out on a
                # condemned device — the ledger's degraded waste class
                from nvme_strom_tpu.obs.ledger import charge_waste
                charge_waste(self._stats, "degraded",
                             int(self._view.nbytes))
            tracer = getattr(self._engine, "tracer", None)
            if tracer is not None and tracer.enabled:
                tracer.add_span("strom.read.degraded", t0, t1,
                                category="strom.health", ctx=self._ctx,
                                bytes=int(self._view.nbytes))
            flight = getattr(self._engine, "flight", None)
            if flight is not None:
                flight.record("read", None, -1, self.fh, self.offset,
                              int(self._view.nbytes),
                              max(0, t1 - t0) // 1000, "degraded")
        return self._view

    def is_ready(self) -> bool:
        return True

    def release(self) -> None:
        self._released = True
        self._view = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class EngineSupervisor:
    """The failure-domain supervisor of one StromEngine.

    No background threads: supervision rounds (``tick``) run on caller
    threads — time-gated from the QoS scheduler's admission poll, the
    planner's submit boundary, and the resilient wait loop — so every
    decision is deterministic and test-drivable (``tick(force=True)``).
    """

    def __init__(self, engine, config: Optional[BreakerConfig] = None):
        self._engine = engine          # the BASE StromEngine
        self.cfg = config or BreakerConfig()
        n = getattr(engine, "n_rings", 1)
        self.rings = [_RingBreaker(self.cfg.window_s) for _ in range(n)]
        self.device_window = _Window(self.cfg.window_s)
        self._degraded = False         # device breaker open
        self._lock = make_rlock("health.EngineSupervisor._lock")
        self._next_tick = 0.0
        self._next_probe = 0.0
        self._rr = 0                   # healthy-ring round-robin cursor
        self._probe_zombies: list = []
        #: (engine, fh, offset, length) of the last degraded-served
        #: span: lets tick() keep probing for recovery even when the
        #: brown-out (plus serving's load shedding) has stopped all
        #: batch traffic — otherwise an idle degraded engine could
        #: never close its device breaker
        self._probe_hint: Optional[tuple] = None
        self._closed = False
        #: degraded-transition observers, called OUTSIDE the lock with
        #: the new state (True = brown-out entered, False = recovered).
        #: The cold-start coordinator registers here to count brown-outs
        #: that land mid-restore (io/coldstart.py); listeners must be
        #: cheap and must not raise into the breaker path.
        self._degraded_listeners: list = []

    # -- cheap queries (hot paths read these without the lock) -------------

    def degraded(self) -> bool:
        """Device breaker open: serve the buffered brown-out path."""
        return self._degraded

    def unhealthy(self) -> bool:
        """Any domain currently not fully trusted (degraded, or any
        ring breaker open/half-open) — what the SLO governor checks
        before boosting hedges into the device."""
        return self._degraded or any(r.state != CLOSED
                                     for r in self.rings)

    def ring_states(self) -> List[str]:
        return [r.state for r in self.rings]

    def mask_free_slots(self, free: List[int]) -> List[int]:
        """The QoS scheduler's admission filter: a ring with an OPEN
        breaker reports zero headroom, so new batches route to healthy
        rings.  Half-open rings admit (that is how they prove
        themselves).  While degraded nothing is masked — the planner
        already bypasses the engine, and any straggler batch must not
        starve in the grant loop."""
        if self._degraded:
            return free
        if all(r.state != OPEN for r in self.rings):
            return free
        return [0 if self.rings[i].state == OPEN else f
                for i, f in enumerate(free[:len(self.rings)])]

    def pick_ring(self) -> Optional[int]:
        """Healthy ring for scalar submissions (retries, hedges,
        probes): None when every ring is trusted (keep the C
        round-robin) or none is (the caller can't do better)."""
        states = [r.state for r in self.rings]
        if OPEN not in states:
            return None
        healthy = [i for i, s in enumerate(states) if s != OPEN]
        if not healthy:
            return None
        self._rr += 1
        return healthy[self._rr % len(healthy)]

    # -- ingestion (error paths only) --------------------------------------

    def note_error(self, ring: int = -1, err: Optional[int] = None,
                   engine_counted: bool = False) -> None:
        """One failed read/write attempt observed ABOVE the C engine
        (ResilientEngine feeds this per attempt) — how Python-level
        fault plans and consumer-visible errors reach the breakers.
        Cancellations are requeues, never device damage, and errors the
        C engine already counted (``engine_counted`` — real completion
        failures, which tick() ingests from the per-ring counters) are
        skipped here so one error never burns the budget twice."""
        if err == errno.ECANCELED or engine_counted or self._closed:
            return
        now = time.monotonic()
        stats = getattr(self._engine, "stats", None)
        with self._lock:
            self.device_window.add(now=now)
            if 0 <= ring < len(self.rings):
                rb = self.rings[ring]
                rb.window.add(now=now)
                if (rb.state in (CLOSED, HALF_OPEN)
                        and rb.window.count(now) >= self.cfg.ring_errors):
                    self._trip_ring(ring, now, stats)
            self._check_device(now, stats)

    # -- the supervision round ---------------------------------------------

    def tick(self, force: bool = False) -> None:
        """One supervision round: poll the C ring counters, detect
        stalls and error budgets crossed below Python, restart tripped
        rings (backoff-gated), close clean half-open breakers.  Time-
        gated (``_TICK_S``) and contention-free: a round already
        running absorbs this call."""
        now = time.monotonic()
        if not force and now < self._next_tick:
            return
        if not self._lock.acquire(blocking=force):
            return
        probe_hint = None
        try:
            if self._closed:
                return
            self._next_tick = now + _TICK_S
            stats = getattr(self._engine, "stats", None)
            self._reap_probe_zombies()
            for i, rb in enumerate(self.rings):
                try:
                    info = self._engine.ring_info(i)
                except (OSError, AttributeError):
                    continue
                failed = int(info.get("failed", 0))
                delta = failed - rb.last_failed
                rb.last_failed = failed
                if delta > 0:
                    rb.window.add(delta, now=now)
                    self.device_window.add(delta, now=now)
                stalled = (int(info.get("oldest_inflight_ns", 0))
                           > self.cfg.stall_s * 1e9)
                if rb.state in (CLOSED, HALF_OPEN) and (
                        stalled
                        or rb.window.count(now) >= self.cfg.ring_errors):
                    self._trip_ring(i, now, stats)
                if rb.state == OPEN and (
                        now - rb.last_restart
                        >= self.cfg.restart_backoff_s):
                    self._restart_ring(i, now, stats)
                if rb.state == HALF_OPEN and (
                        now - rb.half_open_at >= self.cfg.half_open_s
                        and rb.window.count(now) == 0):
                    rb.state = CLOSED
            self._check_device(now, stats)
            self._export_gauges(stats)
            if self._degraded:
                probe_hint = self._probe_hint
        finally:
            self._lock.release()
        if probe_hint is not None:
            # outside the lock: a probe waits on real I/O and must not
            # block note_error/mask queries behind it
            eng, fh, off, ln = probe_hint
            self._maybe_probe(eng, [(fh, off, ln)],
                              getattr(eng, "stats", None))

    def _flight_dump(self, reason: str, **extra) -> None:
        """Post-mortem trigger (io/flightrec.py): capture the recent-op
        ring at the moment a failure-domain verdict lands."""
        flight = getattr(self._engine, "flight", None)
        if flight is not None:
            flight.dump(reason, extra=extra or None)

    def _trip_ring(self, ring: int, now: float, stats) -> None:
        rb = self.rings[ring]
        rb.state = OPEN
        rb.opened_at = now
        if stats is not None:
            stats.add(breaker_trips=1)
        self._flight_dump("breaker_trip", ring=ring,
                          window_errors=rb.window.count(now))
        # all rings open == no healthy failure domain left: that IS the
        # device verdict, decided here atomically so the scheduler can
        # never face an all-masked ring set outside degraded mode
        if all(r.state == OPEN for r in self.rings):
            self._enter_degraded(now, stats)

    def _restart_ring(self, ring: int, now: float, stats) -> None:
        """Hot restart (strom_ring_restart): cancelled extents requeue
        through their waiters' retry loop; -ETIMEDOUT keeps the breaker
        open (an undrainable ring is the degraded path's problem)."""
        rb = self.rings[ring]
        rb.last_restart = now
        t0 = time.monotonic_ns()
        try:
            cancelled = self._engine.ring_restart(ring, self.cfg.drain_s)
        except TimeoutError:
            return        # undrainable in-flight I/O: breaker stays
            #               open, the degraded path is the fallback
        except (OSError, AttributeError):
            return        # EBUSY (concurrent restart) / teardown race
        if stats is not None:
            stats.add(ring_restarts=1,
                      **({"extents_requeued": cancelled}
                         if cancelled else {}))
        tracer = getattr(self._engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            tracer.add_span("strom.health.ring_restart", t0,
                            time.monotonic_ns(),
                            category="strom.health", ring=ring,
                            cancelled=cancelled)
        self._flight_dump("ring_restart", ring=ring,
                          cancelled=cancelled)
        rb.window.clear()
        rb.state = HALF_OPEN
        rb.half_open_at = time.monotonic()

    def _check_device(self, now: float, stats) -> None:
        if self._degraded:
            return
        if self.device_window.count(now) >= self.cfg.device_errors:
            self._enter_degraded(now, stats)

    def _enter_degraded(self, now: float, stats) -> None:
        if not self._degraded:
            self._degraded = True
            self._next_probe = now + self.cfg.probe_s
            if stats is not None:
                stats.add(breaker_trips=1)   # the device breaker's trip
            # a multi-tenant box's degraded-device post-mortem wants
            # WHOSE traffic was on the device when it went sick — embed
            # the per-tenant ledger when one exists (empty = old dump)
            tenants = (stats.tenant_stats
                       if stats is not None else {})
            self._flight_dump("device_degraded",
                              device_errors=self.device_window.count(now),
                              **({"tenant_stats": tenants}
                                 if tenants else {}))
            self._export_gauges(stats)
            self._notify_degraded(True)

    def _recover(self, stats) -> None:
        """A half-open probe succeeded: restore the fast path.  Open
        ring breakers move to half-open (they close after a clean
        interval; fresh errors re-trip them immediately)."""
        with self._lock:
            self._degraded = False
            self._probe_hint = None   # episode over: a later one must
            #                           re-learn a live (fh, span)
            self.device_window.clear()
            now = time.monotonic()
            for rb in self.rings:
                if rb.state == OPEN:
                    rb.state = HALF_OPEN
                    rb.half_open_at = now
                rb.window.clear()
            self._export_gauges(stats)
        self._notify_degraded(False)

    def add_degraded_listener(self, fn) -> None:
        """Register an observer of device-breaker transitions (called
        with True on brown-out entry, False on recovery)."""
        self._degraded_listeners.append(fn)

    def _notify_degraded(self, on: bool) -> None:
        for fn in list(self._degraded_listeners):
            try:
                fn(on)
            except Exception:
                pass   # an observer must never wedge the breaker

    def _export_gauges(self, stats) -> None:
        if stats is not None:
            stats.set_gauges(ring_health=self.ring_states(),
                             engine_degraded=int(self._degraded))

    # -- degraded service ---------------------------------------------------

    def serve_degraded(self, engine, spans: Sequence,
                       stats=None) -> Optional[list]:
        """Serve ``(fh, offset, length)`` spans as :class:`DegradedRead`
        buffered preads — the brown-out.  First runs the half-open
        probe (one real-path read per ``probe_s``, through ``engine``,
        the TOP of the wrapper stack, so a Python-level fault plan
        gates recovery exactly like a device fault); a probe success
        recovers and returns None — the caller re-takes the fast path
        for this very batch."""
        if stats is None:
            stats = getattr(engine, "stats", None)
        if spans:
            fh, off, ln = next(
                ((f, o, n) for f, o, n in spans if n > 0), spans[0])
            self._probe_hint = (engine, fh, off, ln)
            if self._maybe_probe(engine, spans, stats):
                return None
        out = [DegradedRead(self._engine, fh, off, ln, stats)
               for fh, off, ln in spans]
        if stats is not None and out:
            stats.add(degraded_reads=len(out))
        return out

    def degraded_pending(self, fh: int, offset: int, length: int,
                         stats=None, probe_engine=None) -> DegradedRead:
        """One degraded read (counted) — the resilient retry loop's
        fallback for a read already mid-recovery when the device
        breaker opens: its next attempt browns out instead of burning
        the rest of its retry budget against a sick device.

        ``probe_engine``: the engine the recovery probe should ride —
        the layer BELOW the resilient wrapper (fault injection
        included), so a Python-level storm gates recovery exactly like
        a device fault; defaults to the base engine."""
        if stats is None:
            stats = getattr(self._engine, "stats", None)
        if stats is not None:
            stats.add(degraded_reads=1)
        # refresh the recovery hint with the MOST RECENT live span: a
        # device that degraded mid-read and then went idle is probed by
        # tick() from here, and an older hint may name an fh the
        # consumer has since closed
        self._probe_hint = (probe_engine or self._engine, fh, offset,
                            length)
        return DegradedRead(self._engine, fh, offset, length, stats)

    def _maybe_probe(self, engine, spans, stats) -> bool:
        """True when the probe ran AND succeeded (fast path restored)."""
        now = time.monotonic()
        if self.cfg.probe_s > 0 and now < self._next_probe:
            return False
        with self._lock:
            if now < self._next_probe and self.cfg.probe_s > 0:
                return False           # another thread probed first
            self._next_probe = now + max(self.cfg.probe_s, 1e-9)
        fh, off, ln = next(
            ((f, o, n) for f, o, n in spans if n > 0), spans[0])
        # the probe must ride the RAW path: a ResilientEngine on top
        # would retry the probe into the degraded fallback and mask the
        # very failure being probed (recovery would flap) — step below
        # it; a fault layer (FaultyEngine) stays, so Python-level
        # storms gate recovery exactly like device faults
        from nvme_strom_tpu.io.resilient import ResilientEngine
        while isinstance(engine, ResilientEngine):
            engine = engine._engine
        ok = False
        pending = None
        t0 = time.monotonic_ns()
        try:
            pending = engine.submit_read(fh, off,
                                         min(ln, _PROBE_BYTES))
            pending.wait(timeout=self.cfg.probe_timeout_s)
            ok = True
        except TimeoutError:
            # still in flight: park it (release would block on the very
            # wedge being probed); reaped on later ticks/probes.  Under
            # the lock — an unsynchronized append can lose the race
            # against _reap_probe_zombies' list swap and leak the
            # probe's staging-pool slot for the life of the engine.
            with self._lock:
                self._probe_zombies.append(pending)
            pending = None
        except OSError:
            ok = False                 # wait released the request
            if pending is None:
                # the SUBMIT itself failed (closed fh, teardown): this
                # span can never probe again — drop a hint naming it so
                # tick() doesn't re-probe a dead fh forever
                with self._lock:
                    if (self._probe_hint is not None
                            and self._probe_hint[1] == fh):
                        self._probe_hint = None
            pending = None
        finally:
            if pending is not None:
                try:
                    pending.release()
                except OSError:
                    pass
        if stats is not None:
            stats.add(degraded_probes=1)
        tracer = getattr(self._engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            tracer.add_span("strom.health.probe", t0,
                            time.monotonic_ns(),
                            category="strom.health", fh=fh, offset=off,
                            ok=ok)
        if ok:
            self._recover(stats)
        return ok

    def _reap_probe_zombies(self) -> None:
        survivors = []
        for p in self._probe_zombies:
            try:
                if p.is_ready():
                    p.release()
                else:
                    survivors.append(p)
            except OSError:
                pass
        self._probe_zombies = survivors

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Engine teardown: release landed probe zombies and stop
        supervising.  Still-in-flight zombies are left to the engine's
        own drain (which must wait for the kernel regardless)."""
        with self._lock:
            self._closed = True
            self._reap_probe_zombies()
            self._probe_zombies = []
