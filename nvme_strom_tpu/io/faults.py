"""Fault injection at the submit/wait boundary — the chaos half of the
recovery story (docs/RESILIENCE.md).

The paper's DMA chain (NVMe → locked host buffers → TPU) hard-fails on
any link error in the reference; this module makes every failure mode of
that chain *reproducible on demand* so the recovery paths
(``io/resilient.py``, the loader's shard quarantine, checkpoint
restore-fallback) are testable without flaky hardware:

    plan   = FaultPlan.parse("eio:p=0.05, delay:every=100:delay_s=0.2")
    engine = FaultyEngine(StromEngine(), plan)

``FaultyEngine`` wraps any engine-shaped object and injects faults into
the ``PendingRead``s/``PendingWrite``s it hands out — no C rebuild
required.  The fault taxonomy (one class per link of the chain):

    eio      the device/kernel failed the read        → OSError(EIO)
    short    the read returned fewer bytes than asked → truncated view
    delay    a latency straggler                      → wait blocks longer
    stuck    a wedged request                         → waits time out
    bitflip  payload corrupted in flight              → one byte flipped
    estorm   a bounded EIO *storm*: the next max_count matching reads
             ALL fail (consecutive, then clean) — the whole-device
             brown-out that drives the breaker / degraded-mode story
             (io/health.py, docs/RESILIENCE.md "failure domains")

and the write-path mirror (the durability story's failure modes —
checkpoint saves, optimizer spill, KV eviction):

    weio     the device/kernel failed the write       → OSError(EIO)
    wenospc  the namespace filled up                  → OSError(ENOSPC)
    wshort   fewer bytes committed than submitted     → short wait() count
    wdelay   a write-completion straggler             → wait blocks longer

Crash-at-point injection (torn-save recovery) is process-level, not
request-level: ``crash_point(name)`` calls mark the checkpoint commit
sequence's crash windows (tile write → marker → manifest → rename), and
``STROM_CRASH_POINT=<name>`` kills the process (os._exit) at exactly
that point — the subprocess half of the crash-recovery tests.

Plans are deterministic: decisions come from ``random.Random(seed)`` in
submit order, so a failing CI run replays exactly.  For injection BELOW
Python (exercising the C completion path itself), the engine honors
``STROM_FAULT_READ_EIO_EVERY`` / ``STROM_FAULT_READ_SHORT_EVERY`` /
``STROM_FAULT_READ_DELAY_MS`` — and the write mirror
``STROM_FAULT_WRITE_EIO_EVERY`` / ``STROM_FAULT_WRITE_ENOSPC_EVERY`` /
``STROM_FAULT_WRITE_SHORT_EVERY`` / ``STROM_FAULT_WRITE_DELAY_MS`` — at
``strom_engine_create`` time (see csrc/strom_io.cc).  The failure-DOMAIN
kind lives below even that: ``STROM_FAULT_RING_STALL_RING`` /
``STROM_FAULT_RING_STALL_AFTER`` (or :func:`set_ring_stall` on a live
engine) wedge one submission ring — its dispatches park and completions
never arrive — which is the deterministic drive for the supervision
layer's stall detector, circuit breakers, and hot ring restart
(io/health.py, docs/RESILIENCE.md "failure domains").

Every injected fault is counted (``StromStats.faults_injected``), tagged
per kind on the plan, and traced (``strom.fault.<kind>`` spans in
utils/trace.py) — a chaos run's injections are auditable next to the
recoveries they provoked.
"""

from __future__ import annotations

import errno
import os
import random
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

READ_FAULT_KINDS = ("eio", "short", "delay", "stuck", "bitflip",
                    "estorm")
WRITE_FAULT_KINDS = ("weio", "wenospc", "wshort", "wdelay")
FAULT_KINDS = READ_FAULT_KINDS + WRITE_FAULT_KINDS


def set_ring_stall(engine, ring: int, on: bool = True) -> None:
    """Arm/disarm the C-level ring-stall injection on a live engine —
    unwraps any Faulty/Resilient stack to the base StromEngine (the
    stall lives below all of them: requests park at the ring's dispatch
    point and completions never arrive).  The deterministic wedged-ring
    drive for the supervision layer (io/health.py); the env twins
    ``STROM_FAULT_RING_STALL_RING`` / ``STROM_FAULT_RING_STALL_AFTER``
    arm it at engine create for subprocess chaos runs."""
    base = engine
    while getattr(base, "_engine", None) is not None \
            and not hasattr(base, "set_ring_stall"):
        base = base._engine
    base.set_ring_stall(ring, on)


def crash_point(name: str) -> None:
    """Deterministic crash injection: when ``$STROM_CRASH_POINT`` equals
    ``name``, the process dies HERE (``os._exit`` — no atexit, no
    flushes, exactly what a power loss or OOM-kill leaves behind).
    Instrumented at the checkpoint commit sequence's crash windows
    (checkpoint/manager.py): ``ckpt.tiles``, ``ckpt.marker``,
    ``ckpt.meta``, ``ckpt.rename``.  Zero cost when the env is unset."""
    want = os.environ.get("STROM_CRASH_POINT")
    if want and want == name:
        os._exit(137)


@dataclass(frozen=True)
class FaultSpec:
    """One fault class plus its trigger rule.

    Triggering: ``every`` (deterministic: the Nth, 2Nth, ... matching
    read) wins over ``p`` (per-read probability from the plan's seeded
    rng).  ``max_count`` bounds total injections from this spec
    (0 = unlimited).  ``path_substr`` restricts injection to reads of
    files whose path contains the substring ("" = all files).
    """

    kind: str
    p: float = 1.0
    every: int = 0
    max_count: int = 0
    #: delay/stuck duration (seconds).  Negative (the default) resolves
    #: per kind in __post_init__: 0.05 for a latency spike, 300 for
    #: 'stuck' — far past any reasonable stuck_timeout so
    #: cancel-then-retry always triggers first, while staying finite (an
    #: abandoned stuck read can never hang teardown forever)
    delay_s: float = -1.0
    #: errno raised by 'eio' faults
    err: int = errno.EIO
    #: fraction of the payload kept by 'short' faults
    frac: float = 0.5
    path_substr: str = ""

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {FAULT_KINDS}")
        if not 0 <= self.p <= 1:
            raise ValueError(f"p ({self.p}) must be in [0, 1]")
        if self.every < 0 or self.max_count < 0:
            raise ValueError("every/max_count must be >= 0")
        if self.delay_s < 0:   # auto: same default however constructed
            object.__setattr__(
                self, "delay_s", 300.0 if self.kind == "stuck" else 0.05)
        if not 0 <= self.frac < 1:
            raise ValueError(f"frac ({self.frac}) must be in [0, 1)")
        if self.kind == "wenospc" and self.err == errno.EIO:
            # the kind IS the errno: 'wenospc' without an explicit err=
            # models the namespace filling up
            object.__setattr__(self, "err", errno.ENOSPC)
        if self.kind == "estorm" and self.max_count == 0:
            # an EIO *storm* is bounded by definition: CONSECUTIVE
            # failures for max_count matching reads, then clean — the
            # deterministic device-brown-out drive for the breaker /
            # degraded-mode story (io/health.py).  every/p are ignored:
            # a storm that skips reads isn't a storm.
            object.__setattr__(self, "max_count", 16)

    @property
    def is_write(self) -> bool:
        return self.kind in WRITE_FAULT_KINDS


_SPEC_FLOAT = {"p", "delay_s", "frac"}
_SPEC_INT = {"every", "max_count", "err"}


class FaultPlan:
    """A seeded, ordered list of FaultSpecs; decides per submitted
    request (reads and writes draw from separate taxonomy halves of
    the same plan — see ``decide``'s ``op``).

    The first spec whose trigger matches wins, so ordering encodes
    priority.  ``injected`` tallies injections per kind — tests assert
    against it, and tools/strom_stat reads the aggregate via
    ``StromStats.faults_injected``.
    """

    def __init__(self, specs, seed: int = 0):
        self.specs: List[FaultSpec] = list(specs)
        self.seed = seed
        self._rng = random.Random(seed)
        self._reads = 0
        self._matches: Dict[int, int] = {}   # spec index → matching reads
        self._fired: Dict[int, int] = {}     # spec index → injections
        self.injected: Dict[str, int] = {}   # kind → injections

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        """``"eio:p=0.1, delay:every=3:delay_s=0.2"`` → FaultPlan.

        Comma-separated specs; each is ``kind[:key=value]...``.  Keys are
        the FaultSpec fields (p, every, max_count, delay_s, err, frac,
        path).  'stuck' without an explicit delay_s defaults to 300 s.
        """
        specs = []
        for part in filter(None, (s.strip() for s in text.split(","))):
            kind, _, rest = part.partition(":")
            kw: dict = {}
            for item in filter(None, (s.strip() for s in rest.split(":"))):
                key, eq, val = item.partition("=")
                if not eq:
                    raise ValueError(
                        f"fault spec {part!r}: expected key=value, "
                        f"got {item!r}")
                if key == "path":
                    kw["path_substr"] = val
                elif key in _SPEC_FLOAT:
                    kw[key] = float(val)
                elif key in _SPEC_INT:
                    kw[key] = int(val)
                else:
                    raise ValueError(
                        f"fault spec {part!r}: unknown key {key!r}")
            specs.append(FaultSpec(kind=kind, **kw))
        return cls(specs, seed=seed)

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        """Plan from ``$STROM_FAULTS`` (+ ``$STROM_FAULTS_SEED``); None
        when unset — the zero-cost production default."""
        text = os.environ.get("STROM_FAULTS")
        if not text:
            return None
        return cls.parse(text, seed=int(os.environ.get(
            "STROM_FAULTS_SEED", "0")))

    def decide(self, path: str = "", op: str = "read"
               ) -> Optional[FaultSpec]:
        """Fault for the next submitted request (None = runs clean).
        ``op`` selects the taxonomy half: read specs never fire on
        writes and vice versa, so one plan can chaos both directions
        of the chain with independent triggers."""
        self._reads += 1
        want_write = op == "write"
        for i, spec in enumerate(self.specs):
            if spec.is_write != want_write:
                continue
            if spec.path_substr and spec.path_substr not in path:
                continue
            if spec.max_count and self._fired.get(i, 0) >= spec.max_count:
                continue
            n = self._matches[i] = self._matches.get(i, 0) + 1
            if spec.kind == "estorm":
                hit = True      # consecutive until max_count exhausts
            elif spec.every:
                hit = n % spec.every == 0
            else:
                hit = self._rng.random() < spec.p
            if hit:
                self._fired[i] = self._fired.get(i, 0) + 1
                self.injected[spec.kind] = \
                    self.injected.get(spec.kind, 0) + 1
                return spec
        return None

    def corrupt_byte(self, length: int) -> tuple[int, int]:
        """(index, xor mask) for a bitflip — from the plan's own rng so
        corruption position replays with the seed."""
        return (self._rng.randrange(max(1, length)),
                1 << self._rng.randrange(8))


class FaultyRead:
    """A PendingRead with a fault grafted onto its wait/release path.

    Honors the engine contract exactly: ``wait(timeout=...)`` raises
    TimeoutError with the request STILL LIVE; errors release the staging
    buffer before raising (mirroring PendingRead.wait); ``is_ready`` is a
    non-throwing probe; ``release`` is idempotent.
    """

    def __init__(self, inner, spec: FaultSpec, plan: FaultPlan):
        self._inner = inner
        self._spec = spec
        self._plan = plan
        self._t0 = time.monotonic()
        self._view: Optional[np.ndarray] = None
        self._error: Optional[OSError] = None
        self._released = False

    @property
    def was_fallback(self) -> bool:
        return self._inner.was_fallback

    @property
    def length(self) -> int:
        """Bytes requested at submit — NOT shrunk by a 'short' fault:
        consumers compare the completed view against this to detect
        exactly that truncation."""
        return self._inner.length

    @property
    def fh(self) -> int:
        return getattr(self._inner, "fh", -1)

    @property
    def offset(self) -> int:
        return getattr(self._inner, "offset", -1)

    @property
    def ring(self) -> int:
        """Failure-domain attribution rides through the fault layer."""
        return getattr(self._inner, "ring", -1)

    def _remaining_delay(self) -> float:
        if self._spec.kind not in ("delay", "stuck"):
            return 0.0
        return self._spec.delay_s - (time.monotonic() - self._t0)

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if self._view is not None:
            return self._view
        if self._error is not None:
            raise self._error
        remain = self._remaining_delay()
        if remain > 0:
            # latency spike / wedged request: the underlying read may be
            # long done, but this request refuses to complete yet
            if timeout is not None and timeout < remain:
                time.sleep(timeout)
                raise TimeoutError(
                    f"read still in flight after {timeout}s "
                    f"(injected {self._spec.kind})")
            time.sleep(remain)
            if timeout is not None:
                timeout = max(0.0, timeout - remain)
        if self._spec.kind in ("eio", "estorm"):
            self._error = OSError(self._spec.err,
                                  os.strerror(self._spec.err)
                                  + " (injected)")
            self._inner.release()
            raise self._error
        view = self._inner.wait(
            timeout=None if timeout is None else max(0.0, timeout))
        if self._spec.kind == "short" and view.nbytes > 0:
            view = view[:int(view.nbytes * self._spec.frac)]
        elif self._spec.kind == "bitflip" and view.nbytes > 0:
            idx, mask = self._plan.corrupt_byte(view.nbytes)
            # flip in the staging view itself — exactly what in-flight
            # corruption looks like to every downstream consumer
            view[idx] ^= mask
        self._view = view
        return view

    def is_ready(self) -> bool:
        if self._view is not None or self._error is not None \
                or self._released:
            return True
        if self._remaining_delay() > 0:
            return False
        # eio included: completed-with-error counts as ready (wait() will
        # raise) — mirrors PendingRead.is_ready caching semantics
        return self._inner.is_ready()

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._view = None
        self._inner.release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class FaultyWrite:
    """A PendingWrite with a write fault grafted onto its wait path.

    Honors the write contract exactly: ``wait()`` returns the byte
    count actually committed (a ``wshort`` fault shrinks it — the
    signal the resilient write mirror resubmits on); error kinds
    release the request before raising (PendingWrite.wait parity);
    ``release`` is idempotent.
    """

    def __init__(self, inner, spec: FaultSpec):
        self._inner = inner
        self._spec = spec
        self._t0 = time.monotonic()
        self._released = False

    @property
    def fh(self) -> int:
        return getattr(self._inner, "fh", -1)

    @property
    def offset(self) -> int:
        return getattr(self._inner, "offset", -1)

    @property
    def length(self) -> int:
        return getattr(self._inner, "length", 0)

    @property
    def ring(self) -> int:
        return getattr(self._inner, "ring", -1)

    def wait(self, timeout: Optional[float] = None) -> int:
        if self._spec.kind == "wdelay":
            remain = self._spec.delay_s - (time.monotonic() - self._t0)
            if remain > 0:
                if timeout is not None and timeout < remain:
                    time.sleep(timeout)
                    raise TimeoutError(
                        f"write still in flight after {timeout}s "
                        f"(injected wdelay)")
                time.sleep(remain)
                if timeout is not None:
                    timeout = max(0.0, timeout - remain)
        n = self._inner.wait(timeout)
        self._released = True
        if self._spec.kind in ("weio", "wenospc"):
            raise OSError(self._spec.err,
                          os.strerror(self._spec.err) + " (injected)")
        if self._spec.kind == "wshort" and n > 1:
            return int(n * self._spec.frac)
        return n

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._inner.release()


def build_engine(config=None, stats=None, tracer=None):
    """Default engine factory for consumers (loader, checkpoint, weight
    streaming): a plain StromEngine, wrapped per the resilience env
    knobs so ANY existing run becomes a chaos and/or self-healing run
    without code changes (docs/RESILIENCE.md):

    - ``STROM_FAULTS`` set       → FaultyEngine with the env FaultPlan
    - ``STROM_RESILIENT=1``      → ResilientEngine on top (retry /
                                   hedge / cancel-stuck per the
                                   STROM_RETRY_* / STROM_HEDGE_* /
                                   STROM_STUCK_* knobs)

    Both unset (the default) returns the bare engine — zero added
    indirection on the hot path.
    """
    from nvme_strom_tpu.io.engine import StromEngine
    from nvme_strom_tpu.utils.config import EngineConfig

    eng = StromEngine(config or EngineConfig(), stats=stats,
                      tracer=tracer)
    plan = FaultPlan.from_env()
    if plan is not None:
        eng = FaultyEngine(eng, plan)
    if os.environ.get("STROM_RESILIENT", "0") == "1":
        from nvme_strom_tpu.io.resilient import ResilientEngine
        eng = ResilientEngine(eng)
    return eng


class FaultyEngine:
    """Engine wrapper injecting a FaultPlan at the submit boundary.

    Transparent to consumers (ShardedLoader, CheckpointManager,
    ResilientEngine): everything but ``open``/``close`` and the three
    submit paths (``submit_read``/``submit_readv``/``submit_write``)
    delegates to the wrapped engine.  Stack under ResilientEngine —
    ``ResilientEngine(FaultyEngine(StromEngine(), plan))`` — so
    recoveries (read AND write) are exercised against the injected
    faults.
    """

    def __init__(self, engine, plan: Optional[FaultPlan] = None):
        self._engine = engine
        self.plan = plan if plan is not None else FaultPlan.from_env()
        if self.plan is None:
            self.plan = FaultPlan([])
        self._paths: Dict[int, str] = {}

    def open(self, path, **kw) -> int:
        fh = self._engine.open(path, **kw)
        self._paths[fh] = str(path)
        return fh

    def close(self, fh: int) -> None:
        self._paths.pop(fh, None)
        self._engine.close(fh)

    def _maybe_fault(self, pending, fh: int, offset: int, length: int,
                     op: str = "read"):
        """Per-request injection decision + accounting, shared by the
        scalar, vectored, and write submit paths."""
        spec = self.plan.decide(self._paths.get(fh, ""), op=op)
        if spec is None:
            return pending
        self.stats.add(faults_injected=1)
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            now = time.monotonic_ns()
            tracer.add_span(f"strom.fault.{spec.kind}", now, now,
                            category="strom.fault", fh=fh, offset=offset,
                            length=length)
        if op == "write":
            return FaultyWrite(pending, spec)
        return FaultyRead(pending, spec, self.plan)

    def submit_read(self, fh: int, offset: int, length: int,
                    klass: Optional[str] = None):
        # scalar routing stays class-blind (engine contract); the tag
        # rides through for flight-recorder attribution only
        pending = self._engine.submit_read(fh, offset, length,
                                           klass=klass)
        return self._maybe_fault(pending, fh, offset, length)

    def submit_readv(self, reads, klass: Optional[str] = None) -> list:
        """Vectored path: ONE batched submission through the wrapped
        engine (``klass`` rides along to the QoS scheduler below), then
        a PER-EXTENT injection decision — a chaos plan hits individual
        spans of a batch exactly as a real device fails individual
        commands of a multi-command submission."""
        from nvme_strom_tpu.io.plan import submit_spans
        reads = list(reads)
        pendings = submit_spans(self._engine, reads, klass=klass)
        return [self._maybe_fault(p, fh, offset, length)
                for (fh, offset, length), p in zip(reads, pendings)]

    def submit_write(self, fh: int, offset: int, data):
        """Write-path injection: the wrapped engine's write goes down
        unchanged; the handed-back PendingWrite carries the fault
        (weio/wenospc/wshort/wdelay) into its ``wait``."""
        pending = self._engine.submit_write(fh, offset, data)
        return self._maybe_fault(pending, fh, offset,
                                 getattr(pending, "length", 0),
                                 op="write")

    def read(self, fh: int, offset: int, length: int) -> np.ndarray:
        with self.submit_read(fh, offset, length) as p:
            out = p.wait().copy()
        self.stats.add(bounce_bytes=int(out.nbytes))
        return out

    def __getattr__(self, name):
        return getattr(self._engine, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._engine.close_all()
