from nvme_strom_tpu.io.engine import (
    StromEngine,
    PendingRead,
    PendingWrite,
    FileInfo,
    check_file,
)

__all__ = ["StromEngine", "PendingRead", "PendingWrite", "FileInfo",
           "check_file"]
