from nvme_strom_tpu.io.arena import (
    PinnedArena,
    Slab,
    get_arena,
)
from nvme_strom_tpu.io.engine import (
    StromEngine,
    PendingRead,
    PendingWrite,
    FileInfo,
    DeviceInfo,
    Extent,
    check_file,
    resolve_device,
    file_extents,
    file_eligible,
    wait_exact,
)
from nvme_strom_tpu.io.faults import (
    FaultPlan,
    FaultSpec,
    FaultyEngine,
    build_engine,
    crash_point,
)
from nvme_strom_tpu.io.flightrec import (
    FlightRecorder,
    flight_of,
)
from nvme_strom_tpu.io.health import (
    DegradedRead,
    EngineSupervisor,
)
from nvme_strom_tpu.io.hostcache import (
    CacheHitRead,
    HostCache,
    get_cache,
)
from nvme_strom_tpu.io.plan import (
    ExtentPlan,
    SpanView,
    plan_and_submit,
    plan_extents,
    split_spans,
    submit_spans,
    submit_spans_tiered,
)
from nvme_strom_tpu.io.resilient import (
    ReadError,
    ResilientEngine,
    ResilientRead,
    ResilientWrite,
    WriteError,
)
from nvme_strom_tpu.io.scatter import (
    ScatterServeEngine,
    ScatterStore,
    ShareManifest,
    partition_files,
)
from nvme_strom_tpu.io.sched import (
    CLASS_ORDER,
    DEFAULT_CLASS,
    ClassPolicy,
    QoSScheduler,
    default_policies,
)

__all__ = ["PinnedArena", "Slab", "get_arena",
           "StromEngine", "PendingRead", "PendingWrite", "FileInfo",
           "DeviceInfo", "Extent", "check_file", "resolve_device",
           "file_extents", "file_eligible", "wait_exact",
           "FaultPlan", "FaultSpec", "FaultyEngine", "build_engine",
           "crash_point",
           "FlightRecorder", "flight_of",
           "DegradedRead", "EngineSupervisor",
           "CacheHitRead", "HostCache", "get_cache",
           "ExtentPlan", "SpanView", "plan_and_submit", "plan_extents",
           "split_spans", "submit_spans", "submit_spans_tiered",
           "ScatterServeEngine", "ScatterStore", "ShareManifest",
           "partition_files",
           "ReadError", "ResilientEngine", "ResilientRead",
           "ResilientWrite", "WriteError",
           "CLASS_ORDER", "DEFAULT_CLASS", "ClassPolicy", "QoSScheduler",
           "default_policies"]
