from nvme_strom_tpu.io.engine import (
    StromEngine,
    PendingRead,
    PendingWrite,
    FileInfo,
    DeviceInfo,
    Extent,
    check_file,
    resolve_device,
    file_extents,
    file_eligible,
)

__all__ = ["StromEngine", "PendingRead", "PendingWrite", "FileInfo",
           "DeviceInfo", "Extent", "check_file", "resolve_device",
           "file_extents", "file_eligible"]
