from nvme_strom_tpu.io.engine import (
    StromEngine,
    PendingRead,
    PendingWrite,
    FileInfo,
    DeviceInfo,
    check_file,
    resolve_device,
    file_eligible,
)

__all__ = ["StromEngine", "PendingRead", "PendingWrite", "FileInfo",
           "DeviceInfo", "check_file", "resolve_device", "file_eligible"]
