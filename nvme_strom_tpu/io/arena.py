"""Unified pinned host arena — ONE mapping behind every pinned consumer.

Before this module each pinned region was its own mmap/mlock: the
engine's staging pool, the host-cache line arena (io/hostcache.py), and
any bridge-side DMA source each paid their own mapping, their own lock
policy, and — the real cost — their own identity: a byte could only
move between them by copy.  The arena collapses them into ONE
reservation (``strom_arena_create``: anonymous ``MAP_NORESERVE``
memory, virtual until touched) that a simple first-fit allocator carves
into tagged slabs:

  ``staging``    engine staging pools (``strom_engine_create_prealloc``
                 stages, DMA-targets, and registers the carve as fixed
                 buffers exactly as it would its own mapping — but
                 never unmaps it);
  ``hostcache``  the pinned cache-line arena;
  ``bridge``     the overlap pipeline's ping-pong host→HBM DMA slabs
                 (ops/bridge.py).

Pages commit (and best-effort mlock, gated by ``STROM_MLOCK``) per
CARVE, so a generous reservation costs nothing until used.  A carve
that cannot fit falls back to the consumer's private pre-arena path —
counted as ``arena_fallbacks``, never an error.

``STROM_ARENA=0`` removes the module entirely: every consumer takes its
exact pre-arena path, bit-for-bit (proven by test).  ``STROM_ARENA_MB``
sizes the reservation (default 1024 — virtual).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from nvme_strom_tpu.utils.lockwitness import make_lock

#: carve alignment: every slab starts O_DIRECT/page aligned, so an
#: engine pool carved here is exactly as alignment-conformant as its
#: own anonymous mapping would have been
CARVE_ALIGN = 4096


class Slab:
    """One tagged carve of the arena: a zero-copy numpy view plus the
    base address consumers hand to the C ABI.  ``release()`` returns
    the range to the arena's free list (idempotent)."""

    __slots__ = ("arena", "offset", "nbytes", "tag", "addr", "view",
                 "locked", "_released")

    def __init__(self, arena: "PinnedArena", offset: int, nbytes: int,
                 tag: str):
        self.arena = arena
        self.offset = offset
        self.nbytes = nbytes
        self.tag = tag
        self.addr = arena.base + offset
        self.view = arena.view[offset:offset + nbytes]
        #: did THIS carve's mlock hold (set by carve; consumers that
        #: report pin state — hostcache's ``arena_locked`` — read the
        #: slab's own verdict, not arena-wide history)
        self.locked = False
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self.view = None
        self.arena._free(self.offset, self.nbytes, locked=self.locked)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class PinnedArena:
    """First-fit slab allocator over one native reservation.

    Thread-safe.  The free list keeps ``(offset, nbytes)`` ranges sorted
    by offset and coalesces neighbours on release, so carve/release
    churn (tests build and tear down many engines) cannot fragment the
    arena into uselessness.  Accounting (:meth:`carves`) is exact:
    tagged bytes are disjoint by construction and sum with the free
    ranges to the arena size — the invariant tests/test_arena.py pins.
    """

    def __init__(self, nbytes: int, lock_pages: bool = True):
        if nbytes <= 0:
            raise ValueError("arena nbytes must be > 0")
        nbytes = (nbytes + CARVE_ALIGN - 1) // CARVE_ALIGN * CARVE_ALIGN
        self.nbytes = nbytes
        self.lock_pages = lock_pages
        self._lock = make_lock("arena.PinnedArena._lock")
        self._free_list: List[Tuple[int, int]] = [(0, nbytes)]
        self._carved: Dict[int, Tuple[int, str]] = {}   # off → (n, tag)
        self._lib = None
        self._base: Optional[int] = None
        self.locked_bytes = 0
        try:
            from nvme_strom_tpu.io.engine import _load_lib
            # private CDLL handle (ctypes caches one function object per
            # CDLL instance; sharing would let another module's argtypes
            # assignment silently retype ours — the PR-5 lesson)
            lib = ctypes.CDLL(_load_lib()._name)
            lib.strom_arena_create.restype = ctypes.c_void_p
            lib.strom_arena_create.argtypes = [ctypes.c_uint64]
            lib.strom_arena_destroy.restype = None
            lib.strom_arena_destroy.argtypes = [ctypes.c_void_p,
                                                ctypes.c_uint64]
            lib.strom_arena_lock.restype = ctypes.c_int
            lib.strom_arena_lock.argtypes = [ctypes.c_void_p,
                                             ctypes.c_uint64]
            base = lib.strom_arena_create(nbytes)
            if base:
                self._base = int(base)
                self._lib = lib
        except Exception:
            self._base = None
        if self._base is None:
            # trimmed install / exotic kernel: a plain numpy buffer is
            # unpinned but carves identically — consumers never notice
            self._buf = np.zeros(nbytes, dtype=np.uint8)
            self.base = self._buf.ctypes.data
            self.view = self._buf
        else:
            self.base = self._base
            self.view = np.ctypeslib.as_array(
                ctypes.cast(self._base,
                            ctypes.POINTER(ctypes.c_uint8)),
                shape=(nbytes,))
        self._closed = False

    # -- allocation --------------------------------------------------------

    def carve(self, nbytes: int, tag: str,
              lock: Optional[bool] = None) -> Optional[Slab]:
        """First-fit carve of ``nbytes`` (page-rounded) tagged ``tag``;
        None when no free range fits (the caller falls back to its
        private pre-arena path and counts ``arena_fallbacks``).

        ``lock``: pin THIS carve (mlock).  None adopts the arena's
        ``STROM_MLOCK`` policy; a consumer that opted out of pinning
        (``EngineConfig.lock_buffers=False``,
        ``HostCacheConfig.lock_arena=False``) passes False so its
        RLIMIT_MEMLOCK budget is honored exactly as pre-arena."""
        if nbytes <= 0:
            raise ValueError(f"carve nbytes must be > 0, got {nbytes}")
        need = (nbytes + CARVE_ALIGN - 1) // CARVE_ALIGN * CARVE_ALIGN
        with self._lock:
            if self._closed:
                return None
            for i, (off, ln) in enumerate(self._free_list):
                if ln >= need:
                    if ln == need:
                        self._free_list.pop(i)
                    else:
                        self._free_list[i] = (off + need, ln - need)
                    self._carved[off] = (need, tag)
                    break
            else:
                return None
        # pin per carve, outside the lock (mlock faults the pages in —
        # that is the point: a fill/DMA must never page-fault later);
        # best effort, RLIMIT_MEMLOCK refusal leaves it unpinned
        slab = Slab(self, off, need, tag)
        want_lock = self.lock_pages if lock is None else lock
        if want_lock and self._lib is not None:
            if self._lib.strom_arena_lock(self.base + off, need) == 0:
                slab.locked = True
                with self._lock:
                    self.locked_bytes += need
        self._emit_occupancy()
        return slab

    def _emit_occupancy(self) -> None:
        """Perfetto counter track: per-tag carved bytes + free bytes on
        the trace timeline (docs/OBSERVABILITY.md) — emitted at every
        carve/release, only while a trace is live.  Never called with
        the arena lock held (``carves``/``bytes_free`` take it)."""
        from nvme_strom_tpu.utils.trace import global_tracer
        if not global_tracer.exports:
            return   # sink-only attribution tracer: skip the walk too
        vals = {f"carved_{t}": n for t, n in self.carves().items()}
        vals["free"] = self.bytes_free
        global_tracer.add_counter("strom.arena.occupancy", vals)

    def _free(self, offset: int, nbytes: int, locked: bool = False) -> None:
        with self._lock:
            got = self._carved.pop(offset, None)
            if got is None or got[0] != nbytes:
                return   # double free / foreign range: refuse silently
            if locked:
                # the gauge tracks bytes pinned by LIVE carves (munlock
                # is deliberately skipped — the pages recycle pinned,
                # which only helps the next carve — but re-locking them
                # re-adds, so without this decrement the gauge would
                # drift past the arena size under carve churn)
                self.locked_bytes = max(0, self.locked_bytes - nbytes)
            # insert sorted + coalesce with neighbours
            fl = self._free_list
            lo, hi = 0, len(fl)
            while lo < hi:
                mid = (lo + hi) // 2
                if fl[mid][0] < offset:
                    lo = mid + 1
                else:
                    hi = mid
            fl.insert(lo, (offset, nbytes))
            if lo + 1 < len(fl) and fl[lo][0] + fl[lo][1] == fl[lo + 1][0]:
                fl[lo] = (fl[lo][0], fl[lo][1] + fl[lo + 1][1])
                fl.pop(lo + 1)
            if lo > 0 and fl[lo - 1][0] + fl[lo - 1][1] == fl[lo][0]:
                fl[lo - 1] = (fl[lo - 1][0], fl[lo - 1][1] + fl[lo][1])
                fl.pop(lo)
        self._emit_occupancy()

    # -- introspection -----------------------------------------------------

    def carves(self) -> Dict[str, int]:
        """Bytes carved per tag (exact; disjoint by construction)."""
        with self._lock:
            out: Dict[str, int] = {}
            for _off, (n, tag) in self._carved.items():
                out[tag] = out.get(tag, 0) + n
            return out

    @property
    def bytes_carved(self) -> int:
        with self._lock:
            return sum(n for n, _t in self._carved.values())

    @property
    def bytes_free(self) -> int:
        with self._lock:
            return sum(ln for _off, ln in self._free_list)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.view = None
        if self._base is not None:
            self._lib.strom_arena_destroy(self._base, self.nbytes)
            self._base = None


# ---------------------------------------------------------------------------
# module singleton — one reservation per process
# ---------------------------------------------------------------------------

_singleton_lock = make_lock("arena._singleton_lock")
_arena: Optional[PinnedArena] = None
_arena_init = False


def _build_locked() -> None:
    global _arena, _arena_init
    if _arena is not None:
        _arena.close()
        _arena = None
    new = None
    if os.environ.get("STROM_ARENA", "1") != "0":
        try:
            mb = int(os.environ.get("STROM_ARENA_MB", 1024))
        except ValueError:
            mb = 1024
        if mb > 0:
            lock = os.environ.get("STROM_MLOCK", "1") != "0"
            try:
                new = PinnedArena(mb << 20, lock_pages=lock)
            except Exception:
                new = None   # no arena is always safe: private mmaps
    _arena = new
    _arena_init = True


def get_arena() -> Optional[PinnedArena]:
    """The process-wide arena, built lazily from the environment; None
    when ``STROM_ARENA=0`` (every consumer then takes its exact
    pre-arena path).  Double-checked under the lock."""
    if _arena_init:
        return _arena
    with _singleton_lock:
        if not _arena_init:
            _build_locked()
        return _arena


def reset() -> None:
    """Tear the singleton down; the next :func:`get_arena` re-reads the
    environment (tests toggle the arena this way).  Callers must have
    released their slabs — a live slab view into a closed arena is the
    same contract breach as using a staging view after close_all."""
    global _arena, _arena_init
    with _singleton_lock:
        if _arena is not None:
            _arena.close()
        _arena = None
        _arena_init = False


def carve_or_none(nbytes: int, tag: str, stats=None,
                  lock: Optional[bool] = None) -> Optional[Slab]:
    """One-line consumer helper: carve from the process arena, or None
    (arena off / exhausted — counted ``arena_fallbacks`` when a stats
    block rides along, so budget starvation is visible).  ``lock``
    threads the consumer's own pinning choice through to the carve."""
    a = get_arena()
    if a is None:
        return None
    slab = a.carve(nbytes, tag, lock=lock)
    if slab is None and stats is not None:
        stats.add(arena_fallbacks=1)
    return slab
