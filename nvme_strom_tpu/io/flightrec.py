"""Flight recorder — a bounded ring buffer of recent I/O op records
that dumps itself to disk when a failure trigger fires
(docs/OBSERVABILITY.md).

Aggregate counters answer "how much"; the post-mortem question after a
breaker trip, a hot ring restart, an SLO violation, or a watchdog stall
is "what exactly was in flight just before".  The recorder keeps the
answer ALWAYS available at near-zero cost: every completed engine read/
write (plus degraded-mode preads) appends one compact record — class,
ring, bytes, latency, outcome — to a ``deque(maxlen=N)`` (a single
GIL-atomic append, no lock on the hot path), and PR 10's health layer
plus the serving SLO governor and the step watchdog call :meth:`dump`
on their triggers.  The dump is an atomic JSON file carrying the recent
ops, a latency :class:`Log2Histogram` summary, and the full StromStats
snapshot at the moment of the event.

Knobs (``FlightConfig`` in utils/config.py): ``STROM_FLIGHT`` (master,
default on), ``STROM_FLIGHT_OPS`` (ring capacity), ``STROM_FLIGHT_DIR``
(dump directory; default the system temp dir), ``STROM_FLIGHT_MIN_S``
(dump rate limit).  Every dump counts ``StromStats.flight_dumps`` —
rendered by strom_stat's observability block and watchdog dumps.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import Optional

from nvme_strom_tpu.utils.config import FlightConfig
from nvme_strom_tpu.utils.lockwitness import make_lock
from nvme_strom_tpu.utils.stats import Log2Histogram, _atomic_write_text

#: op-record field order (records are plain tuples — ~4x smaller and
#: ~3x faster to append than dicts; the dump re-labels them)
FIELDS = ("t_s", "kind", "klass", "ring", "fh", "offset", "bytes",
          "latency_us", "outcome", "err")


class FlightRecorder:
    """The always-on ring buffer + trigger-dump sink of one engine."""

    def __init__(self, config: Optional[FlightConfig] = None,
                 stats=None):
        self.cfg = config or FlightConfig()
        self.stats = stats
        self._ops: collections.deque = collections.deque(
            maxlen=self.cfg.ops)
        self._lat = Log2Histogram("strom_flight_latency_us",
                                  "recorded op latency (µs)")
        self._dump_lock = make_lock("flightrec.FlightRecorder._dump_lock")
        #: PER-REASON rate-limit watermarks: a ``breaker_trip`` dump
        #: must not shadow the ``slo_violation`` dump that follows it
        #: inside STROM_FLIGHT_MIN_S — they are different incidents'
        #: first post-mortems (the old single watermark did exactly
        #: that shadowing)
        self._last_dump: dict = {}
        self.dumps = 0
        #: optional AttributionCollector (obs/attrib.py): when set,
        #: every dump embeds the recent-request attribution summary —
        #: the post-mortem opens with WHERE the time went, not just
        #: which ops were in flight
        self.attrib = None
        #: dump paths written, newest last (bounded; tests and the
        #: watchdog report read these)
        self.dump_paths: list = []

    # -- hot path ----------------------------------------------------------

    def record(self, kind: str, klass: Optional[str], ring: int,
               fh: int, offset: int, nbytes: int, latency_us: int,
               outcome: str, err: Optional[int] = None) -> None:
        """Append one completed-op record.  One deque append (GIL-atomic
        — no lock) plus one histogram bucket increment; the callers
        guard with ``if flight is not None`` so STROM_FLIGHT=0 keeps the
        hot path untouched."""
        self._ops.append((time.time(), kind, klass, ring, fh, offset,
                          nbytes, latency_us, outcome, err))
        if latency_us > 0:
            # error records carry no real completion latency (0): an
            # EIO storm must not drag the dump's p50/p99 to ~1 µs —
            # those headline numbers exist for exactly that post-mortem
            self._lat.observe(latency_us)

    def __len__(self) -> int:
        return len(self._ops)

    def snapshot_ops(self) -> list:
        """The recent ops as dicts, oldest first (tools, tests)."""
        return [dict(zip(FIELDS, op)) for op in list(self._ops)]

    # -- trigger dump ------------------------------------------------------

    def _dump_dir(self) -> str:
        return self.cfg.dir or tempfile.gettempdir()

    def dump(self, reason: str, extra: Optional[dict] = None,
             force: bool = False) -> Optional[str]:
        """Write the post-mortem file for ``reason``; returns its path,
        or None when rate-limited (``force`` bypasses — the watchdog's
        abort path must never lose its last dump).  Never raises: a
        full disk must not turn a brown-out into a crash."""
        with self._dump_lock:   # dumps are rare: serialize whole-hog
            now = time.monotonic()
            if not force and now - self._last_dump.get(reason, -1e9) \
                    < self.cfg.min_interval_s:
                return None
            ops = self.snapshot_ops()
            doc = {
                "reason": reason,
                "ts": time.time(),
                "pid": os.getpid(),
                "n_ops": len(ops),
                "latency_us_p50": self._lat.percentile(50),
                "latency_us_p99": self._lat.percentile(99),
                "ops": ops,
            }
            if extra:
                doc["extra"] = dict(extra)
            if self.stats is not None:
                try:
                    doc["stats"] = self.stats.snapshot()
                except Exception:
                    pass
            if self.attrib is not None:
                # where recent requests' time went, at the moment the
                # trigger fired (obs/attrib.py summary)
                try:
                    doc["attrib"] = self.attrib.summary()
                except Exception:
                    pass
            safe = "".join(c if c.isalnum() or c in "-_" else "_"
                           for c in reason)[:48]
            path = os.path.join(self._dump_dir(),
                                f"strom_flight_{os.getpid()}_{safe}_"
                                f"{self.dumps + 1}.json")
            try:
                _atomic_write_text(path, json.dumps(doc))
            except OSError:
                # nothing was published: do NOT burn the rate-limit
                # window — the next trigger (a ring restart typically
                # follows a trip within seconds) must still get to
                # write the incident's FIRST usable post-mortem
                return None
            self._last_dump[reason] = now
            self.dumps += 1
        if self.stats is not None:
            self.stats.add(flight_dumps=1)
        self.dump_paths.append(path)
        del self.dump_paths[:-16]
        return path


def flight_of(engine) -> Optional[FlightRecorder]:
    """The recorder behind any engine-shaped object (wrapper chains
    delegate attribute access); None when disabled or absent."""
    return getattr(engine, "flight", None)
