"""Critical-path attribution — where every microsecond of a request
went (docs/OBSERVABILITY.md §4).

PR 11's causal tracer answers "what happened" one Perfetto load at a
time; production triage needs the folded form: *this* decode request
spent 1.2 ms queued at the scheduler, 3.4 ms on NVMe, 0.8 ms in retry
backoff, and the remainder in compute.  This module is that fold:

  collect    an :class:`AttributionCollector` attaches to a
             :class:`~nvme_strom_tpu.utils.trace.Tracer` as a span
             SINK (``Tracer.add_sink``) and buffers each trace's spans
             — bounded per trace and across traces, with drops counted
             (``attrib_spans_dropped``).  Sink delivery works with NO
             export path, so ``STROM_ATTRIB=1`` prices only the span
             emit + a dict append, never a trace file.
  fold       at request retire (models/serving.py calls
             :meth:`AttributionCollector.request_retired`) the trace's
             spans fold into the FIXED component breakdown below.
             Per-component intervals are clipped to the request window
             and interval-UNIONED, so N parallel reads charge their
             covered wall time once; ``unattributed`` is the wall time
             no component covers (compute, host work, scheduling gaps)
             — by construction ``coverage + unattributed == wall``,
             the conservation invariant tests pin within 1%.
  aggregate  folds land in rolling per-QoS-class profiles: one
             :class:`~nvme_strom_tpu.utils.stats.Log2Histogram` (µs)
             per (class, component) yields p50/p99 per component, the
             view ``/attrib`` serves and ``strom-top`` renders.

Components (span-name mapping in ``NAME_TO_COMPONENT``):

  ``sched_queue``    QoS-scheduler queue wait (``strom.sched.queue``)
  ``hostcache``      pinned-host tier hits + fills (``strom.cache.*``)
  ``nvme_read``      engine device time (``strom.read[.fallback]``,
                     ``strom.write``)
  ``retry_backoff``  resilient retry + backoff (``strom.resilient.retry``)
  ``hedge``          hedge submissions/races (``strom.resilient.hedge*``)
  ``degraded``       buffered brown-out service (``strom.read.degraded``,
                     ``strom.health.*``)
  ``bridge``         host→HBM hop (``strom.bridge.hop``, ``strom.h2d.*``)
  ``ici_scatter``    read-once restore shard exchange over the
                     interconnect (``strom.ici.*`` — ops/ici.py)
  ``unattributed``   wall time outside every component (compute)

Activation: ``STROM_ATTRIB=1`` (default off) builds the process-wide
collector; every engine attaches it to its tracer, serving folds at
retire.  ``STROM_ATTRIB=0``/unset is the exact pre-attribution stack.
"""

from __future__ import annotations

import os
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from nvme_strom_tpu.utils.lockwitness import make_lock

#: the fixed breakdown, in render order (``unattributed`` is derived,
#: always last)
COMPONENTS = ("sched_queue", "hostcache", "nvme_read", "retry_backoff",
              "hedge", "degraded", "bridge", "ici_scatter")

#: span name → component.  Prefix matching (see :func:`component_of`)
#: keeps future ``strom.resilient.*`` names in the right bucket.
NAME_TO_COMPONENT = {
    "strom.sched.queue": "sched_queue",
    "strom.cache.hit": "hostcache",
    "strom.cache.fill": "hostcache",
    "strom.read": "nvme_read",
    "strom.read.fallback": "nvme_read",
    "strom.write": "nvme_read",
    "strom.read.degraded": "degraded",
    "strom.health.probe": "degraded",
    "strom.health.ring_restart": "degraded",
    "strom.resilient.retry": "retry_backoff",
    "strom.resilient.write_retry": "retry_backoff",
    "strom.resilient.hedge": "hedge",
    "strom.resilient.hedge_won": "hedge",
    "strom.bridge.hop": "bridge",
    "strom.h2d.dispatch": "bridge",
    "strom.h2d.sync": "bridge",
    "strom.ici.exchange": "ici_scatter",
    "strom.ici.scatter": "ici_scatter",
}

#: serving/root spans: structure, not a cost component — excluded from
#: the fold so the admission span (which CONTAINS prefill + engine I/O)
#: cannot shadow the whole window as one component
_STRUCTURAL = ("strom.serve.",)


def component_of(name: str) -> Optional[str]:
    """The attribution component of a span name (None = structural or
    unknown — contributes to ``unattributed`` only)."""
    c = NAME_TO_COMPONENT.get(name)
    if c is not None:
        return c
    for prefix in _STRUCTURAL:
        if name.startswith(prefix):
            return None
    if name.startswith("strom.resilient."):
        return "retry_backoff"
    if name.startswith("strom.ici."):
        return "ici_scatter"
    return None


def _merge_intervals(ivals: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Sorted interval union (the double-count guard: two parallel
    reads of one request charge their covered wall time once)."""
    if not ivals:
        return []
    ivals.sort()
    out = [list(ivals[0])]
    for b, e in ivals[1:]:
        if b <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([b, e])
    return [(b, e) for b, e in out]


def _union_ns(ivals: List[Tuple[int, int]]) -> int:
    return sum(e - b for b, e in _merge_intervals(list(ivals)))


def fold_events(spans, t0_ns: int, t1_ns: int) -> dict:
    """Fold one request's spans — ``(name, begin_ns, end_ns)`` tuples —
    over the request window ``[t0_ns, t1_ns)`` into the component
    breakdown (all values µs).

    Per-component times are interval unions clipped to the window;
    ``coverage_us`` is the union ACROSS components, ``unattributed_us``
    the uncovered remainder — so ``coverage + unattributed == wall``
    exactly, and with no cross-component overlap (sequential
    deterministic runs) the per-component sum equals the coverage.
    ``overlap_us`` reports cross-component parallelism (per-component
    sum minus coverage) so the conservation check can tell parallel
    I/O from accounting error."""
    wall = max(0, t1_ns - t0_ns)
    per: Dict[str, List[Tuple[int, int]]] = {}
    everything: List[Tuple[int, int]] = []
    for name, b, e in spans:
        comp = component_of(name)
        if comp is None:
            continue
        b, e = max(b, t0_ns), min(e, t1_ns)
        if e <= b:
            continue
        per.setdefault(comp, []).append((b, e))
        everything.append((b, e))
    comps = {c: _union_ns(iv) / 1000.0 for c, iv in per.items()}
    coverage = _union_ns(everything) / 1000.0
    comp_sum = sum(comps.values())
    return {
        "wall_us": wall / 1000.0,
        "components": {c: round(comps.get(c, 0.0), 3)
                       for c in COMPONENTS},
        "coverage_us": round(coverage, 3),
        "unattributed_us": round(wall / 1000.0 - coverage, 3),
        "overlap_us": round(max(0.0, comp_sum - coverage), 3),
        "spans": len(spans),
    }


class AttributionCollector:
    """Bounded span buffer + per-class rolling attribution profiles.

    ``sink`` is the :meth:`Tracer.add_sink` callable: one dict per
    completed span, buffered under the span's trace id.  Traces are
    LRU-bounded (``max_traces``) — a request that never retires (a
    crash, an abandoned trace) ages out instead of leaking — and each
    trace keeps at most ``max_spans`` spans (drops counted).
    """

    #: retired folds kept for the flight recorder's dump summary and
    #: the ``/attrib`` recent view
    _RECENT = 64

    def __init__(self, max_traces: int = 256, max_spans: int = 1024,
                 stats=None):
        self._lock = make_lock("attrib.AttributionCollector._lock")
        self.max_traces = max_traces
        self.max_spans = max_spans
        #: trace id (hex string, as stamped in span args) → span tuples
        self._traces: "OrderedDict[str, list]" = OrderedDict()
        self.stats = stats
        self.dropped = 0
        self.requests = 0
        #: (klass, component) → Log2Histogram in µs — the Log2Histogram
        #: reuse the per-component p50/p99 rides on
        self._hists: Dict[Tuple[str, str], object] = {}
        #: (klass, component) → cumulative µs (exact totals next to the
        #: bucketed percentiles)
        self._totals: Dict[Tuple[str, str], float] = {}
        self._class_n: Dict[str, int] = {}
        self._recent: deque = deque(maxlen=self._RECENT)

    # -- collection (the Tracer sink) --------------------------------------

    def sink(self, ev: dict) -> None:
        """One completed span event (hot-ish path: one dict lookup, one
        list append under the lock; spans without a trace id — the
        flat, request-less majority of a bulk run — return in two
        lookups)."""
        if ev.get("ph") == "C":
            return
        args = ev.get("args")
        if not args:
            return
        tid = args.get("trace")
        if tid is None:
            return
        b_ns = int(ev["ts"] * 1000.0)
        e_ns = b_ns + int(ev.get("dur", 0.0) * 1000.0)
        dropped = 0
        with self._lock:
            spans = self._traces.get(tid)
            if spans is None:
                while len(self._traces) >= self.max_traces:
                    self._traces.popitem(last=False)
                spans = self._traces[tid] = []
            else:
                # true LRU: an actively-emitting long-lived request
                # must outlive abandoned traces created after it, or
                # its retire fold reads as all-unattributed
                self._traces.move_to_end(tid)
            if len(spans) >= self.max_spans:
                self.dropped += 1
                dropped = 1
            else:
                spans.append((ev["name"], b_ns, e_ns))
        if dropped and self.stats is not None:
            self.stats.add(attrib_spans_dropped=dropped)

    # -- the retire-time fold ----------------------------------------------

    def request_retired(self, trace_id, t0_ns: int, t1_ns: int,
                        klass: str = "decode",
                        extra: Optional[dict] = None) -> dict:
        """Fold the retired request's span tree and roll it into the
        ``klass`` profile.  ``trace_id``: the root TraceContext's id
        (int) or the hex string its spans were stamped with.  Returns
        the fold (tests and the caller's own logging use it)."""
        tid = trace_id if isinstance(trace_id, str) else f"{trace_id:x}"
        with self._lock:
            spans = self._traces.pop(tid, [])
        fold = fold_events(spans, t0_ns, t1_ns)
        fold["klass"] = klass
        if extra:
            fold.update(extra)
        with self._lock:
            self.requests += 1
            self._class_n[klass] = self._class_n.get(klass, 0) + 1
            for comp in list(fold["components"]) + ["unattributed"]:
                us = (fold["unattributed_us"] if comp == "unattributed"
                      else fold["components"][comp])
                key = (klass, comp)
                self._totals[key] = self._totals.get(key, 0.0) + us
                if us > 0:
                    h = self._hists.get(key)
                    if h is None:
                        from nvme_strom_tpu.utils.stats import \
                            Log2Histogram
                        h = self._hists[key] = Log2Histogram(
                            f"strom_attrib_{klass}_{comp}_us",
                            "per-request component time (µs)")
                    h.observe(us)
            key = (klass, "wall")
            self._totals[key] = self._totals.get(key, 0.0) \
                + fold["wall_us"]
            h = self._hists.get(key)
            if h is None:
                from nvme_strom_tpu.utils.stats import Log2Histogram
                h = self._hists[key] = Log2Histogram(
                    f"strom_attrib_{klass}_wall_us",
                    "per-request wall time (µs)")
            h.observe(max(fold["wall_us"], 0))
            self._recent.append(fold)
        if self.stats is not None:
            self.stats.add(attrib_requests=1)
        return fold

    # -- views --------------------------------------------------------------

    def profiles(self) -> dict:
        """The rolling per-class attribution profiles: per component
        p50/p99 (µs), cumulative µs, mean share of wall — what
        ``/attrib`` serves and ``strom-top`` renders."""
        with self._lock:
            classes = sorted(self._class_n)
            out: dict = {"requests": self.requests,
                         "spans_dropped": self.dropped,
                         "classes": {}}
            for kl in classes:
                n = self._class_n[kl]
                wall_total = max(self._totals.get((kl, "wall"), 0.0),
                                 1e-9)
                comps = {}
                for comp in list(COMPONENTS) + ["unattributed"]:
                    key = (kl, comp)
                    total = self._totals.get(key, 0.0)
                    h = self._hists.get(key)
                    comps[comp] = {
                        "p50_us": h.percentile(50) if h is not None else 0,
                        "p99_us": h.percentile(99) if h is not None else 0,
                        "total_us": round(total, 1),
                        "share": round(total / wall_total, 4),
                    }
                wh = self._hists.get((kl, "wall"))
                out["classes"][kl] = {
                    "n": n,
                    "wall_p50_us": wh.percentile(50) if wh else 0,
                    "wall_p99_us": wh.percentile(99) if wh else 0,
                    "wall_total_us": round(wall_total, 1),
                    "components": comps,
                }
            return out

    def summary(self) -> dict:
        """Compact recent-request summary for flight-recorder dumps:
        the last few folds plus per-class mean component shares."""
        with self._lock:
            recent = list(self._recent)[-8:]
        prof = self.profiles()
        shares = {kl: {c: v["share"]
                       for c, v in blk["components"].items()}
                  for kl, blk in prof["classes"].items()}
        return {"requests": prof["requests"], "shares": shares,
                "recent": recent}

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._hists.clear()
            self._totals.clear()
            self._class_n.clear()
            self._recent.clear()
            self.requests = 0
            self.dropped = 0


# ---------------------------------------------------------------------------
# process-wide collector (STROM_ATTRIB)
# ---------------------------------------------------------------------------

_singleton_lock = make_lock("attrib._singleton_lock")
_collector: Optional[AttributionCollector] = None
_collector_init = False


def get_collector() -> Optional[AttributionCollector]:
    """The process-wide collector when ``STROM_ATTRIB=1`` (default off:
    None, zero overhead — the exact pre-attribution stack).  Engines
    attach it to their tracer at construction; serving folds at
    retire."""
    global _collector, _collector_init
    if _collector_init:
        return _collector
    with _singleton_lock:
        if not _collector_init:
            if os.environ.get("STROM_ATTRIB", "0") == "1":
                _collector = AttributionCollector()
            _collector_init = True
        return _collector


def reset() -> None:
    """Drop the singleton; the next :func:`get_collector` re-reads the
    environment (tests toggle attribution this way).  Sinks already
    attached to tracers keep feeding the old collector — tests that
    reset should also detach (``tracer.remove_sink``)."""
    global _collector, _collector_init
    with _singleton_lock:
        _collector = None
        _collector_init = False


def attach(tracer, stats=None) -> Optional[AttributionCollector]:
    """Wire the process collector (if enabled) into ``tracer`` as a
    span sink — idempotent; the engine-construction hook."""
    col = get_collector()
    if col is None or tracer is None:
        return None
    if stats is not None and col.stats is None:
        col.stats = stats
    tracer.add_sink(col.sink)
    return col
