"""Live debug endpoint — the stack's state over plain HTTP
(docs/OBSERVABILITY.md §6).

Production triage should not require attaching a debugger or waiting
for the next metrics scrape: with ``STROM_DEBUG_PORT`` set (OFF by
default — the server binds loopback and exists only when asked) every
engine-bearing process serves:

  ``/metrics``  the existing OpenMetrics render of the live counter
                block (``strom_stat --prom`` equivalent, fresh-synced);
  ``/attrib``   the rolling per-class critical-path attribution
                profiles (obs/attrib.py);
  ``/ledger``   the goodput/waste ledger + per-ring time-in-state
                (obs/ledger.py);
  ``/flight``   the flight recorder's recent-op ring and dump paths;
  ``/health``   ring breaker states, device degradation, health
                counters (io/health.py);
  ``/locks``    the runtime lock-order witness's state and observed
                acquisition edges (utils/lockwitness.py);
  ``/``         a JSON index of the routes.

One stdlib ``http.server`` daemon thread; requests serve JSON (or
OpenMetrics text) snapshots — no state is mutated, and a dead/closed
engine degrades each route to whatever is still observable rather than
erroring.  ``strom-top`` (tools/strom_top.py) polls ``/attrib`` +
``/ledger`` and renders the live per-class view.

``STROM_DEBUG_PORT=0`` binds an OS-assigned port (tests); the chosen
port is on :attr:`DebugServer.port`.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from nvme_strom_tpu.utils.lockwitness import make_lock

ROUTES = ("/metrics", "/attrib", "/ledger", "/flight", "/health",
          "/locks")


class DebugServer:
    """One process's debug endpoint: a loopback HTTP server over live
    references to the stats block / engine / attribution collector."""

    def __init__(self, stats, port: int = 0, host: str = "127.0.0.1"):
        self.stats = stats
        self._lock = make_lock("debugsrv.DebugServer._lock")
        self._engine = None
        self._closed = False
        srv = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):      # quiet: triage tool, not
                pass                        # an access-logged service

            def do_GET(self):
                srv._serve(self)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="strom-debugsrv")
        self._thread.start()

    # -- live references ----------------------------------------------------

    def attach_engine(self, engine) -> None:
        with self._lock:
            self._engine = engine

    def detach_engine(self, engine) -> None:
        """Compare-and-clear (engine teardown): a later engine sharing
        the process may have attached over the closing one."""
        with self._lock:
            if self._engine is engine:
                self._engine = None

    def _eng(self):
        with self._lock:
            return self._engine

    # -- routing ------------------------------------------------------------

    def _serve(self, h) -> None:
        path = h.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/":
                body, ctype = json.dumps(
                    {"routes": list(ROUTES)}), "application/json"
            elif path == "/metrics":
                body, ctype = self._metrics()
            elif path == "/attrib":
                body, ctype = self._attrib()
            elif path == "/ledger":
                body, ctype = self._ledger()
            elif path == "/flight":
                body, ctype = self._flight()
            elif path == "/health":
                body, ctype = self._health()
            elif path == "/locks":
                body, ctype = self._locks()
            else:
                h.send_error(404, "unknown route")
                return
        except Exception as e:         # a route must answer, not 500-loop
            body, ctype = json.dumps({"error": repr(e)}), \
                "application/json"
        data = body.encode()
        h.send_response(200)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(data)))
        h.end_headers()
        h.wfile.write(data)

    def _snapshot(self) -> dict:
        eng = self._eng()
        if eng is not None:
            try:
                eng.sync_stats()    # live C counters, not the last sync
            except Exception:
                pass
        return self.stats.snapshot()

    def _metrics(self):
        from nvme_strom_tpu.utils.stats import openmetrics_from_snapshot
        return openmetrics_from_snapshot(self._snapshot()), \
            "text/plain; version=0.0.4"

    def _attrib(self):
        from nvme_strom_tpu.obs.attrib import get_collector
        col = get_collector()
        if col is None:
            doc = {"enabled": False,
                   "hint": "set STROM_ATTRIB=1 to collect attribution"}
        else:
            doc = {"enabled": True, **col.profiles()}
        return json.dumps(doc), "application/json"

    def _ledger(self):
        from nvme_strom_tpu.obs.ledger import ledger_view
        return json.dumps(ledger_view(self._snapshot())), \
            "application/json"

    def _flight(self):
        eng = self._eng()
        flight = getattr(eng, "flight", None) if eng is not None \
            else None
        if flight is None:
            doc = {"enabled": False}
        else:
            ops = flight.snapshot_ops()
            doc = {"enabled": True, "n_ops": len(ops),
                   "ops": ops[-256:], "dumps": flight.dumps,
                   "dump_paths": list(flight.dump_paths)}
        return json.dumps(doc), "application/json"

    def _health(self):
        snap = self._snapshot()
        eng = self._eng()
        sup = getattr(eng, "supervisor", None) if eng is not None \
            else None
        doc = {
            "ring_health": (sup.ring_states() if sup is not None
                            else snap.get("ring_health", [])),
            "degraded": bool(sup.degraded()) if sup is not None
            else bool(snap.get("engine_degraded", 0)),
            "breaker_trips": int(snap.get("breaker_trips", 0)),
            "ring_restarts": int(snap.get("ring_restarts", 0)),
            "extents_requeued": int(snap.get("extents_requeued", 0)),
            "degraded_reads": int(snap.get("degraded_reads", 0)),
            "degraded_probes": int(snap.get("degraded_probes", 0)),
            # elastic cold-start boot phase (io/coldstart.py): absent/
            # None for ordinary boots, cold/faulting/warming/steady for
            # a serve-while-restoring replica — strom-top renders it
            "boot_phase": snap.get("boot_phase"),
            # drain & handoff phase (io/handoff.py): absent/None until
            # a drain begins, then serving/draining/handing_off/retired
            "drain_phase": snap.get("drain_phase"),
        }
        return json.dumps(doc), "application/json"

    def _locks(self):
        from nvme_strom_tpu.utils import lockwitness
        doc = {
            "armed": lockwitness.armed(),
            "mode": os.environ.get("STROM_LOCK_WITNESS", "0"),
            "edges": lockwitness.witness().snapshot_edges(),
        }
        return json.dumps(doc), "application/json"

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Clean shutdown: stop accepting, close the socket, join the
        serve thread (idempotent)."""
        if self._closed:
            return
        self._closed = True
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# process-wide server (STROM_DEBUG_PORT)
# ---------------------------------------------------------------------------

_singleton_lock = make_lock("debugsrv._singleton_lock")
_server: Optional[DebugServer] = None
_server_failed = False


def maybe_start_debug_server(stats, engine=None) -> Optional[DebugServer]:
    """Start the process-wide debug server the first time an engine
    comes up — ONLY when ``STROM_DEBUG_PORT`` is set (off by default:
    no thread, no socket, zero overhead).  Later engines re-attach as
    the live engine reference."""
    global _server, _server_failed
    port = os.environ.get("STROM_DEBUG_PORT")
    if not port:
        return None
    with _singleton_lock:
        if _server is None and not _server_failed:
            try:
                _server = DebugServer(stats, port=int(port))
            except (OSError, ValueError):
                _server_failed = True   # bad port / bind refusal: once
                return None
            atexit.register(_server.close)
        srv = _server
    if srv is not None and engine is not None:
        srv.attach_engine(engine)
    return srv


def reset() -> None:
    """Close and drop the singleton (tests)."""
    global _server, _server_failed
    with _singleton_lock:
        if _server is not None:
            _server.close()
        _server = None
        _server_failed = False
