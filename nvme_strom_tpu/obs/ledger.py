"""Goodput/waste ledger — where every delivered byte went
(docs/OBSERVABILITY.md §5).

The stack already counts HOW MUCH it moved (``bytes_direct`` etc.);
nobody could say what fraction of that bandwidth was *useful*.  The
ledger classifies every completed byte:

  goodput            delivered to a consumer and not re-read, not a
                     planner gap, not a lost race — DERIVED as
                     ``delivered - waste`` so the classes can never
                     double-count it;
  hedge_loss         the losing side of a hedge race (io/resilient.py);
  retry_reread       bytes recovery re-read that an earlier attempt
                     had already delivered (io/resilient.py);
  coalesce_gap       dead gap bytes the planner deliberately read
                     through when merging extents (io/plan.py);
  evicted_unused     host-tier lines filled from NVMe and evicted
                     before a single hit (io/hostcache.py);
  degraded           bytes served through the buffered brown-out
                     (io/health.py — delivered, but at page-cache
                     bandwidth on a condemned device).

The per-kind counters live on :class:`~nvme_strom_tpu.utils.stats.
StromStats` (``waste_*_bytes``) so they ride every existing exporter;
:func:`ledger_view` is the folded view ``/ledger`` serves, ``strom-top``
renders, and ``strom_stat``'s ledger block prints.

Per-ring TIME-in-state accounting rides along
(:class:`RingTimeLedger`): cumulative seconds each ring spent
busy/idle/stalled/restarting, sampled at completion reaping
(io/engine.py, time-gated) and at every stats sync — the capacity
denominator under the byte classification (a ring that is 40% stalled
explains a goodput dip no byte counter can).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

from nvme_strom_tpu.utils.lockwitness import make_lock

#: waste classes → their StromStats counter
WASTE_COUNTERS = {
    "hedge_loss": "waste_hedge_loss_bytes",
    "retry_reread": "waste_retry_reread_bytes",
    "coalesce_gap": "waste_coalesce_gap_bytes",
    "evicted_unused": "waste_evicted_unused_bytes",
    "degraded": "waste_degraded_bytes",
}

#: ring states, render order
RING_STATES = ("busy", "idle", "stalled", "restarting")


def charge_waste(stats, kind: str, nbytes: int) -> None:
    """Charge ``nbytes`` of waste class ``kind`` (one StromStats add;
    the I/O-layer hooks call this so the taxonomy lives in ONE place)."""
    if stats is None or nbytes <= 0:
        return
    stats.add(**{WASTE_COUNTERS[kind]: int(nbytes)})


def ledger_view(snap: dict) -> dict:
    """Fold a :meth:`StromStats.snapshot` into the goodput/waste view.

    ``delivered`` = engine payload (direct + fallback) + host-tier
    served bytes; degraded preads count into ``bytes_fallback`` via
    the C counter AND into their waste class, so the classification
    stays a partition of delivered traffic."""
    delivered = (int(snap.get("bytes_direct", 0))
                 + int(snap.get("bytes_fallback", 0))
                 + int(snap.get("bytes_served_cache", 0)))
    waste = {kind: int(snap.get(counter, 0))
             for kind, counter in WASTE_COUNTERS.items()}
    waste_total = sum(waste.values())
    goodput = max(0, delivered - waste_total)
    out = {
        "delivered_bytes": delivered,
        "goodput_bytes": goodput,
        "waste_bytes": waste_total,
        "waste": waste,
        "goodput_fraction": round(goodput / delivered, 4)
        if delivered else 1.0,
    }
    rs = snap.get("ring_state_s")
    if rs:
        out["ring_state_s"] = {k: [round(float(v), 3) for v in vals]
                               for k, vals in rs.items()}
    return out


class RingTimeLedger:
    """Cumulative per-ring time-in-state accounting.

    ``sample(depths, breaker_states)`` charges the elapsed time since
    the previous sample to each ring's CURRENT state — busy (in-flight
    I/O), idle, or stalled (breaker open / C stall flag) — so the
    accounting is an interval integral of cheap instantaneous reads,
    not per-op bookkeeping.  ``note_restart`` charges hot-restart wall
    time explicitly (restarts are rare, bounded windows the sampler
    would mostly miss).  Callers time-gate sampling (io/engine.py reaps
    at ~10 Hz); the math is O(rings) dict arithmetic under one lock.
    """

    def __init__(self, n_rings: int):
        self.n_rings = max(1, int(n_rings))
        self._lock = make_lock("ledger.RingTimeLedger._lock")
        self._t: Dict[str, List[float]] = {
            s: [0.0] * self.n_rings for s in RING_STATES}
        self._last = time.monotonic()

    def sample(self, depths: Sequence[int],
               breaker_states: Optional[Sequence[str]] = None,
               now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            dt = now - self._last
            self._last = now
            if dt <= 0:
                return
            for r in range(self.n_rings):
                depth = depths[r] if r < len(depths) else 0
                state = "busy" if depth > 0 else "idle"
                if breaker_states is not None \
                        and r < len(breaker_states) \
                        and breaker_states[r] == "open":
                    state = "stalled"
                self._t[state][r] += dt

    def note_restart(self, ring: int, seconds: float) -> None:
        """Charge one hot-restart window (io/engine.py ``ring_restart``
        measures it around the C call).  Advances the sampler watermark
        past the window so the next :meth:`sample` cannot charge the
        same interval to busy/idle/stalled again — state seconds must
        never sum past wall time."""
        if seconds <= 0 or not 0 <= ring < self.n_rings:
            return
        with self._lock:
            self._t["restarting"][ring] += seconds
            self._last = max(self._last, time.monotonic())

    def snapshot(self) -> Dict[str, List[float]]:
        with self._lock:
            return {s: list(v) for s, v in self._t.items()}

    def export(self, stats) -> None:
        """Publish the accounting as the ``ring_state_s`` gauge (ridden
        by every exporter: --json, --prom ``strom_ring_state_seconds``,
        ``/ledger``)."""
        if stats is not None:
            stats.set_gauges(ring_state_s=self.snapshot())
