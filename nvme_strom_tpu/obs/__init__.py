"""Observability ANALYSIS layer (docs/OBSERVABILITY.md §§4-6).

PR 11 made the stack *emit* telemetry (causal spans, OpenMetrics, the
flight recorder); this package *answers questions* with it:

- ``obs.attrib``   critical-path attribution: at request retire, fold
                   the request's span tree into a fixed component
                   breakdown (sched queue-wait, hostcache, NVMe device
                   time, retry/backoff, hedge, degraded fallback,
                   host→HBM bridge hop, unattributed remainder),
                   conservation-checked against wall time and rolled
                   into per-QoS-class p50/p99 profiles.
- ``obs.ledger``   goodput/waste accounting: every completed byte
                   classified goodput vs waste {hedge-loss,
                   retry-reread, coalesce-gap, evicted-before-reuse,
                   degraded-fallback}, plus per-ring time-in-state
                   (busy/idle/stalled/restarting).
- ``obs.debugsrv`` the live debug endpoint (``STROM_DEBUG_PORT``):
                   ``/metrics /attrib /ledger /flight /health /locks``,
                   polled by the ``strom-top`` console tool.
"""

from nvme_strom_tpu.obs.attrib import (AttributionCollector, fold_events,
                                       get_collector)
from nvme_strom_tpu.obs.ledger import (RingTimeLedger, charge_waste,
                                       ledger_view)

__all__ = [
    "AttributionCollector", "fold_events", "get_collector",
    "RingTimeLedger", "charge_waste", "ledger_view",
]
