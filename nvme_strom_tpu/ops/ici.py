"""Read-once shard exchange over the mesh interconnect (docs/PERF.md §7).

The paper's restore bottleneck is per-host SSD bandwidth: every host in a
mesh re-reads the ENTIRE weight/checkpoint payload from its own NVMe, so
an N-host restore moves N·T bytes off flash to deliver T useful bytes per
host.  ICI is an order of magnitude faster than any SSD, so the right
shape is read-once/scatter: each host NVMe-reads only its 1/N byte share
(through the ordinary ``plan_and_submit`` → staging → bridge path at
``restore`` class, governed by the scheduler, breakers and ledger like
any other consumer) and the mesh all-gathers the shares — restore becomes
mesh-aggregate-bound instead of per-host-SSD-bound.

Two layers live here:

:class:`IciExchange`
    ``shard_map``-compatible all-gather of per-host byte rows.  On an
    all-TPU mesh the exchange is a Pallas ring collective built on
    ``pltpu.make_async_remote_copy`` (one-hop neighbour pushes around the
    ring, DMA'd HBM→HBM on the device's own engines); ANY failure — no
    TPU, kernels unavailable, runtime refuses the remote DMA — degrades
    ONE-WAY to the ``jax.lax.all_gather`` collective, exactly the
    ``ops/bridge.py`` ``OverlapStage`` discipline, which is also the
    CPU/emulated-mesh path the tests pin.

:func:`scatter_engine`
    The consumer-facing orchestrator: partition a file set into per-host
    contiguous byte shares, read the local share(s), exchange, and return
    a :class:`~nvme_strom_tpu.io.scatter.ScatterServeEngine` that serves
    every subsequent read of those files from the gathered bytes.  Any
    failure returns None (counted ``ici_fallbacks``) and the caller keeps
    its plain engine — scatter can only ever brown out to the read-all
    path, never black out a restore.

Knobs: ``STROM_ICI_SCATTER`` (default off — ``=0`` is bit-for-bit the
read-all stack), ``STROM_ICI_HOSTS``, ``STROM_ICI_UNIT_BYTES``.
Counters: ``ici_bytes_read``, ``ici_bytes_received``, ``ici_fallbacks``.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional, Sequence

import numpy as np

from nvme_strom_tpu.parallel.mesh import exchange_mesh

_log = logging.getLogger("nvme_strom_tpu.ici")

#: lane-friendly padding of a host's share row: rows exchange as int32
#: words and TPU tiles want multiples of a full (8, 128) tile
_ROW_ALIGN = 4096

#: default partition unit — share boundaries stay on O_DIRECT-friendly
#: 1 MiB lines so each host's span submits as large aligned reads
DEFAULT_UNIT_BYTES = 1 << 20


def ici_scatter_enabled() -> bool:
    """``STROM_ICI_SCATTER=1`` turns the read-once/scatter restore mode
    on; unset/``0`` (the default) is the exact read-all stack — the
    gate sits at the consumer so OFF touches zero code paths."""
    return os.environ.get("STROM_ICI_SCATTER", "0") not in ("", "0")


def ici_unit_bytes() -> int:
    """Partition unit for per-host byte shares (``STROM_ICI_UNIT_BYTES``,
    default 1 MiB; clamped to >= 4 KiB so shares stay O_DIRECT-aligned)."""
    try:
        v = int(os.environ.get("STROM_ICI_UNIT_BYTES", DEFAULT_UNIT_BYTES))
    except ValueError:
        return DEFAULT_UNIT_BYTES
    return max(4096, v)


def ici_hosts() -> Optional[int]:
    """Pinned exchange width (``STROM_ICI_HOSTS``); None = every host
    (one per process, or every local device when single-process)."""
    v = os.environ.get("STROM_ICI_HOSTS")
    if not v:
        return None
    try:
        return max(1, int(v))
    except ValueError:
        return None


class IciExchange:
    """All-gather of per-host byte rows over the mesh interconnect.

    ``all_gather(rows)`` takes a ``(n_hosts, row_bytes)`` uint8 array
    whose row h is host h's share (single-process emulation holds every
    row; multi-process runs only need their own rows populated) and
    returns the fully-gathered array on this host.

    TPU: Pallas ring all-gather — each device primes its own output slot,
    then ``n-1`` lockstep steps push the freshest slot to the right
    neighbour via ``make_async_remote_copy`` so every chunk DMAs straight
    into its final HBM location.  Non-TPU meshes, or any Pallas failure,
    take the one-way ``jax.lax.all_gather`` degrade (the bridge's
    ``_pallas_ok`` discipline): correct everywhere, and the only path a
    CPU-emulated mesh ever compiles.
    """

    def __init__(self, mesh=None, axis: str = "hosts", stats=None,
                 tracer=None):
        if mesh is None:
            mesh = exchange_mesh(ici_hosts())
        if axis not in mesh.shape:
            raise ValueError(f"mesh has no {axis!r} axis: {mesh.shape}")
        self.mesh = mesh
        self.axis = axis
        self.n = int(mesh.shape[axis])
        self.stats = stats
        self.tracer = tracer
        devs = list(mesh.devices.flat)
        self._pallas_ok = bool(devs) and all(
            d.platform == "tpu" for d in devs)
        self._fns: dict = {}    # (words, pallas) -> jitted gather

    # -- the two exchange backends ------------------------------------

    def _shard_map(self, fn, in_specs, out_specs):
        try:
            from jax import shard_map as sm          # jax >= 0.8
        except ImportError:
            from jax.experimental.shard_map import shard_map as sm
        try:
            return sm(fn, mesh=self.mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        except TypeError:
            return sm(fn, mesh=self.mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)

    def _lax_gather_fn(self):
        import jax
        from jax.sharding import PartitionSpec as P

        axis = self.axis

        def gather(block):          # (1, words) int32 per device
            return jax.lax.all_gather(block, axis, axis=0, tiled=True)

        return jax.jit(self._shard_map(gather, P(axis, None),
                                       P(None, None)))

    def _pallas_gather_fn(self, words: int):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        from jax.sharding import PartitionSpec as P

        n, axis = self.n, self.axis

        def kernel(local_ref, out_ref, send_sem, recv_sem):
            my_id = lax.axis_index(axis)
            right = lax.rem(my_id + 1, n)
            left = lax.rem(my_id + n - 1, n)
            # both neighbours must have primed their output slots
            # before any remote DMA lands in them
            barrier = pltpu.get_barrier_semaphore()
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=(left,),
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=(right,),
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            out_ref[pl.ds(my_id, 1)] = local_ref[:]
            pltpu.semaphore_wait(barrier, 2)
            # lockstep ring: at step k every device pushes the chunk
            # that originated k hops to its left straight into the
            # right neighbour's matching output slot — no staging
            # buffer, each chunk DMAs once into its final location
            for step in range(n - 1):
                src = lax.rem(my_id + n - step, n) if step else my_id
                rdma = pltpu.make_async_remote_copy(
                    src_ref=out_ref.at[pl.ds(src, 1)],
                    dst_ref=out_ref.at[pl.ds(src, 1)],
                    send_sem=send_sem.at[step % 2],
                    recv_sem=recv_sem.at[step % 2],
                    device_id=(right,),
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                rdma.start()
                rdma.wait()

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((2,))],
        )

        params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
            pltpu, "TPUCompilerParams")

        def ring(block):            # (1, words) int32 per device
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((n, words), jnp.int32),
                grid_spec=grid_spec,
                compiler_params=params_cls(
                    has_side_effects=True, collective_id=0),
            )(block)

        return jax.jit(self._shard_map(ring, P(axis, None),
                                       P(None, None)))

    def _gather_fn(self, words: int):
        key = (words, self._pallas_ok)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        if self._pallas_ok:
            try:
                fn = self._pallas_gather_fn(words)
            except Exception as e:              # build/trace failure:
                _log.warning("ici: pallas ring unavailable (%s: %s); "
                             "degrading to lax all_gather",
                             type(e).__name__, e)
                self._pallas_ok = False         # degrade ONCE, stay there
        if fn is None:
            fn = self._lax_gather_fn()
        self._fns[(words, self._pallas_ok)] = fn
        return fn

    # -- the host-facing exchange -------------------------------------

    def all_gather(self, rows: np.ndarray) -> np.ndarray:
        """``rows`` (n_hosts, row_bytes) uint8 → the gathered array on
        this host.  Row length pads to an int32-word multiple
        internally; callers see exact bytes back."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if rows.ndim != 2 or rows.shape[0] != self.n:
            raise ValueError(
                f"rows {rows.shape} != ({self.n}, row_bytes)")
        nbytes = rows.shape[1]
        pad = (-nbytes) % _ROW_ALIGN
        if pad:
            rows = np.pad(rows, ((0, 0), (0, pad)))
        words = rows.shape[1] // 4
        t0 = time.monotonic_ns()
        sharding = NamedSharding(self.mesh, P(self.axis, None))
        wrows = np.ascontiguousarray(rows).view(np.int32)
        if jax.process_count() > 1:
            # wrows is the FULL (n, words) array with only this
            # process's row(s) populated, so global_shape must say so
            # explicitly: with it, each process's addressable row is
            # sliced from its local copy (row p belongs to process p —
            # exchange_mesh pins axis index == process index).  Without
            # it JAX treats the n local rows as this process's SHARD,
            # infers an (n·n_proc, words) global array, and the gather
            # silently returns zeros for every peer row instead of
            # raising.
            arr = jax.make_array_from_process_local_data(
                sharding, wrows, global_shape=wrows.shape)
        else:
            arr = jax.device_put(wrows, sharding)
        fn = self._gather_fn(words)
        try:
            out = fn(arr)
            out.block_until_ready()
        except Exception as e:
            if not self._pallas_ok:
                raise
            # runtime refusal AFTER a successful trace: same one-way
            # degrade, retried once on the collective path
            _log.warning("ici: pallas ring failed at run time (%s: %s); "
                         "degrading to lax all_gather",
                         type(e).__name__, e)
            self._pallas_ok = False
            out = self._gather_fn(words)(arr)
            out.block_until_ready()
        got = np.asarray(jax.device_get(out)).view(np.uint8)
        if got.shape != rows.shape:
            # multi-process-semantics guard: a shape drift here means
            # the gather's global view disagrees with the exchange
            # contract — fail loudly so scatter_engine browns out to
            # the read-all path instead of serving corrupt bytes
            raise RuntimeError(
                f"ici: gather returned {got.shape}, expected "
                f"{rows.shape}")
        got = got[:, :nbytes]
        if self.tracer is not None and getattr(self.tracer, "enabled",
                                               False):
            self.tracer.add_span(
                "strom.ici.exchange", t0, time.monotonic_ns(),
                category="strom.ici", hosts=self.n,
                bytes=int(self.n * nbytes),
                backend="pallas" if self._pallas_ok else "lax")
        return got


def _read_share(engine, paths: Sequence[str], fhs: Sequence[int],
                units, row_bytes: int, klass: str) -> np.ndarray:
    """One host's share row: its assigned ``(file_idx, offset, length)``
    units read through the ordinary planner path (coalesced, split at
    the ledger-tuned chunk, ``restore``-class — scheduler, breakers and
    hostcache all apply) and packed in unit order."""
    from nvme_strom_tpu.io.engine import wait_exact
    from nvme_strom_tpu.io.plan import plan_and_submit

    row = np.zeros(row_bytes, dtype=np.uint8)
    extents = [(fhs[fi], off, ln) for fi, off, ln in units]
    pos = 0
    per_extent = plan_and_submit(engine, extents, klass=klass)
    flat = [p for pieces in per_extent for p in pieces]
    try:
        for pieces in per_extent:
            for p in pieces:
                v = wait_exact(p)           # short read must fail HERE
                row[pos:pos + v.nbytes] = v
                pos += v.nbytes
                flat.remove(p)
                p.release()
    finally:
        for p in flat:
            p.release()
    return row


def scatter_engine(engine, paths: Sequence[str], mesh=None,
                   klass: str = "restore",
                   unit_bytes: Optional[int] = None, manifest=None):
    """Read-once/scatter front-end over ``engine`` for ``paths``.

    Partitions the files into per-host contiguous byte shares, reads the
    local share(s) through ``plan_and_submit`` at ``klass``, exchanges
    the shares over :class:`IciExchange`, and returns a
    :class:`~nvme_strom_tpu.io.scatter.ScatterServeEngine` serving every
    later read of those files from the gathered bytes — so the consumer
    above (checkpoint restore, weight streaming) runs unchanged and
    bit-identical while each byte leaves flash exactly once per mesh.

    Single-process meshes emulate every virtual host (reading each
    host's share once, attributed per host in the store); multi-process
    runs read only this process's rows.  Returns None — and counts
    ``ici_fallbacks`` — on ANY failure or on a degraded (breaker-open)
    engine, leaving the caller on the plain read-all path with zero
    consumer-visible errors."""
    from nvme_strom_tpu.io.scatter import (
        ScatterServeEngine, ScatterStore, partition_files)

    stats = getattr(engine, "stats", None)
    tracer = getattr(engine, "tracer", None)

    def fall_back(why: str) -> None:
        _log.warning("ici scatter disabled for this restore: %s "
                     "(falling back to local full reads)", why)
        if stats is not None:
            stats.add(ici_fallbacks=1)

    sup = getattr(engine, "supervisor", None)
    if sup is not None:
        try:
            sup.tick()
            if sup.degraded():
                # a browned-out device must serve the work it already
                # owes, not take on the whole mesh's share traffic
                fall_back("engine degraded (breaker open)")
                return None
        except Exception:
            pass

    t0 = time.monotonic_ns()
    try:
        exchange = IciExchange(mesh, stats=stats, tracer=tracer)
        if exchange.n < 2:
            fall_back(f"exchange mesh has {exchange.n} host(s)")
            return None
        if manifest is None:
            sizes = [os.path.getsize(p) for p in paths]
            manifest = partition_files(
                sizes, exchange.n,
                unit_bytes if unit_bytes is not None else ici_unit_bytes())
        elif manifest.n_hosts != exchange.n:
            fall_back(f"manifest built for {manifest.n_hosts} hosts, "
                      f"exchange mesh has {exchange.n}")
            return None
        row_bytes = max(manifest.host_bytes) if manifest.host_bytes else 0
        if row_bytes == 0:
            fall_back("empty file set")
            return None

        import jax
        multi = jax.process_count() > 1
        my_hosts = ([jax.process_index()] if multi
                    else list(range(exchange.n)))
        fhs = [engine.open(p) for p in paths]
        rows = np.zeros((exchange.n, row_bytes), dtype=np.uint8)
        read_by_host = {}
        try:
            for h in my_hosts:
                units = manifest.units_for(h)
                rows[h] = _read_share(engine, paths, fhs, units,
                                      row_bytes, klass)
                read_by_host[h] = manifest.host_bytes[h]
        finally:
            for fh in fhs:
                engine.close(fh)
        gathered = exchange.all_gather(rows)
        for h in my_hosts:
            # cross-row checksum before trusting the store: the rows
            # this process read itself must round-trip bit-identically
            # through the exchange; a mismatch means the gather's
            # process/row mapping drifted, and the same corruption
            # would hit every peer row we CANNOT check locally
            if not np.array_equal(gathered[h], rows[h]):
                raise RuntimeError(
                    f"ici: exchange corrupted host {h}'s own share row")
        store = ScatterStore(paths, manifest, gathered,
                             host_bytes_read=read_by_host)
        local = sum(read_by_host.values())
        if stats is not None:
            # received = payload obtained from peers over ICI instead
            # of local NVMe.  Single-process emulation has no peers —
            # every byte came off this host's own flash — so it reports
            # 0 rather than crediting phantom interconnect savings to
            # the ledger/dashboards
            received = (manifest.total_bytes - local) if multi else 0
            stats.add(ici_bytes_read=int(local),
                      ici_bytes_received=int(received))
        if tracer is not None and getattr(tracer, "enabled", False):
            tracer.add_span(
                "strom.ici.scatter", t0, time.monotonic_ns(),
                category="strom.ici", hosts=exchange.n,
                files=len(paths), bytes_read=int(local),
                total_bytes=int(manifest.total_bytes))
        return ScatterServeEngine(engine, store)
    except Exception as e:
        fall_back(f"{type(e).__name__}: {e}")
        return None
