"""Pallas paged attention: decode against a BLOCK-TABLE KV pool.

The serving-memory move (vLLM's PagedAttention, done TPU-style): instead
of reserving ``max_len`` cache rows per slot, all slots share one pool
of fixed-size blocks and a per-slot table lists which pool blocks hold
its history.  Capacity is sized for the TOTAL live tokens, not
slots × max_len — heterogeneous requests stop paying for the longest
one's reservation.

Kernel shape: the block table and per-slot positions ride scalar
prefetch (``pltpu.PrefetchScalarGridSpec``), so each grid step's K/V
BlockSpec ``index_map`` dereferences ``table[b, j]`` and the DMA fetches
exactly that pool block — the indirection costs nothing extra over the
contiguous-cache kernel (ops/decode_attention.py), and no gathered copy
of the cache ever materializes in HBM.  Everything else is the same
fused position-masked online softmax at kv-head width.

Padding-table entries may point anywhere (block 0 convention): their
columns sit past ``pos`` and are masked; their V rows are zeroed before
use so garbage cannot ride a 0·NaN.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _paged_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, scale, block_k, n_blocks):
    bi = pl.program_id(0)
    ji = pl.program_id(2)
    g = q_ref.shape[2]

    @pl.when(ji == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32) * scale          # (g, d)
    k = k_ref[0, 0].astype(jnp.float32)                  # (bk, d)
    v = v_ref[0, 0].astype(jnp.float32)
    pos = pos_ref[bi]
    # rows past pos carry zero weight, but padded/foreign blocks may
    # hold garbage and 0·NaN = NaN — zero those V rows outright
    rows_ok = (ji * block_k + jax.lax.broadcasted_iota(
        jnp.int32, v.shape, 0)) <= pos
    v = jnp.where(rows_ok, v, 0.0)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    cols = ji * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (g, block_k), 1)
    s = jnp.where(cols <= pos, s, _NEG_INF)

    m = m_ref[:, 0]
    l = l_ref[:, 0]
    m_new = jnp.maximum(m, jnp.max(s, axis=1))
    p = jnp.exp(s - m_new[:, None])
    alpha = jnp.exp(m - m_new)
    m_ref[:, 0] = m_new
    l_ref[:, 0] = l * alpha + jnp.sum(p, axis=1)
    acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ji == n_blocks - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, table, pos, *, scale=None,
                    interpret: bool = None):
    """q (b, n_heads, 1, d) attends to its block-table history.

    k_pool/v_pool (n_blocks, n_kv_heads, block_k, d): the shared pool.
    table (b, max_blocks) int32: slot b's sequence lives in pool blocks
    ``table[b, 0] .. table[b, ·]`` (padding entries arbitrary — they
    are masked).  pos (b,) int32: index of slot b's newest entry in its
    OWN coordinate space (block j covers positions
    [j·block_k, (j+1)·block_k)).

    Returns (b, n_heads, 1, d).  ``interpret`` defaults to True off-TPU.
    """
    if q.ndim != 4 or q.shape[2] != 1:
        raise ValueError(f"expected q (b, h, 1, d), got {q.shape}")
    b, nh, _, d = q.shape
    n_pool, nkv, block_k, _ = k_pool.shape
    if nh % nkv:
        raise ValueError(f"{nh} query heads not divisible by {nkv} "
                         "kv heads")
    if table.shape[0] != b or table.ndim != 2:
        raise ValueError(f"table must be ({b}, max_blocks), "
                         f"got {table.shape}")
    g = nh // nkv
    max_blocks = table.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qg = q.reshape(b, nkv, g, d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nkv, max_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, g, d),
                         lambda bi, hi, ji, tbl, ps: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ji, tbl, ps:
                         (tbl[bi, ji], hi, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ji, tbl, ps:
                         (tbl[bi, ji], hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, g, d),
                               lambda bi, hi, ji, tbl, ps:
                               (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, scale=float(scale),
                          block_k=block_k, n_blocks=max_blocks),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, g, d), q.dtype),
        interpret=interpret,
    )(jnp.asarray(table, jnp.int32), jnp.asarray(pos, jnp.int32),
      qg, k_pool, v_pool)
    return out.reshape(b, nh, 1, d)
