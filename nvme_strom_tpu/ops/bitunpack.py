"""On-device RLE/bit-packed hybrid index decode (round-2 verdict #5).

The Parquet dictionary index stream is a sequence of runs: RLE runs
(``count × one value``) and bit-packed runs (``groups × 8`` values of
``bit_width`` bits, LSB-first).  Round 2 expanded the WHOLE stream on
host (``pq_direct.decode_rle_hybrid``) and counted the expanded int32
array as bounce — 4 bytes/value of host-touched payload.  But only the
run HEADERS are sequential control flow; the run bodies are not:

- an RLE run is two scalars — ``jnp.full(count, value)`` materializes
  it on DEVICE, zero host bytes;
- a bit-packed run is a fixed-width bitstream — exactly the shape the
  VPU unpacks with shifts/masks: ship the RAW bytes (bit_width/8 per
  value instead of 4) and decode there.

So the host walk shrinks to varint header parsing (~2 bytes per run),
and payload-class host traffic drops from ``4·count`` bytes to the raw
index-stream bytes the engine read anyway.

Bit-unpack math, vectorized over a ``(groups, bit_width)`` uint8 array
(one row = 8 values):

    bit b of output value v lives at stream bit ``v·bw + b`` →
    byte ``(v·bw + b) >> 3``, shift ``(v·bw + b) & 7``.

The gather/shift/mask/dot runs under jit with ``bit_width`` static and
the group count padded to the next power of two (bounded compile
cache: one program per (bw, log2 groups) pair, not per page size).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

#: give up on streams with more runs than this — a low-cardinality
#: column alternating RLE/packed every few values would launch hundreds
#: of tiny device ops; host decode is faster there and its bounce is
#: small (the stream is small).  High-cardinality columns — where the
#: expanded-index bounce actually hurts — pack thousands of values per
#: run and stay far under it.
MAX_SEGMENTS = 256

#: bit widths above this leave the device path (1 << bw weights must
#: fit int32; a >16M-entry dictionary has no business being gathered)
MAX_BIT_WIDTH = 24


def split_rle_hybrid(buf, bit_width: int, count: int
                     ) -> Optional[List[Tuple]]:
    """Parse run headers only → segment list, or None when the device
    path shouldn't be used (too many runs / oversized bit width).

    Segments: ``("rle", take, value)`` or ``("packed", start, nbytes,
    groups, take)`` with ``take`` = values this run contributes after
    discarding the final run's spec-legal padding."""
    if bit_width == 0:
        # single-entry dictionary: every index is 0, no stream to parse
        # — the device answer is one free jnp.zeros
        return [("rle", count, 0)] if count else []
    if bit_width > MAX_BIT_WIDTH:
        return None
    byte_w = (bit_width + 7) // 8
    segs: List[Tuple] = []
    pos, filled, n = 0, 0, len(buf)
    while filled < count:
        if len(segs) >= MAX_SEGMENTS:
            return None
        header = shift = 0
        while True:
            if pos >= n:
                raise ValueError("truncated RLE stream header")
            b = buf[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 35:
                raise ValueError("RLE header varint overflow")
        if header & 1:                       # bit-packed run
            groups = header >> 1
            nbytes = groups * bit_width
            if pos + nbytes > n:
                raise ValueError("truncated bit-packed run")
            take = min(groups * 8, count - filled)
            segs.append(("packed", pos, nbytes, groups, take))
            pos += nbytes
            filled += take
        else:                                # RLE run
            run = header >> 1
            if run == 0:
                raise ValueError("zero-length RLE run")
            if pos + byte_w > n:
                raise ValueError("truncated RLE run value")
            v = int.from_bytes(buf[pos:pos + byte_w], "little")
            pos += byte_w
            take = min(run, count - filled)
            segs.append(("rle", take, v))
            filled += take
    return segs


@functools.lru_cache(maxsize=1)
def _unpack_groups():
    """Jitted (groups*bit_width,) uint8 → (groups*8,) int32, LSB-first.
    Lazy so importing this module never touches a jax backend."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("bit_width", "groups"))
    def unpack(u8, bit_width: int, groups: int):
        rows = u8.reshape(groups, bit_width)
        bit_idx = np.arange(8 * bit_width)
        byte_of = jnp.asarray(bit_idx >> 3)
        shift = jnp.asarray((bit_idx & 7).astype(np.uint8))
        bits = (rows[:, byte_of] >> shift) & 1      # (groups, 8*bw)
        weights = jnp.asarray(
            (1 << np.arange(bit_width, dtype=np.int32)))
        return jnp.einsum(
            "gvb,b->gv",
            bits.reshape(groups, 8, bit_width).astype(np.int32),
            weights, preferred_element_type=np.int32).reshape(-1)

    return unpack


def _pow2_pad(groups: int) -> int:
    p = 1
    while p < groups:
        p *= 2
    return p


def rle_hybrid_to_device(buf, bit_width: int, count: int, dev,
                         engine=None) -> Optional["object"]:
    """Index stream → int32 DEVICE array, or None → caller host-decodes.

    Host work: header parse + one padded device_put per packed run
    (byte counting: the put is ``bytes_to_device``; on CPU the bridge's
    protective copy counts bounce as usual — on an accelerator no
    expanded index array ever exists host-side).  RLE runs are
    ``jnp.full`` on device."""
    import jax.numpy as jnp
    from nvme_strom_tpu.ops.bridge import host_to_device

    segs = split_rle_hybrid(buf, bit_width, count)
    if segs is None:
        return None
    if not segs:
        return jnp.zeros((0,), jnp.int32)
    parts = []
    for seg in segs:
        if seg[0] == "rle":
            _, take, v = seg
            parts.append(jnp.full((take,), v, jnp.int32))
        else:
            _, start, nbytes, groups, take = seg
            padded = _pow2_pad(groups)
            u8 = np.zeros(padded * bit_width, np.uint8)
            u8[:nbytes] = np.frombuffer(buf, np.uint8, nbytes, start)
            u8_dev = (host_to_device(engine, u8, dev) if engine is not None
                      else jnp.asarray(u8))
            vals = _unpack_groups()(u8_dev, bit_width, padded)
            parts.append(vals[:take])
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)
