"""On-device RLE/bit-packed hybrid index decode (round-2 verdict #5).

The Parquet dictionary index stream is a sequence of runs: RLE runs
(``count × one value``) and bit-packed runs (``groups × 8`` values of
``bit_width`` bits, LSB-first).  Round 2 expanded the WHOLE stream on
host (``pq_direct.decode_rle_hybrid``) and counted the expanded int32
array as bounce — 4 bytes/value of host-touched payload.  But only the
run HEADERS are sequential control flow; the run bodies are not:

- an RLE run is two scalars — ``jnp.full(count, value)`` materializes
  it on DEVICE, zero host bytes;
- a bit-packed run is a fixed-width bitstream — exactly the shape the
  VPU unpacks with shifts/masks: ship the RAW bytes (bit_width/8 per
  value instead of 4) and decode there.

So the host walk shrinks to varint header parsing (~2 bytes per run),
and payload-class host traffic drops from ``4·count`` bytes to the raw
index-stream bytes the engine read anyway.

Decode shape (round-4): the WHOLE stream — all pages of a column
chunk, every run — decodes in ONE fused device program.  The host
parse emits a (5, runs) int32 table (output offset, absolute bit
offset, RLE value, bit width, kind); on device each output row finds
its run by ``searchsorted`` over the offsets, packed rows bit-extract
through a 4-byte gather window (value v of a run starts at stream bit
``bit_base + v·bw``; shift ≤ 7 plus bw ≤ 24 keeps the window
sufficient), RLE rows select the literal.  Three device ops total —
the round-2 per-run design dispatched one put + one unpack per run,
which at the tunnel's ~20 ms/dispatch cost a 1474 s suite step.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

#: give up on streams with more runs than this.  Runs are pure
#: metadata rows in the batched decoder (20 bytes each), so the cap is
#: generous — it only bounds the metadata put; beyond it the stream is
#: so fragmented that host decode's bounce (the stream itself is tiny
#: per value) is the better trade.
MAX_SEGMENTS = 1 << 18

#: bit widths above this leave the device path: a packed value is read
#: through a 4-byte little-endian gather window, so shift (≤7) plus
#: bit_width must fit in 32 bits — bw 25 at shift 7 would truncate high
#: bits into silently wrong indices.  (A >16M-entry dictionary has no
#: business being gathered anyway.)
MAX_BIT_WIDTH = 24


def split_rle_hybrid(buf, bit_width: int, count: int,
                     max_segments: int = MAX_SEGMENTS
                     ) -> Optional[List[Tuple]]:
    """Parse run headers only → segment list, or None when the device
    path shouldn't be used (too many runs / oversized bit width).

    Segments: ``("rle", take, value)`` or ``("packed", start, nbytes,
    groups, take)`` with ``take`` = values this run contributes after
    discarding the final run's spec-legal padding."""
    if bit_width == 0:
        # single-entry dictionary: every index is 0, no stream to parse
        # — the device answer is one free jnp.zeros
        return [("rle", count, 0)] if count else []
    if bit_width > MAX_BIT_WIDTH:
        return None
    byte_w = (bit_width + 7) // 8
    segs: List[Tuple] = []
    pos, filled, n = 0, 0, len(buf)
    while filled < count:
        if len(segs) >= max_segments:
            return None
        header = shift = 0
        while True:
            if pos >= n:
                raise ValueError("truncated RLE stream header")
            b = buf[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 35:
                raise ValueError("RLE header varint overflow")
        if header & 1:                       # bit-packed run
            groups = header >> 1
            nbytes = groups * bit_width
            if pos + nbytes > n:
                raise ValueError("truncated bit-packed run")
            take = min(groups * 8, count - filled)
            segs.append(("packed", pos, nbytes, groups, take))
            pos += nbytes
            filled += take
        else:                                # RLE run
            run = header >> 1
            if run == 0:
                raise ValueError("zero-length RLE run")
            if pos + byte_w > n:
                raise ValueError("truncated RLE run value")
            v = int.from_bytes(buf[pos:pos + byte_w], "little")
            pos += byte_w
            take = min(run, count - filled)
            segs.append(("rle", take, v))
            filled += take
    return segs


def _pow2_pad(groups: int) -> int:
    p = 1
    while p < groups:
        p *= 2
    return p


@functools.lru_cache(maxsize=1)
def _batch_decode():
    """Jitted whole-stream decode: (u8 buffer, (5, R) run table) →
    int32 indices.  ONE fused program regardless of run count.

    Row → run by ``searchsorted`` over the run table's output-offset
    row (pad entries are int32 max so they are never selected); packed
    values bit-extract with a 4-byte little-endian gather window
    (shift ≤ 7 + bit_width ≤ 24 → 31 bits, so the window always
    covers the value); RLE rows select the run's literal value.
    Retraces per (pow2 buffer, pow2 runs, pow2 rows) triple — bounded,
    and served by the persistent compile cache."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("cpad",))
    def decode(u8, meta, cpad: int):
        out_start, bit_base, val, bw, kind = meta
        i = jnp.arange(cpad, dtype=jnp.int32)
        rid = jnp.searchsorted(out_start, i, side="right") - 1
        rel = i - out_start[rid]
        rbw = bw[rid]
        bb = bit_base[rid] + rel * rbw
        byte0 = jnp.minimum(bb >> 3, u8.shape[0] - 4)
        word = (u8[byte0].astype(jnp.uint32)
                | (u8[byte0 + 1].astype(jnp.uint32) << 8)
                | (u8[byte0 + 2].astype(jnp.uint32) << 16)
                | (u8[byte0 + 3].astype(jnp.uint32) << 24))
        mask = (jnp.uint32(1) << rbw.astype(jnp.uint32)) - jnp.uint32(1)
        pv = ((word >> (bb & 7).astype(jnp.uint32)) & mask)
        return jnp.where(kind[rid] == 1, pv.astype(jnp.int32), val[rid])

    return decode


def rle_hybrid_batch_to_device(parts, dev, engine=None
                               ) -> Optional["object"]:
    """``[(buf, bit_width, count), ...]`` (page order) → ONE int32
    device array of the concatenated decoded indices, or None → caller
    host-decodes.

    Exactly three device ops regardless of run count: one put of the
    concatenated raw streams (pow2(+4 window slack) padded), one put
    of the (5, Rpad) int32 run table, one fused decode program.  The
    round-2 per-run design dispatched one put + one unpack PER RUN —
    a 256 MiB dictionary column ledgered 16,784 device puts per scan
    pass, which at the tunnel's ~20 ms/dispatch priced the whole
    1474 s suite_13 step.  Host work is unchanged in kind: varint
    header parsing only; no expanded index array ever exists host-side.
    """
    import jax.numpy as jnp
    from nvme_strom_tpu.ops.bridge import host_to_device

    rows = []            # (out_start, bit_base, val, bw, kind)
    out_base = 0
    buf_chunks = []
    buf_base = 0
    budget = MAX_SEGMENTS
    for buf, bit_width, count in parts:
        segs = split_rle_hybrid(buf, bit_width, count,
                                max_segments=budget)
        if segs is None:
            return None
        budget -= len(segs)
        need_payload = any(s[0] == "packed" for s in segs)
        for s in segs:
            if s[0] == "rle":
                _, take, v = s
                rows.append((out_base, 0, v, 0, 0))
            else:
                _, start, nbytes, groups, take = s
                rows.append((out_base, (buf_base + start) * 8, 0,
                             bit_width, 1))
            out_base += take
        if need_payload:
            buf_chunks.append(bytes(buf))
            buf_base += len(buf)
    total = out_base
    if total == 0:
        return jnp.zeros((0,), jnp.int32)
    if not buf_chunks and len(rows) == 1:
        # pure single-RLE stream (whole page one run, or bit_width 0):
        # one jnp.full beats two puts + a program
        return jnp.full((total,), rows[0][2], jnp.int32)
    # bit offsets must stay inside int32 (the decode math is int32 on
    # both CPU and TPU): cap the concatenated stream at 128 MiB
    if buf_base * 8 + 64 > np.iinfo(np.int32).max:
        return None
    rpad = _pow2_pad(len(rows))
    meta = np.zeros((5, rpad), np.int32)
    meta[0, len(rows):] = np.iinfo(np.int32).max
    meta[:, :len(rows)] = np.array(rows, np.int32).T
    raw = b"".join(buf_chunks)
    bpad = max(8, _pow2_pad(len(raw) + 4))
    u8 = np.zeros(bpad, np.uint8)
    u8[:len(raw)] = np.frombuffer(raw, np.uint8)
    if engine is not None:
        u8_dev = host_to_device(engine, u8, dev)
        meta_dev = host_to_device(engine, meta, dev)
    else:
        u8_dev = jnp.asarray(u8)
        meta_dev = jnp.asarray(meta)
    out = _batch_decode()(u8_dev, meta_dev, _pow2_pad(total))
    return out[:total]


def rle_hybrid_to_device(buf, bit_width: int, count: int, dev,
                         engine=None) -> Optional["object"]:
    """Single-stream form of :func:`rle_hybrid_batch_to_device`."""
    return rle_hybrid_batch_to_device([(buf, bit_width, count)], dev,
                                      engine=engine)
