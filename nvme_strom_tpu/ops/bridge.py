"""JAX bridge: engine staging buffers → device-resident arrays.

This is the consumer half of the reference's hot path (SURVEY.md §3.1): where
the reference DMAs NVMe blocks into pre-pinned CUDA BAR1 pages and userspace
then launches kernels on them, we hand the engine's locked staging buffer
*by pointer* to JAX — ``np.ctypeslib`` views cost zero copies — and let PJRT
run the host→device PCIe transfer straight out of that buffer.  With
``depth > 1`` the next chunk's NVMe read overlaps the current chunk's PCIe
transfer, so the SSD and the PCIe link stay concurrently busy — the same
pipelining the reference gets from N in-flight DMA requests (SURVEY.md §3.4).

The staging buffer is released back to the pool only after
``block_until_ready`` confirms the device transfer consumed it.
"""

from __future__ import annotations

import os
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from nvme_strom_tpu.io.engine import StromEngine, PendingRead
from nvme_strom_tpu.io.plan import split_spans, submit_spans_tiered
from nvme_strom_tpu.utils.config import EngineConfig


def _default_device():
    import jax
    return jax.local_devices()[0]


def overlap_env_enabled() -> bool:
    """Global kill switch of the double-buffered host→HBM stage:
    ``STROM_BRIDGE_OVERLAP=0`` restores today's wait→device_put path
    bit-for-bit, even for streams constructed with ``overlap=True``
    (an off-switch that explicit call sites could override would not
    be an off-switch)."""
    return os.environ.get("STROM_BRIDGE_OVERLAP", "1") != "0"


#: per-device cache of the jitted Pallas host→HBM DMA callable
_H2D_DMA_CACHE: dict = {}


def _pallas_h2d(dev):
    """Jitted Pallas kernel DMA'ing a pinned-host array into device HBM
    (SNIPPETS.md [2]'s pinned-host→HBM ``pltpu.async_copy`` pattern).
    The copy runs on the device's DMA engines, asynchronously to the
    Python thread — which is what lets the NVMe read of chunk K+1
    overlap the host→HBM hop of chunk K."""
    fn = _H2D_DMA_CACHE.get(dev)
    if fn is not None:
        return fn
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def _dma_kernel(x_ref, y_ref):
        def body(sem):
            pltpu.make_async_copy(x_ref, y_ref, sem).wait()

        pl.run_scoped(body, pltpu.SemaphoreType.DMA)

    @jax.jit
    def _call(x):
        return pl.pallas_call(
            _dma_kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x)

    _H2D_DMA_CACHE[dev] = _call
    return _call


class OverlapStage:
    """Double-buffered host→HBM stage of ``DeviceStream.stream_ranges``
    (docs/PERF.md §6).

    Two ping-pong slabs carved from the unified pinned arena
    (io/arena.py, tag ``bridge``; private buffers when the arena is
    off/full).  Per chunk: the completed — and verified — staging view
    is memcpy'd into the next slab, the STAGING buffer releases
    immediately (the NVMe read of chunk K+1 can start while chunk K is
    still in flight to the device), and the device transfer launches
    asynchronously off the slab.  A slab is never overwritten before
    the transfer it sources reports ready — the rotation invariant
    tests/test_bridge.py pins with a fake transfer.

    ``transfer(host_view, dtype, shape) -> device_array`` is injectable
    (tests, exotic transports); the default is the Pallas
    pinned-host→HBM DMA on a TPU device and the alias-safe
    ``host_to_device`` everywhere else.
    """

    def __init__(self, engine: StromEngine, dev, chunk_bytes: int,
                 transfer: Optional[Callable] = None):
        from nvme_strom_tpu.io import arena as _arena
        self.engine = engine
        self.dev = dev
        self.chunk_bytes = chunk_bytes
        self._slabs: list = []       # numpy views, one per ping-pong slot
        self._carves: list = []      # arena Slab objects (None = private)
        for _ in range(2):
            slab = _arena.carve_or_none(chunk_bytes, "bridge",
                                        stats=engine.stats)
            if slab is not None:
                self._carves.append(slab)
                self._slabs.append(slab.view)
            else:
                self._carves.append(None)
                self._slabs.append(np.empty(chunk_bytes, dtype=np.uint8))
        self._busy: list = [None, None]   # device array sourcing slot k
        self._k = 0
        self._transfer = transfer
        self._pallas_ok = dev.platform == "tpu"

    # -- transfer backends -------------------------------------------------

    def _default_transfer(self, host: np.ndarray, dtype, shape):
        import jax
        arr = host if dtype is None else host.view(dtype)
        if shape is not None:
            arr = arr.reshape(shape)
        if self._pallas_ok:
            try:
                # pinned-host residency first (one host copy at DRAM
                # speed), then the Pallas DMA moves it to HBM on the
                # device's own engines — fully async to this thread
                sharding = jax.sharding.SingleDeviceSharding(
                    self.dev, memory_kind="pinned_host")
                pinned = jax.device_put(arr, sharding)
                out = _pallas_h2d(self.dev)(pinned)
                self.engine.stats.add(bytes_to_device=int(host.nbytes))
                return out
            except Exception:
                # kernels/memory-kinds unavailable on this runtime:
                # degrade once to the plain path, stay correct
                self._pallas_ok = False
        return host_to_device(self.engine, arr, self.dev)

    # -- the ping-pong rotation --------------------------------------------

    def put(self, view: np.ndarray, dtype, shape):
        """Stage one completed chunk view and launch its device
        transfer; returns the device array, or None for a view larger
        than the slabs (an oversized cache-line hit, say) — the CALLER
        must then take the non-overlapped path and hold the source
        until the transfer is ready (transferring straight off the
        view here and letting the caller release it immediately would
        let the buffer recycle under a live DMA).  Blocks only when
        BOTH slabs still source in-flight transfers (depth-2
        backpressure — by then the link, not the host, is the
        bottleneck)."""
        n = view.nbytes
        if n > self.chunk_bytes:
            return None
        k = self._k
        self._k ^= 1
        prev = self._busy[k]
        if prev is not None:
            # slab-reuse gate: the transfer sourced from this slab must
            # be done with the bytes before they are overwritten
            prev.block_until_ready()
            self._busy[k] = None
        import time as _time
        t0 = _time.monotonic_ns()
        slab_view = self._slabs[k][:n]
        slab_view[:] = view.reshape(-1).view(np.uint8)
        arr = (self._transfer or self._default_transfer)(
            slab_view, dtype, shape)
        self._busy[k] = arr
        self.engine.stats.add(overlap_chunks=1, overlap_bytes=int(n))
        tracer = getattr(self.engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            # the host→HBM hop of this chunk (slab copy + async launch)
            # — the `bridge` component of obs/attrib.py's breakdown
            tracer.add_span("strom.bridge.hop", t0, _time.monotonic_ns(),
                            category="strom.bridge", bytes=int(n),
                            slab=k)
        return arr

    def close(self) -> None:
        """Block out the in-flight transfers, then recycle the slabs
        (a carve returned while a DMA still sources it would let the
        next consumer overwrite live transfer bytes)."""
        for i, arr in enumerate(self._busy):
            if arr is not None:
                try:
                    arr.block_until_ready()
                except Exception:
                    pass
                self._busy[i] = None
        self._slabs = []
        for slab in self._carves:
            if slab is not None:
                slab.release()
        self._carves = []


def split_ranges(spans, chunk: int):
    """(offset, length) spans → (flat sub-ranges ≤ ``chunk``, per-span
    sub-range counts).  Delegates to the planner's shared splitting
    rule (``io.plan.split_spans``) — kept under its historical name for
    the format readers that import it from here."""
    return split_spans(spans, chunk)


def host_to_device(engine: StromEngine, host: np.ndarray, dev,
                   alias_safe: bool = False):
    """``device_put`` with the staging-alias rule and byte accounting.

    On a host-backed device, ``jax.device_put`` may ALIAS the numpy buffer;
    staging memory is recycled after release(), so a copy is forced (and
    counted as a bounce). On an accelerator the PCIe transfer itself moves
    the bytes and no host copy exists.  Single source of truth for every
    consumer that puts staging-backed views on device.

    ``alias_safe=True``: the source is a long-lived immutable host
    array (e.g. the KV host-cache tier), never recycled staging memory
    — aliasing is fine, so no protective copy and no bounce count.

    Spans: the dispatch is recorded in the strom tracer AND annotated for
    the JAX profiler, so chrome://tracing / Perfetto views line up
    (both clocks are CLOCK_MONOTONIC).
    """
    import jax
    if dev.platform == "cpu" and not alias_safe:
        host = np.array(host)
        engine.stats.add(bounce_bytes=int(host.nbytes))
    with jax.profiler.TraceAnnotation("strom.h2d"), \
            engine.tracer.span("strom.h2d.dispatch", bytes=int(host.nbytes)):
        arr = jax.device_put(host, dev)
    engine.stats.add(bytes_to_device=int(host.nbytes))
    return arr


class StagingRetirePool:
    """Deferred staging release for read→host-decode→device pipelines.

    ``DeviceStream`` owns the raw-range case; format readers that must
    touch the bytes on host BETWEEN the engine read and the device put
    (Arrow IPC decode, safetensors slicing) can't use it — and the
    conservative alternative they shipped with (block on every batch's
    transfers before releasing its staging buffer) costs one
    stop-and-wait link round trip per batch, the same disease the
    round-3 verdict called on the SQL scan.  This pool is
    ``DeviceStream``'s drain discipline, factored out: push each
    batch's (release, device_arrays); completed heads retire
    opportunistically (``is_ready``), and only when more than ``depth``
    batches' staging is outstanding does it block on the OLDEST — by
    which time ``depth-1`` younger transfers are overlapping it.

    Correctness rule unchanged: a staging buffer is released only
    after every device array transferred out of it reports ready.

    ``depth`` counts outstanding entries; 0 degrades to the old
    block-per-batch behavior — the safe fallback when the engine's
    staging pool is too small to also hold deferred entries (callers
    must budget: reads in flight + deferred entries < pool buffers, or
    a deferred submit can wait on a buffer only this pool can free)."""

    def __init__(self, depth: int = 3):
        self.depth = max(0, depth)
        self._q: list = []          # (release_cb, [device arrays])

    def push(self, release, arrays) -> None:
        """``release``: the staging release callback (None = nothing to
        retire, e.g. a host-owned buffer); ``arrays``: device arrays
        whose transfers consume that staging."""
        if release is None:
            return
        self._q.append((release, list(arrays)))
        self._drain_ready()
        while len(self._q) > self.depth:
            self._block_oldest()

    def drain_ready(self) -> None:
        """Retire every completed head entry without blocking."""
        while self._q and all(a.is_ready() for a in self._q[0][1]):
            rel, _ = self._q.pop(0)
            rel()

    _drain_ready = drain_ready

    def retire_oldest(self) -> bool:
        """Blocking-retire the oldest entry; False when none remain.
        Callers under staging-pool pressure loop on this — it always
        makes progress (the device finishes transfers on its own)."""
        if not self._q:
            return False
        self._block_oldest()
        return True

    def _block_oldest(self) -> None:
        rel, arrs = self._q.pop(0)
        for a in arrs:
            a.block_until_ready()
        rel()

    def flush(self) -> None:
        """Retire everything (end of stream, or error-path cleanup)."""
        while self._q:
            self._block_oldest()


class DeviceStream:
    """Pipelined NVMe→HBM chunk stream over one engine.

    ``depth`` chunks are kept in flight: while chunk *k* rides PCIe to the
    device, chunks *k+1 … k+depth* are being DMA'd from NVMe into staging
    buffers.  Yields device-resident arrays.

    ``drain``: "blocking" waits on the OLDEST transfer once ``depth``
    are in flight (the round-2 behavior); "ready" additionally retires
    any already-completed head transfers opportunistically
    (``jax.Array.is_ready``) after every dispatch, so staging buffers
    recycle the moment the device is done with them instead of waiting
    for the pipeline to fill — on a high-latency link this keeps the
    NVMe side of the pipe fed (round-2 verdict: the 0.69 stream
    efficiency investigation, task #2).
    """

    def __init__(self, engine: StromEngine, device=None, depth: int = 3,
                 drain: str = "blocking", klass: Optional[str] = None,
                 overlap: Optional[bool] = None,
                 overlap_transfer: Optional[Callable] = None):
        if depth < 1:
            raise ValueError("depth must be >= 1")
        if drain not in ("blocking", "ready"):
            raise ValueError(f"bad drain={drain!r}")
        self.engine = engine
        self.device = device
        self.depth = depth
        self.drain = drain
        #: latency class every batch of this stream submits under
        #: (io/sched.py; the per-stream default — stream_ranges can
        #: override per call)
        self.klass = klass
        #: double-buffered host→HBM stage (docs/PERF.md §6).  None =
        #: auto: engage on a TPU device when STROM_BRIDGE_OVERLAP
        #: allows (the CPU fallback keeps the current device_put path
        #: bit-for-bit — an extra slab copy would only cost there).
        #: True forces the stage on any device (tests, measurements);
        #: False disables for this stream; STROM_BRIDGE_OVERLAP=0
        #: overrides everything.
        self.overlap = overlap
        #: injectable transfer callable for the stage (tests)
        self.overlap_transfer = overlap_transfer

    def _overlap_active(self, dev) -> bool:
        if not overlap_env_enabled():
            return False
        if self.overlap is not None:
            return self.overlap
        return dev.platform == "tpu"

    def _put(self, view: np.ndarray, dtype, shape):
        dev = self.device or _default_device()
        arr = view if dtype is None else view.view(dtype)
        if shape is not None:
            arr = arr.reshape(shape)
        return host_to_device(self.engine, arr, dev)

    def stream_file(self, path, chunk_bytes: Optional[int] = None,
                    dtype=None) -> Iterator:
        """Yield device arrays of consecutive file chunks (uint8 unless
        ``dtype`` given; chunk_bytes must then be dtype-size aligned)."""
        chunk = chunk_bytes or self.engine.config.chunk_bytes
        if chunk > self.engine.config.chunk_bytes:
            raise ValueError("chunk_bytes exceeds engine buffer capacity")
        fh = self.engine.open(path)
        try:
            size = self.engine.file_size(fh)
            offsets = list(range(0, size, chunk))
            yield from self.stream_ranges(
                fh, [(o, min(chunk, size - o)) for o in offsets], dtype=dtype)
        finally:
            self.engine.close(fh)

    def stream_ranges(self, fh: int, ranges: Sequence[tuple[int, int]],
                      dtype=None, shapes: Optional[Sequence] = None,
                      verify: Optional[Callable] = None,
                      klass: Optional[str] = None) -> Iterator:
        """Yield device arrays for arbitrary (offset, length) ranges of an
        open file — the planner-facing API used by the format readers.

        ``verify``: optional ``fn(range_index, host_view)`` invoked on
        the completed staging view BEFORE the device transfer — the one
        window where payload bytes are host-visible on this path, so
        read-side integrity checks (STROM_VERIFY, utils/checksum.py)
        hook here; raising aborts the stream loudly.

        ``klass``: latency class of this stream's batches (defaults to
        the stream's own ``klass``) — the QoS tag consumers set so the
        scheduler can rank their traffic (io/sched.py)."""
        if klass is None:
            klass = self.klass
        pending: list = []   # (PendingRead, shape, range_index)
        inflight: list = []  # (device_array, PendingRead-or-None)
        dev = self.device or _default_device()
        # double-buffered host→HBM stage (docs/PERF.md §6): the staging
        # buffer releases the moment its bytes land in a ping-pong slab,
        # so the NVMe read of chunk K+1 overlaps the host→HBM DMA of
        # chunk K instead of queueing behind it.  Inactive (None) =
        # today's wait→device_put path, bit-for-bit.
        stage = (OverlapStage(self.engine, dev,
                              self.engine.config.chunk_bytes,
                              transfer=self.overlap_transfer)
                 if self._overlap_active(dev) else None)

        def drain_one():
            arr, pr = inflight.pop(0)
            with self.engine.tracer.span("strom.h2d.sync",
                                         bytes=int(arr.nbytes)):
                arr.block_until_ready()  # device owns the bytes now
            if pr is not None:
                pr.release()
            return arr

        def drain_ready():
            # retire completed head transfers without blocking: their
            # staging buffers go back to the pool NOW, so the engine
            # can keep reading ahead instead of stalling on buffers
            # still pinned under long-done transfers
            while inflight and inflight[0][0].is_ready():
                yield drain_one()

        def start_transfer():
            # oldest pending read → verified staging view → device;
            # the entry leaves ``pending`` first, so on a verify
            # failure the finally can't see it — release here, no
            # buffer leak
            pr, shp, ri = pending.pop(0)
            view = pr.wait()
            if verify is not None:
                # ordering contract (docs/PERF.md §6): the verify hook
                # (and the host-tier fill inside pr.wait()) runs on the
                # completed view BEFORE any slab copy/reuse — a corrupt
                # chunk never reaches a DMA slab, let alone the device
                try:
                    verify(ri, view)
                except BaseException:
                    # a corrupt read may have been FILLED into the
                    # pinned tier before this check ran: spoil the
                    # overlapping lines so no retry/future read is
                    # served the same bytes from DRAM
                    from nvme_strom_tpu.io.hostcache import spoil_span
                    try:
                        spoil_span(self.engine, pr.fh, pr.offset,
                                   pr.length, self.engine.stats)
                    except Exception:
                        pass
                    pr.release()
                    raise
            arr = (stage.put(view, dtype, shp)
                   if stage is not None else None)
            if arr is not None:
                pr.release()   # staging recycles NOW — the overlap win
                inflight.append((arr, None))
            else:
                # no stage, or the view outgrew the slabs: the classic
                # path, source held until its transfer drains ready
                inflight.append((self._put(view, dtype, shp), pr))

        ranges = list(ranges)
        shapes_l = list(shapes) if shapes is not None else None
        try:
            i = 0
            while i < len(ranges):
                # vectored refill: up to ``depth`` ranges enter the
                # engine as ONE batched submission (single
                # io_uring_enter via submit_readv) instead of one
                # boundary crossing per chunk
                take = ranges[i:i + self.depth]
                # tiered refill: ranges resident in the pinned host
                # cache come back as ready zero-copy views (no engine
                # I/O); the rest enter as ONE batched submission
                prs = submit_spans_tiered(
                    self.engine, [(fh, off, ln) for off, ln in take],
                    klass=klass)
                for j, pr in enumerate(prs):
                    shape = (shapes_l[i + j] if shapes_l is not None
                             else None)
                    pending.append((pr, shape, i + j))
                i += len(take)
                # keep `depth` reads in flight before starting transfers
                while len(pending) > self.depth:
                    start_transfer()
                    if self.drain == "ready":
                        yield from drain_ready()
                    while len(inflight) > self.depth:
                        yield drain_one()
            while pending:
                start_transfer()
            while inflight:
                yield drain_one()
        finally:
            for pr, _, _ in pending:
                try:
                    pr.wait()
                except OSError:
                    pass
                pr.release()
            for _, pr in inflight:
                if pr is not None:
                    pr.release()
            if stage is not None:
                stage.close()

    def read_to_device(self, path, dtype=None, shape=None):
        """Whole file → one device array (concatenated on device, not host).

        Chunks stream independently to the device and are joined with a
        jitted concatenate there, so no host-side assembly buffer exists.
        """
        import jax.numpy as jnp
        parts = list(self.stream_file(path))  # uint8 chunks on device
        if not parts:
            out = jnp.zeros((0,), dtype=jnp.uint8)
        elif len(parts) == 1:
            out = parts[0]
        else:
            out = jnp.concatenate(parts)
        if dtype is not None:
            out = out.view(dtype)  # on-device bitcast, no transfer
        if shape is not None:
            out = out.reshape(shape)
        return out


def submit_chunked_writes(engine: StromEngine, fh: int, offset: int,
                          host: np.ndarray, pend: list) -> int:
    """Chunk-split pipelined writes of ``host`` bytes at ``offset`` into
    an open fh.  In-flight submissions live in the CALLER-OWNED ``pend``
    list (bounded at the engine's queue depth here) so several calls can
    share one pipeline and drain together — the one write-side pattern
    every consumer (checkpointing, KV eviction, optimizer offload)
    shares, mirroring ``split_ranges`` on the read side.

    The caller must drain ``pend`` (``.wait()`` each) before closing the
    fh: in-flight writes target it, and closing first would EBADF them —
    or hit a recycled descriptor.  Returns the bytes confirmed by waits
    done HERE (depth-bound drains); bytes still in ``pend`` are the
    caller's to count."""
    chunk = engine.config.chunk_bytes
    depth = engine.config.queue_depth
    drained = 0
    for pos in range(0, host.nbytes, chunk):
        pend.append(engine.submit_write(fh, offset + pos,
                                        host[pos:pos + chunk]))
        while len(pend) >= depth:
            drained += pend.pop(0).wait()
    return drained


def write_from_device(engine: StromEngine, array, path,
                      offset: int = 0) -> int:
    """Device array → NVMe (the checkpoint/inverse path, SURVEY.md §5).

    The device→host transfer lands in one numpy buffer; chunks of it are
    then submitted as pipelined engine writes (O_DIRECT zero-copy when the
    chunk is alignment-conformant, bounced + counted otherwise).
    """
    host = np.ascontiguousarray(np.asarray(array)).view(np.uint8).reshape(-1)
    fh = engine.open(path, writable=True)
    total = 0
    pend: list = []
    try:
        total += submit_chunked_writes(engine, fh, offset, host, pend)
        while pend:
            total += pend.pop(0).wait()
    finally:
        for p in pend:
            try:
                p.wait()
            except OSError:
                pass
        engine.close(fh)
    return total
