from nvme_strom_tpu.ops.bridge import DeviceStream, write_from_device
from nvme_strom_tpu.ops.ici import (
    IciExchange,
    ici_scatter_enabled,
    scatter_engine,
)

__all__ = ["DeviceStream", "write_from_device", "IciExchange",
           "ici_scatter_enabled", "scatter_engine"]
