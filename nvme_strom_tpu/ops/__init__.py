from nvme_strom_tpu.ops.bridge import DeviceStream, write_from_device

__all__ = ["DeviceStream", "write_from_device"]
