"""Pallas decode attention: one query token against the KV cache.

The decode hot op (models/decode.py) is memory-bound: every step streams
the whole (b, kv_heads, S, d) cache from HBM.  This kernel fuses score,
position-masked online softmax, and the weighted sum into one pass over
K/V blocks, so the score row never exists in HBM and the cache is read
exactly once — at kv-head width: the GQA query-head group attends to its
kv head INSIDE the kernel, so no nh-wide expanded copy of K/V is ever
materialised.

Memory layout: the sequence dimension lives in the GRID (sequential on a
TPU core), with the running (m, l, acc) online-softmax state in VMEM
scratch that persists across the k-block iterations — only one
(block_k, d) K tile and V tile are resident at a time, so cache length is
bounded by HBM, not VMEM.  S need not divide block_k; out-of-range block
tails are masked the same way out-of-position columns are.

Forward-only by design: decoding is inference; the training path uses
ops/flash_attention.py (which has the custom VJP).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, scale, block_k, n_kb):
    bi = pl.program_id(0)
    ki = pl.program_id(2)
    g = q_ref.shape[2]                                   # query group size

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32) * scale          # (g, d)
    k = k_ref[0, 0].astype(jnp.float32)                  # (bk, d)
    v = v_ref[0, 0].astype(jnp.float32)
    pos = pos_ref[bi]
    # Rows past pos carry zero weight (p == 0), but a padded block tail
    # may hold NaN/garbage and 0·NaN = NaN — zero those V rows outright.
    rows_ok = (ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, v.shape, 0)) <= pos
    v = jnp.where(rows_ok, v, 0.0)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (g, bk)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (g, block_k), 1)
    # <= pos masks unfilled cache AND any padded tail (pos < seq <= pad)
    s = jnp.where(cols <= pos, s, _NEG_INF)

    m = m_ref[:, 0]
    l = l_ref[:, 0]
    m_new = jnp.maximum(m, jnp.max(s, axis=1))
    p = jnp.exp(s - m_new[:, None])
    alpha = jnp.exp(m - m_new)
    m_ref[:, 0] = m_new
    l_ref[:, 0] = l * alpha + jnp.sum(p, axis=1)
    acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def decode_attention(q, k, v, pos, *, scale=None, block_k: int = 512,
                     interpret: bool = None):
    """q (b, n_heads, 1, d) attends to the kv-width cache k/v
    (b, n_kv_heads, S, d) at positions [0, pos].  ``pos`` is the int32
    index of the newest entry — a scalar, or a (b,) vector when rows
    sit at DIFFERENT positions (the continuous-batching serve step,
    models/serving.py): each grid row then masks by its own bound.
    n_heads % n_kv_heads == 0; the query group per kv head rides the
    kernel's second-to-last block dim.

    Returns (b, n_heads, 1, d).  ``interpret`` defaults to True off-TPU so
    CPU tests run the identical kernel in the Pallas interpreter.
    """
    if q.ndim != 4 or q.shape[2] != 1:
        raise ValueError(f"expected q (b, h, 1, d), got {q.shape}")
    b, nh, _, d = q.shape
    _, nkv, S, _ = k.shape
    if nh % nkv:
        raise ValueError(f"{nh} query heads not divisible by {nkv} "
                         "kv heads")
    g = nh // nkv
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_k = min(block_k, S)
    n_kb = -(-S // block_k)               # ceil: tail masked, not sliced
    qg = q.reshape(b, nkv, g, d)
    pos_arr = jnp.asarray(pos, jnp.int32)
    if pos_arr.ndim == 0:
        pos_arr = jnp.broadcast_to(pos_arr, (b,))
    elif pos_arr.shape != (b,):
        raise ValueError(f"pos must be scalar or ({b},), "
                         f"got {pos_arr.shape}")
    # Positions ride scalar prefetch (SMEM): they are control data, and a
    # (b, 1) VMEM operand would need a (1, 1) block, which the Mosaic
    # lowering rejects (last two block dims must be (8k, 128k) or the
    # array dims).
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, nkv, n_kb),
        in_specs=[
            pl.BlockSpec((1, 1, g, d),
                         lambda bi, hi, ki, ps: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, ps: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, ps: (bi, hi, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, g, d),
                               lambda bi, hi, ki, ps: (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),    # running max
            pltpu.VMEM((g, 1), jnp.float32),    # running denominator
            pltpu.VMEM((g, d), jnp.float32),    # running accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale),
                          block_k=block_k, n_kb=n_kb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, g, d), q.dtype),
        interpret=interpret,
    )(pos_arr, qg, k, v)
    return out.reshape(b, nh, 1, d)


def make_decode_attn(**kw):
    """cache_attn(q, k_cache, v_cache, pos) for models.decode.decode_step
    — the fused Pallas replacement for its masked dense einsum.  Receives
    the cache at kv-head width (no GQA expansion).

    When to use (measured on v5e, d=2048 L=8 b=8, steady-state decode
    with prefill time subtracted): the kernel wins on LONG caches — 3066
    vs 1813 tok/s at S≈1856 (~1.7x) — because it never materializes the
    masked (h, S) score row in HBM; XLA's fused einsum wins on short
    caches (6726 vs 4916 tok/s at S≈160) where per-call kernel overhead
    dominates.  Rule of thumb: prefer the kernel once the live cache
    length clears ~1k positions."""
    return functools.partial(decode_attention, **kw)
