"""Pallas flash attention — the flagship model's hot op, TPU-native.

The reference has no compute kernels at all (it is a storage engine,
SURVEY.md §1); its consumer, PG-Strom, runs CUDA kernels over the DMA'd
data (SURVEY.md §3.5).  This module is that consumer-side analogue for the
TPU build: a fused, tiled, online-softmax attention kernel so the model
exercising the NVMe→HBM data path never materialises the (s, s) score
matrix in HBM.

Design (classic FlashAttention, re-tiled for the TPU memory hierarchy):

- forward: grid over (batch, head, q-block); K/V for the head live in VMEM
  and the kernel walks k-blocks with a ``fori_loop`` whose trip count is
  causally bounded (later q-blocks do more work; earlier ones skip their
  masked-out tail entirely).  Running max/denominator (m, l) keep the
  softmax numerically exact; accumulation is fp32 regardless of input
  dtype; the log-sum-exp per row is written out as a residual.
- backward: two kernels recompute probabilities blockwise from the saved
  lse (no s×s residual): one accumulates dQ over k-blocks, the other
  dK/dV over q-blocks.  Wrapped in ``jax.custom_vjp``.
- CPU (tests, virtual meshes) runs the same kernels in interpreter mode —
  selected automatically from the default backend.

VMEM sizing: one head's K and V (s × head_dim each) must fit in VMEM,
which holds to s ≈ 16k at head_dim 128 in bf16.  Beyond that, shard the
sequence with ring attention (parallel/ring_attention.py), which can run
this kernel as its per-block inner via ``flash_attention_lse``: the
(out, lse) pair is differentiable — the LSE cotangent folds into the
existing backward kernels as ``delta_eff = delta - dlse`` (the score
gradient is ``ds = p·(dp - delta + dlse)·scale``), so the ring's
LSE-weighted block combine trains end-to-end with no extra kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _vma(*arrays):
    """Union of the inputs' varying-mesh-axes sets, so pallas_call
    out_shapes type-check under shard_map's VMA system (outside a manual
    context this is the empty set and has no effect)."""
    out = frozenset()
    for a in arrays:
        out |= getattr(jax.typeof(a), "vma", frozenset())
    return out


def _struct(shape, dtype, vma):
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # older jax: no vma kwarg, no VMA checking either
        return jax.ShapeDtypeStruct(shape, dtype)


def _pick_block(seq: int, want: int) -> int:
    """Largest divisor of ``seq`` that is <= want (block shapes must tile
    the sequence exactly)."""
    b = min(want, seq)
    while seq % b:
        b -= 1
    return b


# ----------------------------- forward -----------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                scale, block_q, block_k, causal, kv_seq):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale          # (bq, d)
    d = q.shape[-1]

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    if causal:
        # k-blocks strictly after this q-block's last row are fully masked
        n_kb = ((qi + 1) * block_q + block_k - 1) // block_k
    else:
        n_kb = kv_seq // block_k

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)
    # lse carried 4D with a trailing singleton: TPU block tiling requires
    # the last two block dims divisible by (8, 128) or equal to the array
    # dims — (block_q, 1) satisfies that where (1, 1, block_q) cannot.
    lse_ref[0, 0, :, 0] = m + jnp.log(l)


def _fwd(q, k, v, scale, block_q, block_k, causal, interpret):
    b, h, s, d = q.shape
    skv = k.shape[2]                 # may differ from s when non-causal
    grid = (b, h, s // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, kv_seq=skv),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, skv, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, skv, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            _struct(q.shape, q.dtype, _vma(q, k, v)),
            _struct((b, h, s, 1), jnp.float32, _vma(q, k, v)),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ----------------------------- backward -----------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, block_q, block_k, causal, kv_seq):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)                  # (bq, d)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]                            # (bq,)
    delta = delta_ref[0, 0, :, 0]
    d = q.shape[-1]

    n_kb = (((qi + 1) * block_q + block_k - 1) // block_k) if causal \
        else kv_seq // block_k

    def body(i, dq):
        k = k_ref[0, 0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])                    # exact probs
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_kb, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, block_q, block_k, causal, seq):
    ki = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)                  # (bk, d)
    v = v_ref[0, 0].astype(jnp.float32)
    d = k.shape[-1]
    n_qb = seq // block_q
    q_start = (ki * block_k) // block_q if causal else 0

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(j * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(j * block_q, block_q), 0]
        delta = delta_ref[0, 0, pl.ds(j * block_q, block_q), 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])                    # (bq, bk)
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(q_start, n_qb, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd_pallas(scale, block_q, block_k, causal, interpret,
                q, k, v, lse, dout, delta):
    """Shared backward: ``delta`` is (b, h, s, 1) fp32.  For the plain
    output VJP it is Σ_d do·o; when an LSE cotangent exists it is
    Σ_d do·o − dlse (the dlse term enters ds with the opposite sign of
    delta, so folding it here reuses both kernels unchanged)."""
    b, h, s, d = q.shape
    skv = k.shape[2]
    kw = dict(scale=scale, block_q=block_q, block_k=block_k, causal=causal)
    blk_q = lambda bi, hi, qi: (bi, hi, qi, 0)       # noqa: E731
    full = lambda bi, hi, qi: (bi, hi, 0, 0)         # noqa: E731

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, kv_seq=skv, **kw),
        grid=(b, h, s // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), blk_q),
            pl.BlockSpec((1, 1, skv, d), full),
            pl.BlockSpec((1, 1, skv, d), full),
            pl.BlockSpec((1, 1, block_q, d), blk_q),
            pl.BlockSpec((1, 1, block_q, 1), blk_q),
            pl.BlockSpec((1, 1, block_q, 1), blk_q),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), blk_q),
        out_shape=_struct(q.shape, q.dtype, _vma(q, k, v, dout, lse, delta)),
        interpret=interpret,
    )(q, k, v, dout, lse, delta)

    blk_k = lambda bi, hi, ki: (bi, hi, ki, 0)       # noqa: E731
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, seq=s, **kw),
        grid=(b, h, skv // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, s, d), full),
            pl.BlockSpec((1, 1, block_k, d), blk_k),
            pl.BlockSpec((1, 1, block_k, d), blk_k),
            pl.BlockSpec((1, 1, s, d), full),
            pl.BlockSpec((1, 1, s, 1), full),
            pl.BlockSpec((1, 1, s, 1), full),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), blk_k),
            pl.BlockSpec((1, 1, block_k, d), blk_k),
        ],
        out_shape=[
            _struct(k.shape, k.dtype, _vma(q, k, v, dout, lse, delta)),
            _struct(v.shape, v.dtype, _vma(q, k, v, dout, lse, delta)),
        ],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


# ----------------------------- public API -----------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, scale, block_q, block_k, causal, interpret):
    out, lse = _fwd(q, k, v, scale, block_q, block_k, causal, interpret)
    return out, lse[..., 0]


def _flash_lse_fwd(q, k, v, scale, block_q, block_k, causal, interpret):
    out, lse = _fwd(q, k, v, scale, block_q, block_k, causal, interpret)
    return (out, lse[..., 0]), (q, k, v, out, lse)


def _flash_lse_bwd(scale, block_q, block_k, causal, interpret, res, cts):
    q, k, v, out, lse = res
    dout, dlse = cts
    delta = (jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                     axis=-1, keepdims=True)
             - dlse.astype(jnp.float32)[..., None])      # (b, h, s, 1)
    return _bwd_pallas(scale, block_q, block_k, causal, interpret,
                       q, k, v, lse, dout, delta)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention(q, k, v, *, causal: bool = True, scale: float = None,
                    block_q: int = None, block_k: int = None,
                    interpret: bool = None):
    """Fused attention over (batch, heads, seq, head_dim) tensors.

    Differentiable (custom VJP with blockwise-recompute backward).
    ``interpret`` defaults to True off-TPU so CPU tests and virtual meshes
    run the identical kernel in the Pallas interpreter.

    ``block_q``/``block_k`` default to the ledgered kernel-probe best
    for the nearest probed shape (utils/tuning.best_attn_blocks; the
    window-7 sweep measured the 128x128 fallback at ~1.8x the tuned
    tiling's step time), else 128x128.

    K/V may have a different sequence length than Q when ``causal=False``
    (blockwise/ring combines, cross-attention); causal masking assumes
    aligned positions and therefore requires equal lengths.
    """
    out, _ = _flash_lse(q, k, v, *_prep(q, k, causal, scale, block_q,
                                        block_k, interpret))
    return out


def _prep(q, k, causal, scale, block_q, block_k, interpret):
    """Shared argument normalisation: returns the static tail
    (scale, block_q, block_k, causal, interpret) for ``_flash_lse``."""
    if q.ndim != 4:
        raise ValueError(f"expected (b, h, s, d), got {q.shape}")
    s, skv = q.shape[2], k.shape[2]
    if causal and skv != s:
        raise ValueError(
            f"causal attention requires equal q/kv lengths, got {s} vs "
            f"{skv} (position alignment is ambiguous otherwise)")
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_q is None or block_k is None:
        from nvme_strom_tpu.utils.tuning import best_attn_blocks
        tuned = best_attn_blocks(s, skv) or (128, 128)
        block_q = tuned[0] if block_q is None else block_q
        block_k = tuned[1] if block_k is None else block_k
    return (float(scale), _pick_block(s, block_q),
            _pick_block(skv, block_k), bool(causal), bool(interpret))


def flash_attention_lse(q, k, v, *, causal: bool = True, scale: float = None,
                        block_q: int = None, block_k: int = None,
                        interpret: bool = None):
    """Like :func:`flash_attention` but also returns the per-row
    log-sum-exp, shape (b, h, s) fp32 — the residual a blockwise combine
    needs (ring attention weights per-block outputs by LSE).  The pair is
    differentiable: cotangents on BOTH outputs flow through the shared
    backward kernels.
    """
    return _flash_lse(q, k, v, *_prep(q, k, causal, scale, block_q,
                                      block_k, interpret))


def make_flash_attn(causal: bool = True, **kw):
    """attn_fn for models.transformer.forward — drop-in replacement for
    dense_causal_attention with O(s) memory."""
    return functools.partial(flash_attention, causal=causal, **kw)
