"""Apache Arrow IPC file: footer-only planning + zero-copy batch decode.

Backs benchmark config 2 (BASELINE.md: "Apache Arrow column file →
single-chip DeviceArray") — the PG-Strom Arrow-scan analogue (SURVEY.md
§3.5).  Strategy:

1. Parse the file footer ourselves (a small flatbuffer at the file tail —
   ~60 lines of cursor arithmetic, no flatbuffers dependency) to get each
   record batch's ``(offset, metadata_length, body_length)`` Block.  Only
   the footer is read with buffered I/O.
2. Direct-read whole batches (metadata+body) through the engine.
3. Let pyarrow wrap the engine buffer ZERO-COPY (``pa.py_buffer`` over the
   numpy view) and decode the record batch — column buffers point into the
   staging memory; no host memcpy happens.
4. ``device_put`` individual columns (the host→TPU transfer reads staging
   memory directly).

File layout: ``ARROW1\\0\\0 | messages... | footer | i32 footer_len | ARROW1``.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, List, Optional

import numpy as np

from nvme_strom_tpu.formats.base import (PlanEntry, ReadPlan,
                                         pread_nopollute)

_MAGIC = b"ARROW1"


class _FlatBuf:
    """Minimal flatbuffer cursor: just enough for the Arrow Footer table."""

    def __init__(self, buf: bytes):
        self.buf = buf

    def u16(self, pos):
        return struct.unpack_from("<H", self.buf, pos)[0]

    def i32(self, pos):
        return struct.unpack_from("<i", self.buf, pos)[0]

    def u32(self, pos):
        return struct.unpack_from("<I", self.buf, pos)[0]

    def i64(self, pos):
        return struct.unpack_from("<q", self.buf, pos)[0]

    def root(self) -> int:
        return self.u32(0)

    def field(self, table: int, field_id: int) -> int:
        """Absolute position of a table field, or 0 if absent."""
        vtable = table - self.i32(table)
        vlen = self.u16(vtable)
        slot = 4 + 2 * field_id
        if slot >= vlen:
            return 0
        off = self.u16(vtable + slot)
        return table + off if off else 0

    def vector(self, field_pos: int):
        """(element_start, length) of a vector field."""
        vec = field_pos + self.u32(field_pos)
        return vec + 4, self.u32(vec)


def _parse_footer_blocks(footer: bytes) -> List[tuple]:
    """RecordBatch Blocks from the Footer flatbuffer.

    Footer table fields: 0=version, 1=schema, 2=dictionaries,
    3=recordBatches.  Block is an inline 24-byte struct:
    i64 offset, i32 metaDataLength (+4 pad), i64 bodyLength.
    """
    fb = _FlatBuf(footer)
    table = fb.root()
    field = fb.field(table, 3)
    if not field:
        return []
    start, n = fb.vector(field)
    blocks = []
    for i in range(n):
        base = start + 24 * i
        blocks.append((fb.i64(base), fb.i32(base + 8), fb.i64(base + 16)))
    return blocks


class ArrowFileReader:
    """Plan + decode an Arrow IPC file through the direct engine."""

    def __init__(self, path):
        self.path = str(path)
        # no-pollution metadata reads (one open): the head magic's
        # readahead would leave the FIRST message's pages resident and
        # flip the engine's residency planner to the buffered path
        fd = os.open(self.path, os.O_RDONLY)
        try:
            size = os.fstat(fd).st_size
            head = pread_nopollute(self.path, 8, fd=fd)
            if head[:6] != _MAGIC:
                raise ValueError(f"{path}: not an Arrow IPC file")
            tail = pread_nopollute(self.path, 10, size - 10, fd=fd)
            if tail[4:] != _MAGIC:
                raise ValueError(f"{path}: bad trailing magic")
            (flen,) = struct.unpack("<i", tail[:4])
            footer = pread_nopollute(self.path, flen, size - 10 - flen,
                                     fd=fd)
        finally:
            os.close(fd)
        self.blocks = _parse_footer_blocks(footer)
        import pyarrow as pa
        import pyarrow.ipc as ipc
        with pa.OSFile(self.path, "rb") as f:
            self.schema = ipc.open_file(f).schema

    @property
    def num_batches(self) -> int:
        return len(self.blocks)

    def plan(self) -> ReadPlan:
        entries = tuple(
            PlanEntry(key=f"batch{i}", offset=off, length=mlen + blen,
                      meta={"metadata_length": mlen, "body_length": blen})
            for i, (off, mlen, blen) in enumerate(self.blocks))
        return ReadPlan(self.path, entries)

    def decode_batch(self, view: np.ndarray):
        """Zero-copy decode of one direct-read batch range."""
        import pyarrow as pa
        import pyarrow.ipc as ipc
        buf = pa.py_buffer(view)  # wraps the staging memory, no copy
        msg = ipc.read_message(pa.BufferReader(buf))
        return ipc.read_record_batch(msg, self.schema)

    def read_columns_to_device(self, engine, columns: Optional[List[str]]
                               = None, device=None, depth: int = 3
                               ) -> Dict[str, object]:
        """Config-2 path: stream batches direct (``depth`` reads in flight,
        so NVMe overlaps decode + PCIe) → zero-copy pyarrow decode →
        device_put columns via the shared bridge rule → on-device concat."""
        import jax
        import jax.numpy as jnp
        from nvme_strom_tpu.ops.bridge import (StagingRetirePool,
                                               host_to_device)
        import numpy as np
        from nvme_strom_tpu.ops.bridge import split_ranges
        dev = device or jax.local_devices()[0]
        names = columns or [f.name for f in self.schema]
        parts: Dict[str, list] = {n: [] for n in names}
        entries = self.plan().entries
        chunk = engine.config.chunk_bytes
        # Budget against the engine staging pool (a deferred submit
        # waits for a buffer only THIS consumer can free): entry_depth
        # messages in flight × the widest message's sub-chunk count,
        # plus deferred-release entries, must leave a buffer free.
        # Tiny pools degrade to retire depth 0 = block per batch.
        max_subs = max((-(-e.length // chunk) for e in entries),
                       default=1)
        if max_subs > engine.n_buffers:
            raise ValueError(
                f"one record batch needs {max_subs} staging buffers "
                f"but the pool has {engine.n_buffers}; raise "
                "EngineConfig.chunk_bytes or buffer_pool_bytes")
        entry_depth = min(depth,
                          max(1, (engine.n_buffers // 2) // max_subs))
        # retire depth is counted in ENTRIES, and a deferred multi-
        # chunk message holds max_subs staging buffers — budget in
        # buffers or the submit loop can block on a buffer only this
        # consumer's retire can free (deadlock on a real accelerator,
        # where transfers are not instantly ready)
        retire = StagingRetirePool(
            max(0, (engine.n_buffers - entry_depth * max_subs - 1)
                // max_subs))
        fh = engine.open(self.path)
        pend: list = []    # (entry, [PendingRead, ...]) per message
        import pyarrow as pa
        col_types = {n: self.schema.field(n).type for n in names}
        layout_ok = all(pa.types.is_integer(t) or pa.types.is_floating(t)
                        for t in col_types.values())
        # one zeros buffer serves every message's fake-body decode
        # (body bytes are never read — only buffer ADDRESSES matter —
        # so stale bytes from a previous reuse are harmless)
        fake_buf = (np.zeros(max((e.length for e in entries), default=0),
                             np.uint8)
                    if layout_ok and max_subs > 1 else None)
        try:
            def decode_and_put(batch, release):
                put = []
                for n in names:
                    col = batch.column(n)
                    if col.null_count:
                        raise ValueError(
                            f"column {n} has nulls; dense scan only")
                    host = col.to_numpy(zero_copy_only=True)
                    arr = host_to_device(engine, host, dev)
                    parts[n].append(arr)
                    put.append(arr)
                # staging released once the transfers complete —
                # DEFERRED, not blocked per batch: the per-batch
                # block_until_ready this replaces paid one link round
                # trip per record batch
                retire.push(release, put)

            def layout_put(entry, views, reads):
                """Multi-chunk message, assembled ON DEVICE: decode the
                metadata against a ZEROS body (no payload byte touched)
                to learn each column buffer's (offset, length), then put
                the staging pieces directly and concatenate there —
                the parquet degap recipe applied to Arrow IPC.  Returns
                the device arrays, or None when a column isn't a
                fixed-width int/float (the assembly fallback handles
                those)."""
                import pyarrow.ipc as ipc
                if fake_buf is None:
                    return None
                mlen = entry.meta["metadata_length"]
                total = entry.length
                fake = fake_buf[:total]
                pos = 0
                for v in views:              # metadata bytes are tiny
                    if pos >= mlen:
                        break
                    take = min(mlen - pos, v.nbytes)
                    fake[pos:pos + take] = v[:take]
                    pos += take
                buf = pa.py_buffer(fake)
                msg = ipc.read_message(pa.BufferReader(buf))
                batch = ipc.read_record_batch(msg, self.schema)
                base = fake_buf.ctypes.data
                rows = batch.num_rows
                put = []
                for n in names:
                    col = batch.column(n)
                    if col.null_count:
                        raise ValueError(
                            f"column {n} has nulls; dense scan only")
                    data = col.buffers()[-1]
                    np_dtype = np.dtype(col_types[n].to_pandas_dtype())
                    start = data.address - base   # message-relative
                    nbytes = rows * np_dtype.itemsize
                    pieces, vpos = [], 0
                    for v in views:
                        vend = vpos + v.nbytes
                        if vend > start and vpos < start + nbytes:
                            a = max(0, start - vpos)
                            b = min(v.nbytes, start + nbytes - vpos)
                            if b > a:
                                pieces.append(host_to_device(
                                    engine, v[a:b], dev))
                        vpos = vend
                    put.extend(pieces)
                    arr = (pieces[0] if len(pieces) == 1
                           else jnp.concatenate(pieces)).view(np_dtype)
                    parts[n].append(arr)
                retire.push(lambda rs=reads: [p.release() for p in rs],
                            put)
                return put

            def consume(item):
                entry, reads = item
                try:
                    if len(reads) == 1:
                        # whole message in one staging buffer:
                        # zero-copy decode straight from it
                        decode_and_put(
                            self.decode_batch(reads[0].wait()),
                            reads[0].release)
                        return
                    views = [p.wait() for p in reads]
                    if layout_put(entry, views, reads) is not None:
                        return
                    # non-primitive columns: the decoder needs the
                    # message contiguous, so sub-chunks assemble into
                    # ONE host buffer (counted as bounce)
                    host = np.empty(sum(v.nbytes for v in views),
                                    np.uint8)
                    pos = 0
                    for p, v in zip(reads, views):
                        host[pos:pos + v.nbytes] = v
                        pos += v.nbytes
                        p.release()
                    engine.stats.add(bounce_bytes=int(pos))
                    decode_and_put(self.decode_batch(host), None)
                except BaseException:
                    for p in reads:    # idempotent: leak-free on a
                        p.release()    # mid-assembly wait() failure
                    raise

            for entry in entries:
                ranges, _ = split_ranges([(entry.offset, entry.length)],
                                         chunk)
                pend.append((entry, [engine.submit_read(fh, o, ln)
                                     for o, ln in ranges]))
                if len(pend) >= entry_depth:
                    consume(pend.pop(0))
            while pend:
                consume(pend.pop(0))
        finally:
            retire.flush()
            for _, reads in pend:
                for p in reads:
                    p.release()  # waits if still in flight
            engine.close(fh)
        return {n: (v[0] if len(v) == 1 else jnp.concatenate(v))
                for n, v in parts.items()}
