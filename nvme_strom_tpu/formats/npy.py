"""NumPy ``.npy`` / ``.npz``-directory planning: arrays NVMe→HBM direct.

The simplest fixed-layout format there is — one header, one contiguous
payload — and therefore the purest demonstration of the framework's
read path (SURVEY.md §3.1): the header is metadata-class buffered I/O,
the payload spans stream O_DIRECT → staging → device and the "decode"
is an on-device bitcast + reshape.  Fortran-ordered and object arrays
fall back with a reason (no on-device transpose surprise, no pickle).

``.npz`` (a zip of .npy members) is planned by walking the zip central
directory; STORED (uncompressed) members stream direct, DEFLATE
members are rejected with a reason — compression is host decode by
nature and numpy's default ``savez`` is uncompressed.
"""

from __future__ import annotations

import ast
import struct
import zipfile
from typing import Dict, Optional, Tuple

import numpy as np

from nvme_strom_tpu.formats.base import PlanEntry, ReadPlan

_MAGIC = b"\x93NUMPY"


class _HeaderWindow(ValueError):
    """Header extends past the read window; ``needed`` bytes suffice."""

    def __init__(self, needed: int):
        super().__init__(f"header needs {needed} bytes")
        self.needed = needed


def _parse_npy_header(buf: bytes) -> Tuple[dict, int]:
    """→ (header dict, payload offset).  Raises ValueError on anything
    that is not a v1/v2/v3 .npy header; _HeaderWindow when the window
    was simply too small (the format allows headers far beyond 4 KiB —
    callers re-read with ``needed``)."""
    if buf[:6] != _MAGIC:
        raise ValueError("not a .npy file (bad magic)")
    major = buf[6]
    if major == 1:
        (hlen,) = struct.unpack_from("<H", buf, 8)
        start = 10
    elif major in (2, 3):
        (hlen,) = struct.unpack_from("<I", buf, 8)
        start = 12
    else:
        raise ValueError(f"unsupported .npy version {major}")
    if start + hlen > len(buf):
        raise _HeaderWindow(start + hlen)
    header = ast.literal_eval(buf[start:start + hlen].decode("latin1"))
    return header, start + hlen


def plan_npy(path, name: Optional[str] = None,
             base_offset: int = 0, header_window: int = 4096,
             read_at=None) -> PlanEntry:
    """One .npy file (or embedded member at ``base_offset``) → its
    payload PlanEntry.  ``read_at(off, ln)`` overrides the default
    buffered open (zip members)."""
    import os

    if read_at is None:
        f = open(path, "rb")
        read_at = lambda off, ln: os.pread(f.fileno(), ln, off)  # noqa
    else:
        f = None
    try:
        buf = read_at(base_offset, header_window)
        try:
            header, payload_off = _parse_npy_header(buf)
        except _HeaderWindow as hw:
            # clamp the re-read: a corrupt length field must not drive
            # a multi-GiB allocation (any sane header is far smaller;
            # a still-short buffer re-raises as a plain ValueError)
            buf = read_at(base_offset, min(hw.needed, 1 << 26))
            header, payload_off = _parse_npy_header(buf)
    finally:
        if f is not None:
            f.close()
    descr, shape = header["descr"], tuple(header["shape"])
    if header.get("fortran_order"):
        raise ValueError("fortran_order arrays need a host transpose — "
                         "load via numpy instead")
    dt = np.dtype(descr)
    if dt.hasobject:
        raise ValueError("object arrays are pickle payloads, not raw "
                         "bytes")
    if dt.names is not None or dt.kind == "V":
        raise ValueError(f"structured dtype {descr!r} has no on-device "
                         "representation — load via numpy instead")
    if dt.byteorder == ">":
        raise ValueError(f"big-endian dtype {descr!r}: the on-device "
                         "bitcast is little-endian — byteswap and "
                         "re-save, or load via numpy")
    length = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    return PlanEntry(key=name or str(path),
                     offset=base_offset + payload_off, length=length,
                     dtype=dt.str, shape=shape)


def plan_npz(path) -> ReadPlan:
    """A .npz archive → one PlanEntry per STORED member.

    The zip central directory (buffered metadata read via zipfile) gives
    each member's data offset; the member's own .npy header is then
    parsed in place.  DEFLATE members raise with a reason."""
    entries = []
    with zipfile.ZipFile(path) as z, open(path, "rb") as f:
        import os

        def read_at(off: int, ln: int) -> bytes:
            return os.pread(f.fileno(), ln, off)

        for info in z.infolist():
            if info.compress_type != zipfile.ZIP_STORED:
                raise ValueError(
                    f"member {info.filename!r} is compressed "
                    f"(type {info.compress_type}) — host decode; use "
                    f"np.load or save with np.savez (uncompressed)")
            # local header: fixed 30 bytes + name + extra
            lh = read_at(info.header_offset, 30)
            if lh[:4] != b"PK\x03\x04":
                raise ValueError(f"bad local header for "
                                 f"{info.filename!r}")
            nlen, elen = struct.unpack_from("<HH", lh, 26)
            data_off = info.header_offset + 30 + nlen + elen
            name = info.filename
            if name.endswith(".npy"):
                name = name[:-4]
            entries.append(plan_npy(path, name=name,
                                    base_offset=data_off,
                                    read_at=read_at))
    return ReadPlan(str(path), tuple(entries))


def read_npy_to_device(engine, path, device=None):
    """Whole .npy array → device, payload zero-copy through the engine."""
    out = _read_plan_to_device(engine, path,
                               ReadPlan(str(path), (plan_npy(path),)),
                               device)
    return next(iter(out.values()))


def read_npz_to_device(engine, path, device=None,
                       keys=None) -> Dict[str, object]:
    """.npz members → {name: device array}, all members pipelined
    through ONE stream (queue depth stays full across member
    boundaries — the sql/pq_direct multi-span pattern)."""
    plan = plan_npz(path)
    if keys is not None:
        plan = plan.subset(list(keys))
    return _read_plan_to_device(engine, path, plan, device)


def _read_plan_to_device(engine, path, plan: ReadPlan, device=None):
    import jax
    import jax.numpy as jnp
    from nvme_strom_tpu.ops.bridge import DeviceStream, split_ranges
    for e in plan.entries:
        if (np.dtype(e.dtype).itemsize == 8
                and not jax.config.jax_enable_x64):
            # the on-device bitcast would silently truncate i64/f64
            raise ValueError(f"{e.key}: dtype {e.dtype} needs "
                             f"jax_enable_x64 (bitcast would truncate)")
    dev = device or jax.local_devices()[0]
    ds = DeviceStream(engine, device=dev,
                      depth=engine.config.queue_depth)
    ranges, counts = split_ranges(plan.ranges(),
                                  engine.config.chunk_bytes)
    out: Dict[str, object] = {}
    fh = engine.open(path)
    try:
        it = ds.stream_ranges(fh, ranges)
        for entry, n in zip(plan.entries, counts):
            parts = [next(it) for _ in range(n)]
            if not parts:
                flat = jnp.zeros((0,), jnp.uint8)
            else:
                flat = (parts[0] if len(parts) == 1
                        else jnp.concatenate(parts))
            out[entry.key] = flat.view(
                np.dtype(entry.dtype)).reshape(entry.shape)
    finally:
        engine.close(fh)
    return out
