"""WebDataset (POSIX tar) sample indexing + ranged-read planning.

A WebDataset shard is an uncompressed tar whose members are grouped into
samples by basename: ``000123.jpg`` + ``000123.cls`` form sample
``000123`` with parts ``jpg`` and ``cls``.  The index pass parses only the
512-byte tar headers; member payloads are planned as direct-engine ranges.
Backs benchmark config 3 (BASELINE.md).
"""

from __future__ import annotations

import os
import subprocess
import tarfile
from typing import Dict, List, Optional

from nvme_strom_tpu.formats.base import (PlanEntry, ReadPlan,
                                         pread_nopollute)

_BLOCK = 512


def _split_key(name: str):
    """webdataset convention: key = path up to the FIRST dot of the
    basename; extension = everything after it."""
    slash = name.rfind("/")
    dot = name.find(".", slash + 1)
    if dot < 0:
        return name, ""
    return name[:dot], name[dot + 1:]


class WdsShardIndex:
    """Sample → {ext: (offset, length)} map for one tar shard."""

    def __init__(self, path):
        self.path = str(path)
        self.samples: Dict[str, Dict[str, tuple]] = {}
        self.order: List[str] = []
        # magic sniff without page-cache pollution (see
        # formats.base.pread_nopollute: a plain read(2)'s readahead
        # would flip the engine's residency planner to the buffered
        # path for the first dozen members)
        head = pread_nopollute(self.path, 2)
        if head == b"\x1f\x8b":
            raise ValueError(
                f"{self.path}: gzip-compressed shard (.tar.gz) — "
                "a compressed stream has no random access, so the "
                "direct-read path cannot serve it; store shards as "
                "plain .tar (WebDataset's recommended layout for "
                "high-throughput readers)")
        for name, off, size in self._members():
            key, ext = _split_key(name)
            if key not in self.samples:
                self.samples[key] = {}
                self.order.append(key)
            self.samples[key][ext] = (off, size)
        # No-pollution note: the native C walker reads its 4 MiB
        # windows via O_DIRECT (csrc strom_tar_index), so indexing
        # leaves the page cache exactly as it found it — a resident
        # member span would otherwise make the engine's submit-time
        # mincore planner choose the buffered path for every member
        # read that follows (a cold wds_raw epoch measured 100%
        # fallback+bounce from exactly this).  The Python tarfile
        # fallback still walks buffered; it only runs when the C
        # library is absent or the archive needs features the walker
        # lacks.

    def _members(self):
        """(name, data offset, size) per regular member — the native C
        header walk (io.engine.tar_index, ~5x the Python loop) when
        the engine library is built; tarfile otherwise, or when
        STROM_PY_TAR=1 forces the fallback (tests/bench compare the
        two)."""
        if not os.environ.get("STROM_PY_TAR"):
            try:
                from nvme_strom_tpu.io.engine import tar_index
                return tar_index(self.path)
            except (OSError, ImportError, subprocess.SubprocessError):
                pass   # library absent or unbuildable — Python fallback
            except NotImplementedError:
                pass   # valid archive, feature the C walker doesn't do
                       # (global pax overrides, >4096-byte names):
                       # tarfile handles these — corrupt archives still
                       # raise ValueError loudly above
        out = []
        # tarfile parses headers only; data is skipped via seeks.
        with tarfile.open(self.path, "r:") as tf:
            for m in tf:
                if m.isfile():
                    out.append((m.name, m.offset_data, m.size))
        return out

    def __len__(self) -> int:
        return len(self.order)

    def plan(self, keys: Optional[List[str]] = None,
             exts: Optional[List[str]] = None) -> ReadPlan:
        keys = keys if keys is not None else self.order
        entries = []
        for k in keys:
            parts = self.samples[k]
            for ext, (off, ln) in parts.items():
                if exts is not None and ext not in exts:
                    continue
                entries.append(PlanEntry(key=f"{k}.{ext}", offset=off,
                                         length=ln))
        return ReadPlan(self.path, tuple(entries))


def write_wds_shard(path, samples: List[Dict[str, bytes]],
                    keys: Optional[List[str]] = None,
                    checksums: bool = False) -> None:
    """Write samples (each a {ext: payload} dict) as an uncompressed tar.

    ``checksums=True`` also stamps an offset-keyed CRC32C sidecar
    (``<path>.crc.json``, utils/checksum.py) so readers under
    ``STROM_VERIFY`` — and the offline scrubber — can prove every
    member payload; existing shards stamp after the fact via
    ``utils.checksum.stamp_wds`` / ``strom-scrub --stamp``."""
    import io
    # a previous writer's sidecar must never pair with the NEW bytes
    # (stale stamps would "verify" them against the OLD contents and
    # quarantine a healthy shard), including the crash window between
    # the data write below and a checksums=True restamp — drop it
    # BEFORE any new byte lands; unstamped merely skips verification
    from nvme_strom_tpu.utils.checksum import sidecar_path
    try:
        os.unlink(sidecar_path(path))
    except OSError:
        pass
    spans = []      # (payload offset, length, payload) per tar member
    with tarfile.open(path, "w", format=tarfile.USTAR_FORMAT) as tf:
        for i, sample in enumerate(samples):
            key = keys[i] if keys else f"{i:08d}"
            for ext, payload in sample.items():
                info = tarfile.TarInfo(name=f"{key}.{ext}")
                info.size = len(payload)
                tf.addfile(info, io.BytesIO(payload))
                if checksums:
                    # addfile leaves tf.offset at the end of the
                    # 512-padded payload (it deep-copies the TarInfo,
                    # so info.offset_data is NOT updated) — recover the
                    # payload start from there and stamp from the bytes
                    # in hand instead of re-reading the whole shard
                    # back (utils.checksum's stamp_wds exists for
                    # after-the-fact stamping)
                    padded = -(-len(payload) // 512) * 512
                    spans.append((tf.offset - padded, len(payload),
                                  payload))
    if checksums:
        from nvme_strom_tpu.utils.checksum import write_sidecar
        write_sidecar(path, spans)
