"""safetensors: header parsing + ranged-read planning (no deserialization).

Format: ``u64le header_len | header_json | tensor data``, where the JSON maps
tensor name → {"dtype", "shape", "data_offsets": [begin, end)} with offsets
relative to the end of the header.  Parsing only touches the header; tensor
bytes are planned as direct-engine ranges.  This backs benchmark config 4
(BASELINE.md: "Llama-3 8B safetensors weight shards on NVMe → lazy HBM param
load") — the read side of the reference's inverse path noted in SURVEY.md §5
"Checkpoint/resume".

A writer is included so tests and the checkpoint path can produce the format
without external dependencies.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Dict, Optional, Sequence

import numpy as np

from nvme_strom_tpu.formats.base import (PlanEntry, ReadPlan,
                                         pread_nopollute)

_DTYPES: Dict[str, str] = {
    "BOOL": "bool", "U8": "uint8", "I8": "int8",
    "U16": "uint16", "I16": "int16", "U32": "uint32", "I32": "int32",
    "U64": "uint64", "I64": "int64",
    "F16": "float16", "F32": "float32", "F64": "float64",
    "BF16": "bfloat16",
}
_DTYPES_INV = {v: k for k, v in _DTYPES.items()}


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


class SafetensorsFile:
    """Lazily-parsed safetensors header; never reads tensor payloads."""

    def __init__(self, path):
        self.path = str(path)
        # no-pollution header parse (one open): a buffered read's
        # readahead would leave the file head resident and flip the
        # engine's residency planner to the buffered path for every
        # small early tensor
        fd = os.open(self.path, os.O_RDONLY)
        try:
            (hlen,) = struct.unpack(
                "<Q", pread_nopollute(self.path, 8, fd=fd))
            if hlen > 100 << 20:
                raise ValueError(
                    f"implausible safetensors header: {hlen}")
            header = json.loads(pread_nopollute(self.path, hlen, 8,
                                                fd=fd))
        finally:
            os.close(fd)
        self.data_start = 8 + hlen
        self.metadata = header.pop("__metadata__", {})
        # integrity stamps ride __metadata__ on disk (spec-legal) but
        # are plumbing, not user metadata: split them out so consumers
        # of .metadata see exactly what the writer was asked to record
        self._integrity = {
            k: self.metadata.pop(k) for k in list(self.metadata)
            if k.startswith(_CRC_PREFIX) or k == _CRC_ALGO_KEY}
        self.tensors: Dict[str, dict] = {}
        for name, info in header.items():
            begin, end = info["data_offsets"]
            self.tensors[name] = {
                "dtype": _DTYPES.get(info["dtype"], info["dtype"].lower()),
                "shape": tuple(info["shape"]),
                "offset": self.data_start + begin,
                "nbytes": end - begin,
            }

    def keys(self):
        return self.tensors.keys()

    def plan(self, names: Optional[Sequence[str]] = None) -> ReadPlan:
        names = list(names) if names is not None else list(self.tensors)
        entries = []
        for n in names:
            t = self.tensors[n]
            entries.append(PlanEntry(key=n, offset=t["offset"],
                                     length=t["nbytes"], dtype=t["dtype"],
                                     shape=t["shape"]))
        return ReadPlan(self.path, tuple(entries))

    def slice_plan(self, name: str, start_row: int, num_rows: int
                   ) -> PlanEntry:
        """Byte range of rows [start_row, start_row+num_rows) of a tensor —
        rows along axis 0 are contiguous, so a row-shard of a tensor is one
        contiguous direct read.  This is what lets a pjit'd host read ONLY
        its local shard of a weight matrix (benchmark config 4)."""
        t = self.tensors[name]
        shape = t["shape"]
        if not shape:
            raise ValueError(f"{name} is a scalar; cannot row-slice")
        if start_row < 0 or start_row + num_rows > shape[0]:
            raise ValueError(
                f"rows [{start_row}, {start_row + num_rows}) out of bounds "
                f"for {name} with shape {shape}")
        row_elems = int(np.prod(shape[1:], dtype=np.int64)) if len(shape) > 1 else 1
        itemsize = _np_dtype(t["dtype"]).itemsize
        row_bytes = row_elems * itemsize
        return PlanEntry(
            key=name,
            offset=t["offset"] + start_row * row_bytes,
            length=num_rows * row_bytes,
            dtype=t["dtype"],
            shape=(num_rows,) + tuple(shape[1:]),
        )


#: __metadata__ key prefix for per-tensor CRC32C stamps (str values —
#: the spec keeps metadata flat string→string); the algo tag rides
#: alongside under _CRC_ALGO_KEY so readers never compare values from
#: different polynomials
_CRC_PREFIX = "crc32c."
_CRC_ALGO_KEY = "checksum_algo"


def _checksum_metadata(tensors: Dict[str, np.ndarray]) -> dict:
    """Per-tensor CRC32C stamps for ``__metadata__`` — write-time
    integrity (docs/RESILIENCE.md): one pass over the payload bytes at
    native CRC speed, so a reader (restore, weight streaming,
    strom_scrub) can prove the bytes it got are the bytes written."""
    from nvme_strom_tpu.utils.checksum import CRC_ALGO, crc32c
    meta = {_CRC_ALGO_KEY: CRC_ALGO}
    for name, arr in tensors.items():
        meta[_CRC_PREFIX + name] = str(crc32c(np.asarray(arr)))
    return meta


def tensor_checksums(sf: "SafetensorsFile") -> Dict[str, int]:
    """Stamped per-tensor checksums of a parsed file ({} when the file
    predates stamping or used a different algo — verification of an
    unstamped tensor is silently skipped, never an error)."""
    from nvme_strom_tpu.utils.checksum import CRC_ALGO
    md = getattr(sf, "_integrity", {})
    if md.get(_CRC_ALGO_KEY) != CRC_ALGO:
        return {}
    out = {}
    for k, v in md.items():
        if k.startswith(_CRC_PREFIX):
            try:
                out[k[len(_CRC_PREFIX):]] = int(v)
            except ValueError:
                continue
    return out


def build_header(tensors: Dict[str, np.ndarray],
                 metadata: Optional[dict] = None,
                 align: int = 8) -> tuple[bytes, Dict]:
    """Serialize the safetensors header for ``tensors`` (insertion order).

    Returns ``(header_bytes, offsets)`` where ``offsets[name]`` is the
    absolute file offset of that tensor's payload.  ``align`` pads the
    header (trailing spaces in the JSON — spec-legal) so the data
    section starts at that boundary; the engine writer passes its
    O_DIRECT alignment so data-section chunks can DMA without bouncing.
    """
    header: Dict[str, dict] = {}
    if metadata:
        header["__metadata__"] = {str(k): str(v) for k, v in metadata.items()}
    pos = 0
    for name, arr in tensors.items():
        arr = np.asarray(arr)
        dt = str(arr.dtype)
        if dt not in _DTYPES_INV:
            raise TypeError(f"unsupported dtype {dt}")
        header[name] = {
            "dtype": _DTYPES_INV[dt],
            "shape": list(arr.shape),
            "data_offsets": [pos, pos + arr.nbytes],
        }
        pos += arr.nbytes
    hjson = json.dumps(header, separators=(",", ":")).encode()
    pad = (-(8 + len(hjson))) % max(align, 8)
    hjson += b" " * pad
    head = struct.pack("<Q", len(hjson)) + hjson
    offsets = {name: len(head) + info["data_offsets"][0]
               for name, info in header.items() if name != "__metadata__"}
    return head, offsets


def write_safetensors(path, tensors: Dict[str, np.ndarray],
                      metadata: Optional[dict] = None) -> None:
    """Minimal safetensors writer (row-major, offsets in insertion order).
    Stamps per-tensor CRC32C in ``__metadata__`` (spec-legal; readers
    that ignore metadata are unaffected)."""
    md = dict(metadata or {})
    md.update(_checksum_metadata(tensors))
    head, _ = build_header(tensors, md)
    with open(path, "wb") as f:
        f.write(head)
        for arr in tensors.values():
            # NOT ascontiguousarray: it promotes 0-d arrays to shape (1,).
            f.write(np.asarray(arr).tobytes())


def _aligned_scratch(nbytes: int, align: int) -> np.ndarray:
    """A numpy uint8 view whose data pointer is ``align``-aligned."""
    raw = np.empty(nbytes + align, np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + nbytes]


def write_safetensors_engine(path, tensors: Dict[str, np.ndarray], engine,
                             metadata: Optional[dict] = None) -> None:
    """safetensors writer over the engine's O_DIRECT write path — the
    HBM→NVMe inverse of the DMA read path (SURVEY.md §5 "Checkpoint/
    resume").  One file handle for the whole file, ``queue_depth``
    pipelined writes in flight (a many-leaf optimizer pytree is one
    open/close, not one per tensor).

    Alignment: O_DIRECT needs source pointer, file offset, and length
    all alignment-conformant, which tensor boundaries never are.  The
    header is padded so the data section starts aligned (trailing JSON
    spaces — spec-legal), and the data section streams as full aligned
    chunks copied into rotating aligned scratch buffers (ONE host copy,
    honestly counted as bounce — it replaces the engine's internal
    staging memcpy, which counted the same) that DMA straight to the
    device: no kernel page-cache copy, no writeback debt, bytes durable
    at completion.  Only the final partial chunk takes the buffered
    path.  The file stays 100% standard safetensors.

    Every tensor is CRC32C-stamped in ``__metadata__`` at write time
    (one extra host pass at native CRC speed — the write half of the
    end-to-end integrity story; the read half is ``STROM_VERIFY``)."""
    align = engine.config.alignment
    md = dict(metadata or {})
    md.update(_checksum_metadata(tensors))
    head, _ = build_header(tensors, md, align=align)
    open(path, "wb").close()  # truncate any previous file
    fh = engine.open(path, writable=True)
    # Direct streaming is safe only when alignment is a whole number of
    # kernel pages: header/tail ride the page cache while chunks DMA, and
    # if a buffered span shared a PAGE with an in-flight direct chunk,
    # the page's read-modify-write + later writeback could flush stale
    # bytes over the DMA'd data.  Page-multiple alignment makes the two
    # families page-disjoint by construction.
    page = os.sysconf("SC_PAGESIZE")
    direct_ok = engine.file_is_direct(fh) and align % page == 0
    chunk = engine.config.chunk_bytes
    depth = engine.config.queue_depth
    pend: list = []  # (PendingWrite, scratch_idx or None)

    # rotating aligned scratches; a scratch is reusable once its write
    # completed (wait() below strictly precedes reuse).  Count capped by
    # the engine's own buffer pool so host scratch memory is bounded the
    # same way the staging pool is (depth alone may be configured large).
    n_scratch = max(2, min(depth, engine.n_buffers))
    scratches = [None] * n_scratch
    free_idx = list(range(n_scratch))

    def drain_one():
        p, sidx = pend.pop(0)
        p.wait()
        if sidx is not None:
            free_idx.append(sidx)

    def body_bytes():
        """The data section as a flat byte stream, tensor order."""
        for arr in tensors.values():
            yield np.ascontiguousarray(
                np.asarray(arr)).view(np.uint8).reshape(-1)

    try:
        pend.append((engine.submit_write(
            fh, 0, np.frombuffer(head, np.uint8)), None))

        data_start = len(head)               # aligned by construction
        total = sum(int(np.asarray(a).nbytes) for a in tensors.values())
        # n_full aligned chunks stream direct; 0 on a buffered fs (the
        # tail path below then carries the whole data section)
        n_full = total // chunk if direct_ok else 0
        # fill aligned chunk-sized scratches from the tensor stream
        stream = body_bytes()
        cur = next(stream, np.empty(0, np.uint8))
        cur_pos = 0
        for ci in range(n_full):
            while not free_idx:
                drain_one()
            sidx = free_idx.pop()
            if scratches[sidx] is None:
                scratches[sidx] = _aligned_scratch(chunk, align)
            buf = scratches[sidx]
            filled = 0
            while filled < chunk:
                if cur_pos >= cur.nbytes:
                    cur = next(stream)
                    cur_pos = 0
                n = min(chunk - filled, cur.nbytes - cur_pos)
                buf[filled:filled + n] = cur[cur_pos:cur_pos + n]
                filled += n
                cur_pos += n
            engine.stats.add(bounce_bytes=chunk)   # the one host copy
            pend.append((engine.submit_write(
                fh, data_start + ci * chunk, buf), sidx))
        # tail: remaining bytes (unaligned length) via the normal path
        tail_off = data_start + n_full * chunk
        tail_parts = []
        if cur_pos < cur.nbytes:
            tail_parts.append(cur[cur_pos:])
        tail_parts.extend(stream)
        pos = tail_off
        for part in tail_parts:
            for p0 in range(0, part.nbytes, chunk):
                pend.append((engine.submit_write(
                    fh, pos, part[p0:p0 + chunk]), None))
                pos += min(chunk, part.nbytes - p0)
                if len(pend) >= depth:
                    drain_one()
        while pend:
            drain_one()
    finally:
        # Drain before close: in-flight writes target this fh.
        for p, _ in pend:
            try:
                p.wait()
            except OSError:
                pass
        engine.close(fh)
    # Direct chunks are durable at completion, but the header/tail (and,
    # on fs without O_DIRECT, everything) rode the page cache —
    # fdatasync closes that gap so callers' commit markers/renames can
    # rely on "writer returned ⇒ bytes on disk".  fdatasync, not fsync:
    # it flushes the data and the size metadata needed to retrieve it
    # (this file is freshly created) but skips the mtime-only inode
    # write — each sync here costs a full device FLUSH (~70 ms on a
    # virtio disk), the dominant term of a small checkpoint save.
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fdatasync(fd)
    finally:
        os.close(fd)
