"""TFRecord: index scan + ranged-read planning + writer.

Wire format per record: ``u64le length | u32le masked_crc32c(length) |
payload | u32le masked_crc32c(payload)``.  Indexing scans only the 16-byte
framing per record (one buffered sequential pass, the analogue of the
reference's extent walk); payloads are then planned as direct-engine ranges.
Backs benchmark config 3 (BASELINE.md: ImageNet-1k WebDataset/TFRecord
shards → infeed dataloader).

crc32c (Castagnoli) is implemented here with a numpy table — no external
dependency; verification is optional on the hot path (``verify=True`` reads
payloads through buffered I/O and is for integrity checks, not streaming).
"""

from __future__ import annotations

import struct
from typing import Iterator, Optional

import numpy as np

from nvme_strom_tpu.formats.base import PlanEntry, ReadPlan

# ---- crc32c: native (SSE4.2 / slice-by-8 in libstrom_io), python fallback

_POLY = 0x82F63B78


def _make_table() -> list:
    tbl = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        tbl.append(c)
    return tbl


_TABLE = _make_table()


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    c = ~crc & 0xFFFFFFFF
    tbl = _TABLE
    for b in data:
        c = tbl[(c ^ b) & 0xFF] ^ (c >> 8)
    return ~c & 0xFFFFFFFF


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C via the stack's single binding owner (utils/checksum —
    binding the same CDLL symbol here too would race it for the cached
    function object's ``argtypes``); falls back to the pure-Python
    table when the native library is unavailable."""
    from nvme_strom_tpu.utils.checksum import crc32c as _impl
    return _impl(data, crc)


def masked_crc(data: bytes) -> int:
    c = crc32c(data)
    return ((c >> 15 | c << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---- reader ----

class TFRecordIndex:
    """Offsets/lengths of every record in a TFRecord file."""

    def __init__(self, path, verify_framing_crc: bool = False):
        import os
        self.path = str(path)
        self.offsets: list[int] = []   # payload offsets
        self.lengths: list[int] = []
        fsize = os.path.getsize(self.path)
        with open(self.path, "rb") as f:
            if f.read(2) == b"\x1f\x8b":
                raise ValueError(
                    f"{self.path}: gzip-compressed TFRecord — a "
                    "compressed stream has no random access, so the "
                    "direct-read path cannot serve it; decompress the "
                    "shards at prep time (zcat) or use uncompressed "
                    "TFRecords")
        pos = 0
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(12)
                if not hdr:
                    break
                if len(hdr) < 12:
                    raise ValueError(f"truncated framing at {pos}")
                (ln,), (lcrc,) = struct.unpack("<Q", hdr[:8]), \
                    struct.unpack("<I", hdr[8:])
                if verify_framing_crc and masked_crc(hdr[:8]) != lcrc:
                    raise ValueError(f"length crc mismatch at {pos}")
                if pos + 12 + ln + 4 > fsize:
                    raise ValueError(
                        f"record at {pos} claims {ln} payload bytes but the "
                        f"file ends at {fsize}: truncated or corrupt shard")
                self.offsets.append(pos + 12)
                self.lengths.append(ln)
                pos += 12 + ln + 4
                f.seek(pos)

    def __len__(self) -> int:
        return len(self.offsets)

    def plan(self, indices: Optional[list] = None) -> ReadPlan:
        idx = indices if indices is not None else range(len(self))
        entries = tuple(
            PlanEntry(key=str(i), offset=self.offsets[i],
                      length=self.lengths[i])
            for i in idx)
        return ReadPlan(self.path, entries)


def read_records(path, verify: bool = True) -> Iterator[bytes]:
    """Buffered full read with CRC verification — the integrity-check path
    (mirrors the reference's ssd2gpu_test pread comparison, SURVEY.md §4)."""
    with open(path, "rb") as f:
        pos = 0
        while True:
            hdr = f.read(12)
            if not hdr:
                return
            (ln,) = struct.unpack("<Q", hdr[:8])
            payload = f.read(ln)
            (pcrc,) = struct.unpack("<I", f.read(4))
            if verify:
                (lcrc,) = struct.unpack("<I", hdr[8:])
                if masked_crc(hdr[:8]) != lcrc:
                    raise ValueError(f"length crc mismatch at {pos}")
                if masked_crc(payload) != pcrc:
                    raise ValueError(f"payload crc mismatch at {pos}")
            pos += 12 + ln + 4
            yield payload


def write_tfrecords(path, payloads) -> None:
    with open(path, "wb") as f:
        for p in payloads:
            p = bytes(p)
            hdr = struct.pack("<Q", len(p))
            f.write(hdr)
            f.write(struct.pack("<I", masked_crc(hdr)))
            f.write(p)
            f.write(struct.pack("<I", masked_crc(p)))
