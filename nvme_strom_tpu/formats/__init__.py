from nvme_strom_tpu.formats.base import PlanEntry, ReadPlan
from nvme_strom_tpu.formats.safetensors import (
    SafetensorsFile,
    write_safetensors,
)
from nvme_strom_tpu.formats.tfrecord import (
    TFRecordIndex,
    read_records,
    write_tfrecords,
    crc32c,
    masked_crc,
)
from nvme_strom_tpu.formats.wds import WdsShardIndex, write_wds_shard
from nvme_strom_tpu.formats.arrow import ArrowFileReader
from nvme_strom_tpu.formats.npy import (plan_npy, plan_npz,
                                        read_npy_to_device,
                                        read_npz_to_device)

__all__ = [
    "PlanEntry", "ReadPlan",
    "SafetensorsFile", "write_safetensors",
    "TFRecordIndex", "read_records", "write_tfrecords", "crc32c",
    "masked_crc",
    "WdsShardIndex", "write_wds_shard",
    "ArrowFileReader",
    "plan_npy", "plan_npz", "read_npy_to_device", "read_npz_to_device",
]
