"""Read-plan primitives shared by all format readers.

The reference resolves file offsets → filesystem extents → NVMe LBAs inside
the kernel (SURVEY.md §3.1 "walk filesystem extents").  Userspace cannot (and
need not) see LBAs; the equivalent planning step here is format-aware: each
reader turns a file's metadata into a list of payload byte ranges which are
then read O_DIRECT through the engine and land on device with no host copy.
Metadata itself (headers, footers, indexes) is tiny and read with ordinary
buffered I/O — it is not payload and is never counted as such.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence


@dataclass(frozen=True)
class PlanEntry:
    """One contiguous payload range inside a file."""

    key: str                 # tensor name / record id / sample.ext / column
    offset: int              # absolute file offset
    length: int              # bytes
    dtype: Optional[str] = None   # numpy-style dtype string when known
    shape: Optional[tuple] = None
    meta: Any = None         # format-specific extras


@dataclass(frozen=True)
class ReadPlan:
    path: str
    entries: tuple

    @property
    def total_bytes(self) -> int:
        return sum(e.length for e in self.entries)

    def ranges(self) -> list:
        return [(e.offset, e.length) for e in self.entries]

    def subset(self, keys: Sequence[str]) -> "ReadPlan":
        keep = set(keys)
        entries = tuple(e for e in self.entries if e.key in keep)
        missing = keep - {e.key for e in entries}
        if missing:
            raise KeyError(f"keys not in plan: {sorted(missing)}")
        return ReadPlan(self.path, entries)
