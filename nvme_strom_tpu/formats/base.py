"""Read-plan primitives shared by all format readers.

The reference resolves file offsets → filesystem extents → NVMe LBAs inside
the kernel (SURVEY.md §3.1 "walk filesystem extents").  Userspace cannot (and
need not) see LBAs; the equivalent planning step here is format-aware: each
reader turns a file's metadata into a list of payload byte ranges which are
then read O_DIRECT through the engine and land on device with no host copy.
Metadata itself (headers, footers, indexes) is tiny and read with ordinary
buffered I/O — it is not payload and is never counted as such.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional, Sequence


def pread_nopollute(path: str, length: int, offset: int = 0,
                    fd: int | None = None) -> bytes:
    """Read header/footer bytes WITHOUT page-cache pollution.

    A plain ``open().read()``'s readahead faults ~128 KiB resident per
    call, and any fully-resident span makes the engine's submit-time
    mincore planner deliberately choose the buffered path for the
    payload reads that follow — one metadata parse silently demoting
    the O_DIRECT pipeline to memcpy (a cold wds_raw epoch measured
    100% fallback+bounce from exactly this; a safetensors checkpoint's
    many small early tensors are the same exposure).  FADV_RANDOM
    suppresses readahead and the touched pages are dropped after;
    best-effort on filesystems without fadvise.

    ``fd`` reuses an already-open descriptor (a reader parsing several
    metadata spans of one file should open once).

    The DONTNEED span rounds OUT to page boundaries on both sides: the
    kernel drops only pages wholly inside the advised range, so ending
    at ``offset+length`` would silently keep the final partial page
    resident — the exact defect this helper exists to prevent
    (verified with mincore)."""
    close = fd is None
    if fd is None:
        fd = os.open(path, os.O_RDONLY)
    try:
        try:
            os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_RANDOM)
        except (OSError, AttributeError):
            pass
        out = os.pread(fd, length, offset)
        try:
            lo = offset & ~4095
            hi = (offset + len(out) + 4095) & ~4095
            os.posix_fadvise(fd, lo, hi - lo, os.POSIX_FADV_DONTNEED)
        except (OSError, AttributeError):
            pass
        return out
    finally:
        if close:
            os.close(fd)


@dataclass(frozen=True)
class PlanEntry:
    """One contiguous payload range inside a file."""

    key: str                 # tensor name / record id / sample.ext / column
    offset: int              # absolute file offset
    length: int              # bytes
    dtype: Optional[str] = None   # numpy-style dtype string when known
    shape: Optional[tuple] = None
    meta: Any = None         # format-specific extras


@dataclass(frozen=True)
class ReadPlan:
    path: str
    entries: tuple

    @property
    def total_bytes(self) -> int:
        return sum(e.length for e in self.entries)

    def ranges(self) -> list:
        return [(e.offset, e.length) for e in self.entries]

    def subset(self, keys: Sequence[str]) -> "ReadPlan":
        keep = set(keys)
        entries = tuple(e for e in self.entries if e.key in keep)
        missing = keep - {e.key for e in entries}
        if missing:
            raise KeyError(f"keys not in plan: {sorted(missing)}")
        return ReadPlan(self.path, entries)
