"""Fixed-size-record shard format — the zero-bounce loader fast path.

WebDataset/TFRecord interleave per-record headers with payloads, so a
batch of samples is never one contiguous byte range and the loader must
touch every payload on the host (SURVEY.md §3.5's "payload never touched
by host" is unreachable).  This format is the TPU-first fix, following
the high-throughput-loader lineage (ArrayRecord, ffcv): records of ONE
fixed byte size packed back-to-back, with a tiny JSON footer — so any
batch of records is a single contiguous file span that the engine can
O_DIRECT straight into a staging buffer and PJRT can transfer without a
host-side copy (VERDICT round 1 #2).

Layout:

    [record 0][record 1]…[record n-1][json meta][8B LE meta len][SFR1]

The footer is read with ordinary buffered I/O (it is tens of bytes and
read once); payload reads go through the engine.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Iterable, Union

import numpy as np

MAGIC = b"SFR1"
_TAIL = struct.Struct("<Q4s")   # meta length + magic


def write_fixedrec(path: Union[str, os.PathLike],
                   records: Union[np.ndarray, Iterable[bytes]],
                   dtype=None, shape=None,
                   checksums: bool = False) -> int:
    """Write records to ``path``; returns the record count.

    ``records`` is either an (n, *shape) array (dtype/shape recorded so
    batches decode as arrays with no further parsing) or an iterable of
    equal-length bytes objects (recorded as uint8 vectors).

    ``checksums=True`` also stamps a per-record CRC32C sidecar
    (``<path>.crc.json``) — the zero-copy read path never touches
    payload bytes on the host, so fixedrec integrity is verified
    offline by ``strom-scrub`` against exactly this sidecar.
    """
    if isinstance(records, np.ndarray):
        if records.ndim < 1:
            raise ValueError("records array must have a leading dim")
        dtype = records.dtype
        shape = records.shape[1:]
        # memoryview streams straight from the array — no tobytes()
        # doubling of a multi-GB shard's memory
        payload = [memoryview(np.ascontiguousarray(records)).cast("B")]
        count = records.shape[0]
        rec_bytes = records.dtype.itemsize * int(
            np.prod(shape, dtype=np.int64)) if shape else \
            records.dtype.itemsize
    else:
        payload = [memoryview(r) for r in records]
        if not payload:
            raise ValueError("no records")
        rec_bytes = payload[0].nbytes
        if any(r.nbytes != rec_bytes for r in payload):
            raise ValueError("records must be one fixed size")
        count = len(payload)
        if dtype is None:
            dtype, shape = np.dtype(np.uint8), (rec_bytes,)
    meta = json.dumps({
        "record_bytes": rec_bytes, "count": count,
        "dtype": np.dtype(dtype).str,
        "shape": list(shape if shape is not None else (rec_bytes,)),
    }).encode()
    # temp + atomic rename: a concurrent reader (multi-host shard setup
    # — one process writes, peers poll for the file) must never see a
    # half-written shard; the footer-last layout alone can't guarantee
    # that since exists+size checks pass mid-write
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        for p in payload:
            f.write(p)
        f.write(meta)
        f.write(_TAIL.pack(len(meta), MAGIC))
        f.flush()
        os.fsync(f.fileno())
    # a previous writer's sidecar must never pair with the NEW bytes
    # (stale stamps would "verify" them against the OLD contents and
    # quarantine a healthy shard), including the crash window between
    # the rename below and a checksums=True restamp — drop it BEFORE
    # publishing; unstamped merely skips verification
    from nvme_strom_tpu.utils.checksum import sidecar_path
    try:
        os.unlink(sidecar_path(path))
    except OSError:
        pass
    os.replace(tmp, path)
    if checksums:
        # stamp from the in-memory payload — re-reading a multi-GB
        # shard just written would double its I/O (utils.checksum's
        # stamp_fixedrec exists for after-the-fact stamping of shards
        # written elsewhere)
        from nvme_strom_tpu.utils.checksum import write_sidecar
        flat = payload[0] if len(payload) == 1 else None

        def spans():
            if flat is not None:        # one contiguous array
                for i in range(count):
                    yield (i * rec_bytes, rec_bytes,
                           flat[i * rec_bytes:(i + 1) * rec_bytes])
            else:
                for i, p in enumerate(payload):
                    yield i * rec_bytes, rec_bytes, p

        write_sidecar(path, spans())
    return count


class FixedRecIndex:
    """Footer parse of one fixedrec shard: record size/count/dtype/shape.
    ``span(i, n)`` → the (offset, length) of records [i, i+n) — always
    one contiguous range, the whole point of the format."""

    def __init__(self, path: Union[str, os.PathLike]):
        self.path = str(path)
        with open(self.path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size < _TAIL.size:
                raise ValueError(f"{self.path}: not a fixedrec file")
            f.seek(size - _TAIL.size)
            meta_len, magic = _TAIL.unpack(f.read(_TAIL.size))
            if magic != MAGIC:
                raise ValueError(f"{self.path}: bad magic {magic!r}")
            f.seek(size - _TAIL.size - meta_len)
            meta = json.loads(f.read(meta_len))
        self.record_bytes = int(meta["record_bytes"])
        self.count = int(meta["count"])
        self.dtype = np.dtype(meta["dtype"])
        self.shape = tuple(meta["shape"])
        if self.record_bytes * self.count > size - _TAIL.size - meta_len:
            raise ValueError(f"{self.path}: truncated payload")

    def span(self, i: int, n: int) -> tuple[int, int]:
        if i < 0 or i + n > self.count:
            raise IndexError(f"records [{i},{i + n}) out of "
                             f"[0,{self.count})")
        return i * self.record_bytes, n * self.record_bytes

    def __len__(self) -> int:
        return self.count
