"""Device-side prefetch: keep N batches ahead of the training step.

The loader already overlaps NVMe reads and host batch assembly in a
producer thread; this last stage pulls ahead of the consumer.  For
pipelines that already yield jax Arrays (ShardedLoader) the effect is
dispatch-ahead: placements for batch k+1..k+size are issued while step k
computes.  For host-array pipelines pass ``device=`` (or a Sharding) and
non-jax leaves are explicitly ``device_put`` on pull — without it the
wrapper is lookahead only and moves no bytes itself.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Optional


def prefetch_to_device(batches: Iterable, size: int = 2,
                       device=None) -> Iterator:
    """Yield from ``batches`` while keeping ``size`` items pulled ahead,
    optionally device_put-ing each batch's non-Array leaves to
    ``device``.  Validates eagerly (plain function returning a
    generator); closing the returned generator closes the wrapped
    iterator too, so upstream producer threads wind down deterministically
    (examples/train_lm.py relies on this before engine teardown)."""
    if size < 1:
        raise ValueError("size must be >= 1")
    it = iter(batches)
    return _prefetch_gen(it, size, device)


def _prefetch_gen(it, size: int, device) -> Iterator:
    def pull():
        b = next(it)
        if device is None:
            return b
        import jax
        return jax.tree.map(
            lambda x: x if isinstance(x, jax.Array)
            else jax.device_put(x, device), b)

    buf: collections.deque = collections.deque()
    try:
        try:
            for _ in range(size):
                buf.append(pull())
        except StopIteration:
            pass
        while buf:
            out = buf.popleft()
            try:
                buf.append(pull())
            except StopIteration:
                pass
            yield out
    finally:
        close = getattr(it, "close", None)
        if close is not None:
            close()
