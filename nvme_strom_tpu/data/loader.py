"""Sharded dataloader: local-NVMe shards → globally-sharded device batches.

The consumer-facing equivalent of the reference's PG-Strom integration
(SURVEY.md §3.5): where PG-Strom pulls table blocks through the DMA ioctls
into GPU scan kernels, this loader pulls WebDataset/TFRecord samples through
the strom-io engine and assembles them into ``jax.Array``s sharded over a
``Mesh`` data axis — benchmark config 3 (BASELINE.md).

Pipeline per batch (prefetched in a background thread):

    index shard (headers only) → planned payload ranges → engine direct
    reads → decode (user fn; raw view for fixed-size records) → host batch
    → make_array_from_process_local_data → global device array

Every process touches only its own shards (data/sharding.py); the global
array is assembled without bulk cross-host traffic.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from nvme_strom_tpu.data.sharding import assign_shards, shuffled_indices
from nvme_strom_tpu.formats.tfrecord import TFRecordIndex
from nvme_strom_tpu.formats.wds import WdsShardIndex
from nvme_strom_tpu.io.engine import StromEngine
from nvme_strom_tpu.parallel.mesh import batch_sharding
from nvme_strom_tpu.utils.config import EngineConfig, LoaderConfig

_SENTINEL = object()


def _process_span(sharding, global_shape, dim: int, proc: int):
    """Contiguous [lo, hi) index range this process's addressable devices
    cover along ``dim`` of the global array.

    The sp mesh axis may span processes (multi-host long context); each
    process must then hand make_array_from_process_local_data only its
    own sequence slice.  Raises if the process's shards are
    non-contiguous along ``dim`` (an sp axis interleaved across hosts —
    a mesh layout the loader does not support)."""
    spans = set()
    size = global_shape[dim]
    for d, idx in sharding.devices_indices_map(tuple(global_shape)).items():
        if d.process_index != proc:
            continue
        sl = idx[dim]
        spans.add((sl.start or 0,
                   size if sl.stop is None else sl.stop))
    lo = min(s for s, _ in spans)
    hi = max(e for _, e in spans)
    covered = sorted(spans)
    # contiguity: the union of spans must tile [lo, hi) without holes
    reach = lo
    for s, e in covered:
        if s > reach:
            raise ValueError(
                f"process {proc} holds non-contiguous spans {covered} "
                f"along dim {dim}; lay out the mesh so the seq axis is "
                "contiguous per process")
        reach = max(reach, e)
    return lo, hi


def _default_decode(parts: dict) -> np.ndarray:
    """Single-part raw samples → uint8 array (copy: counted by caller)."""
    if len(parts) != 1:
        raise ValueError(
            f"sample has parts {sorted(parts)}; pass decode= to combine")
    (payload,) = parts.values()
    return np.frombuffer(payload, dtype=np.uint8)


class ShardedLoader:
    """Iterate globally-sharded batches from per-host local shards.

    Args:
      shard_paths: ALL shard files of the dataset (same list on all hosts).
      mesh: jax Mesh; batches are sharded over `axis` (default "dp").
      global_batch: global batch size (divided across processes).
      fmt: "wds" or "tfrecord".
      decode: fn(parts: dict[ext, bytes]) -> np.ndarray | dict of arrays.
        For tfrecord, parts is {"": payload}.
      engine: shared StromEngine (one is created if omitted).
      exts: for wds, restrict to these extensions.
      seq_axis: also shard dim 1 of every RANK-2 batch leaf — (batch,
        seq) token arrays — over this mesh axis: the input layout for
        ring/Ulysses sequence parallelism.  Leaves of any other rank
        (per-sample scalars, images, ...) keep the batch-only sharding;
        a rank-2 leaf whose dim 1 the axis cannot divide raises.
    """

    def __init__(self, shard_paths: Sequence, mesh, global_batch: int,
                 fmt: str = "wds",
                 decode: Optional[Callable] = None,
                 engine: Optional[StromEngine] = None,
                 exts: Optional[List[str]] = None,
                 config: Optional[LoaderConfig] = None,
                 axis: str = "dp",
                 seq_axis: Optional[str] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        import jax
        if fmt not in ("wds", "tfrecord"):
            raise ValueError(f"unknown fmt {fmt!r}")
        self.mesh = mesh
        self.axis = axis
        self.seq_axis = seq_axis
        batch_sharding(mesh, axis, seq_axis)   # validate axes early
        self.fmt = fmt
        self.decode = decode or _default_decode
        self.exts = exts
        self.config = config or LoaderConfig(batch_size=global_batch)
        self.global_batch = global_batch
        pi = jax.process_index() if process_index is None else process_index
        pc = jax.process_count() if process_count is None else process_count
        if global_batch % pc:
            raise ValueError(
                f"global_batch {global_batch} not divisible by "
                f"{pc} processes")
        if global_batch % mesh.shape[axis]:
            raise ValueError(
                f"global_batch {global_batch} not divisible by mesh axis "
                f"{axis}={mesh.shape[axis]}")
        self.local_batch = global_batch // pc
        self.local_shards = assign_shards(shard_paths, pi, pc)
        self._engine = engine or StromEngine(EngineConfig())
        self._owns_engine = engine is None
        self.epoch = 0

    # -- sample iteration (host side) -------------------------------------

    def _index_shard(self, path):
        if self.fmt == "wds":
            idx = WdsShardIndex(path)
            return [
                {ext: rng for ext, rng in idx.samples[k].items()
                 if self.exts is None or ext in self.exts}
                for k in idx.order
            ]
        idx = TFRecordIndex(path)
        return [{"": (idx.offsets[i], idx.lengths[i])}
                for i in range(len(idx))]

    def _iter_local_samples(self) -> Iterator[np.ndarray]:
        eng = self._engine
        order = list(self.local_shards)
        if self.config.shuffle_buffer:
            perm = shuffled_indices(len(order), self.config.seed, self.epoch)
            order = [order[i] for i in perm]
        for path in order:
            samples = self._index_shard(path)
            sample_order = range(len(samples))
            if self.config.shuffle_buffer:
                sample_order = shuffled_indices(
                    len(samples), self.config.seed + 1, self.epoch)
            fh = eng.open(path)
            pend: list = []
            try:
                depth = max(2, eng.config.queue_depth // 2)

                def finish(entry):
                    idx_parts, reads = entry
                    parts = {}
                    for ext, p in reads.items():
                        view = p.wait()
                        parts[ext] = view.tobytes()  # host copy for decode
                        p.release()
                    eng.stats.add(bounce_bytes=sum(
                        len(v) for v in parts.values()))
                    return self.decode(parts)

                for si in sample_order:
                    reads = {
                        ext: eng.submit_read(fh, off, ln)
                        for ext, (off, ln) in samples[si].items()}
                    pend.append((si, reads))
                    if len(pend) >= depth:
                        yield finish(pend.pop(0))
                while pend:
                    yield finish(pend.pop(0))
            finally:
                # Drain before close: in-flight reads DMA into pool buffers
                # and must be waited + released, or the pool leaks and the
                # engine teardown would race the I/O.
                for _, reads in pend:
                    for p in reads.values():
                        p.release()  # waits if still in flight
                eng.close(fh)

    # -- batching + device placement ---------------------------------------

    def _host_batches(self) -> Iterator:
        import jax
        batch: list = []
        for sample in self._iter_local_samples():
            batch.append(sample)
            if len(batch) == self.local_batch:
                yield jax.tree.map(lambda *xs: np.stack(xs), *batch)
                batch = []
        if batch and not self.config.drop_remainder:
            raise ValueError(
                "partial final batch with drop_remainder=False is not "
                "representable as a fixed global shape; pad your dataset "
                "or use drop_remainder=True")

    def __iter__(self) -> Iterator:
        """Yield pytrees of global jax.Arrays sharded over the mesh axis."""
        import jax
        sharding = batch_sharding(self.mesh, self.axis)
        if self.seq_axis is not None:
            # long-context batches: samples over `axis`, the sequence dim
            # over `seq_axis` (ring/Ulysses consume this layout); rank-1
            # leaves (per-sample scalars) keep the batch-only sharding
            seq_sharding = batch_sharding(self.mesh, self.axis,
                                          self.seq_axis)
        q: queue.Queue = queue.Queue(maxsize=self.config.prefetch)
        err: list = []
        stop = threading.Event()

        def put_checked(item) -> bool:
            """Blocking put that aborts when the consumer went away."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            gen = self._host_batches()
            try:
                for hb in gen:
                    if not put_checked(hb):
                        break
            except BaseException as e:  # surfaced in the consumer
                err.append(e)
            finally:
                gen.close()  # runs the sample iterator's drain/close
                put_checked(_SENTINEL)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                hb = q.get()
                if hb is _SENTINEL:
                    if err:
                        raise err[0]
                    break
                global_shape_of = (
                    lambda x: (self.global_batch,) + x.shape[1:])
                span_cache: dict = {}
                def put(x):
                    sh = sharding
                    gshape = global_shape_of(x)
                    # exactly rank 2 == (batch, seq): images and other
                    # higher-rank leaves are NOT sequences — batch-only
                    if self.seq_axis is not None and x.ndim == 2:
                        n_sp = self.mesh.shape[self.seq_axis]
                        if x.shape[1] % n_sp:
                            raise ValueError(
                                f"seq_axis={self.seq_axis!r} (size "
                                f"{n_sp}) cannot shard batch leaf of "
                                f"shape {x.shape}: dim 1 not divisible")
                        sh = seq_sharding
                        # Multi-host sp: each process generated the FULL
                        # sequence locally, but make_array_from_process_
                        # local_data wants only this process's addressable
                        # span along dim 1 — slice it out.  The global
                        # shape keeps the full extent; the span depends
                        # only on (sharding, shape) so it is computed once
                        # per leaf shape, not per batch.
                        if gshape not in span_cache:
                            span_cache[gshape] = _process_span(
                                sh, gshape, dim=1,
                                proc=jax.process_index())
                        lo, hi = span_cache[gshape]
                        if (hi - lo) != x.shape[1]:
                            x = x[:, lo:hi]
                    return jax.make_array_from_process_local_data(
                        sh, x, gshape)
                yield jax.tree.map(put, hb)
        finally:
            # Abandoned iterator: unblock and stop the producer, then wait
            # for it — close() must never race a thread still submitting.
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=30)
        self.epoch += 1

    def close(self) -> None:
        if self._owns_engine:
            self._engine.close_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
