"""Sharded dataloader: local-NVMe shards → globally-sharded device batches.

The consumer-facing equivalent of the reference's PG-Strom integration
(SURVEY.md §3.5): where PG-Strom pulls table blocks through the DMA ioctls
into GPU scan kernels, this loader pulls WebDataset/TFRecord samples through
the strom-io engine and assembles them into ``jax.Array``s sharded over a
``Mesh`` data axis — benchmark config 3 (BASELINE.md).

Pipeline per batch (prefetched in a background thread):

    index shard (headers only) → planned payload ranges → engine direct
    reads → decode (user fn; raw view for fixed-size records) → host batch
    → make_array_from_process_local_data → global device array

Every process touches only its own shards (data/sharding.py); the global
array is assembled without bulk cross-host traffic.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from nvme_strom_tpu.data.sharding import assign_shards, shuffled_indices
from nvme_strom_tpu.formats.tfrecord import TFRecordIndex
from nvme_strom_tpu.formats.wds import WdsShardIndex
from nvme_strom_tpu.io.engine import StromEngine, wait_exact
from nvme_strom_tpu.io.plan import plan_and_submit
from nvme_strom_tpu.parallel.mesh import batch_sharding
from nvme_strom_tpu.utils.config import EngineConfig, LoaderConfig
from nvme_strom_tpu.utils.tuning import tuned_chunk_bytes

_SENTINEL = object()
_log = logging.getLogger(__name__)


class ShardReadError(RuntimeError):
    """A shard failed (index/read/decode) and could not be quarantined.

    Always names the originating shard (``path``); the underlying
    exception rides along as ``__cause__``."""

    def __init__(self, path: str, exc: BaseException, detail: str = ""):
        self.path = str(path)
        super().__init__(
            f"shard {self.path}: {type(exc).__name__}: {exc}"
            + (f" ({detail})" if detail else ""))


class LoaderErrors(RuntimeError):
    """Several producer-side errors queued before the consumer saw any.

    3.10-compatible stand-in for ExceptionGroup: every queued error is
    in ``errors`` (oldest first) and in the message; the first is also
    the ``__cause__`` chain root."""

    def __init__(self, errors):
        self.errors = list(errors)
        super().__init__(
            f"{len(self.errors)} loader errors: "
            + "; ".join(f"{type(e).__name__}: {e}" for e in self.errors))


def _process_span(sharding, global_shape, dim: int, proc: int):
    """Contiguous [lo, hi) index range this process's addressable devices
    cover along ``dim`` of the global array.

    The sp mesh axis may span processes (multi-host long context); each
    process must then hand make_array_from_process_local_data only its
    own sequence slice.  Raises if the process's shards are
    non-contiguous along ``dim`` (an sp axis interleaved across hosts —
    a mesh layout the loader does not support)."""
    spans = set()
    size = global_shape[dim]
    for d, idx in sharding.devices_indices_map(tuple(global_shape)).items():
        if d.process_index != proc:
            continue
        sl = idx[dim]
        spans.add((sl.start or 0,
                   size if sl.stop is None else sl.stop))
    lo = min(s for s, _ in spans)
    hi = max(e for _, e in spans)
    covered = sorted(spans)
    # contiguity: the union of spans must tile [lo, hi) without holes
    reach = lo
    for s, e in covered:
        if s > reach:
            raise ValueError(
                f"process {proc} holds non-contiguous spans {covered} "
                f"along dim {dim}; lay out the mesh so the seq axis is "
                "contiguous per process")
        reach = max(reach, e)
    return lo, hi


def _group_blocks(blocks: dict, n_blk: int, pi: int,
                  axis: str) -> tuple:
    """Validate and index the process→batch-block map.

    ``blocks`` maps process_index → set of batch-axis block starts that
    process's devices cover.  Groups must partition the blocks into
    equal tiles: overlapping or unequal coverage would assign disjoint
    shard lists to processes that feed the SAME global rows (silent
    data corruption), or break local_batch = global/n_groups."""
    groups = sorted({frozenset(b) for b in blocks.values()}, key=min)
    all_blocks = [b for g in groups for b in g]
    if (len(all_blocks) != len(set(all_blocks))
            or set(all_blocks) != set(range(n_blk))
            or len({len(g) for g in groups}) != 1):
        raise ValueError(
            f"batch axis {axis!r}: process groups do not tile the "
            f"axis blocks equally ({[sorted(g) for g in groups]}) — "
            "unsupported mesh layout")
    return groups.index(frozenset(blocks[pi])), len(groups)


def _settle(arrays) -> None:
    """Best-effort block on dispatched device transfers before their
    staging is released (the release-after-ready rule's error path):
    a failed batch may have younger puts still reading the buffers."""
    for a in arrays:
        try:
            a.block_until_ready()
        except Exception:
            pass


def _default_decode(parts: dict) -> np.ndarray:
    """Single-part raw samples → uint8 array (copy: counted by caller)."""
    if len(parts) != 1:
        raise ValueError(
            f"sample has parts {sorted(parts)}; pass decode= to combine")
    (payload,) = parts.values()
    return np.frombuffer(payload, dtype=np.uint8)


class ShardedLoader:
    """Iterate globally-sharded batches from per-host local shards.

    Args:
      shard_paths: ALL shard files of the dataset (same list on all hosts).
      mesh: jax Mesh; batches are sharded over `axis` (default "dp").
      global_batch: global batch size (divided across processes).
      fmt: "wds", "tfrecord", or "fixedrec" (the zero-copy contiguous-
        batch fast path, formats/fixedrec.py — no decode, no seq_axis).
      decode: fn(parts: dict[ext, bytes]) -> np.ndarray | dict of arrays.
        For tfrecord, parts is {"": payload}.
      engine: shared StromEngine (one is created if omitted).
      exts: for wds, restrict to these extensions.
      seq_axis: also shard dim 1 of every RANK-2 batch leaf — (batch,
        seq) token arrays — over this mesh axis: the input layout for
        ring/Ulysses sequence parallelism.  Leaves of any other rank
        (per-sample scalars, images, ...) keep the batch-only sharding;
        a rank-2 leaf whose dim 1 the axis cannot divide raises.
    """

    def __init__(self, shard_paths: Sequence, mesh, global_batch: int,
                 fmt: str = "wds",
                 decode: Optional[Callable] = None,
                 engine: Optional[StromEngine] = None,
                 exts: Optional[List[str]] = None,
                 config: Optional[LoaderConfig] = None,
                 axis: str = "dp",
                 seq_axis: Optional[str] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None):
        import jax
        if fmt not in ("wds", "wds_raw", "tfrecord", "fixedrec"):
            raise ValueError(f"unknown fmt {fmt!r}")
        if fmt in ("fixedrec", "wds_raw"):
            if decode is not None:
                raise ValueError(
                    f"{fmt} is a zero-copy raw path: payload goes "
                    "staging→device untouched; decode on device instead")
            if seq_axis is not None:
                raise ValueError(
                    f"{fmt} cannot seq-shard: a device's seq slice of "
                    "every row is not a contiguous file span")
            if config is not None and config.shard_error_budget > 0:
                raise ValueError(
                    f"{fmt} does not support shard_error_budget: its "
                    "batch spans coalesce across shards, so per-shard "
                    "quarantine isolation does not exist — zero-copy "
                    "paths fail fast (docs/RESILIENCE.md)")
        self.mesh = mesh
        self.axis = axis
        self.seq_axis = seq_axis
        batch_sharding(mesh, axis, seq_axis)   # validate axes early
        self.fmt = fmt
        self.decode = decode or _default_decode
        self.exts = exts
        self.config = config or LoaderConfig(batch_size=global_batch)
        self.global_batch = global_batch
        pi = jax.process_index() if process_index is None else process_index
        pc = jax.process_count() if process_count is None else process_count
        if global_batch % mesh.shape[axis]:
            raise ValueError(
                f"global_batch {global_batch} not divisible by mesh axis "
                f"{axis}={mesh.shape[axis]}")
        # Shard assignment must follow the BATCH-AXIS group, not the
        # process: when seq_axis spans processes (multi-host long
        # context), several processes hold seq slices of the SAME global
        # batch rows — they must read the same shards in the same order,
        # each slicing its own sequence span at assembly time.  With a
        # batch axis that spans processes (the common case) every group
        # is one process and this reduces to plain per-process
        # round-robin.  Explicit process_index/process_count overrides
        # (single-process multi-host simulation in tests) keep the plain
        # behavior — there is no real device→process map to group by.
        if (seq_axis is not None and process_index is None
                and process_count is None and pc > 1):
            group_idx, n_groups = self._batch_groups(mesh, axis, pi)
        else:
            group_idx, n_groups = pi, pc
        if global_batch % n_groups:
            raise ValueError(
                f"global_batch {global_batch} not divisible by "
                f"{n_groups} batch-axis groups")
        self.local_batch = global_batch // n_groups
        self.local_shards = assign_shards(shard_paths, group_idx, n_groups)
        if engine is None:
            from nvme_strom_tpu.io.faults import build_engine
            engine, self._owns_engine = build_engine(EngineConfig()), True
        else:
            self._owns_engine = False
        self._engine = engine
        self.epoch = 0
        #: shards skipped under config.shard_error_budget, in failure
        #: order — public so a training loop can alert on degradation
        self.quarantined: List[str] = []
        self._quarantined_set: set = set()
        # shard files are immutable for the loader's lifetime: index
        # each once, not once per epoch — the per-epoch re-walk was a
        # whole extra pass of I/O per epoch.  LRU-bounded by
        # config.index_cache_samples so web-scale shard lists don't
        # grow host RSS without limit.
        from collections import OrderedDict
        self._shard_index: "OrderedDict[str, list]" = OrderedDict()
        self._shard_index_total = 0    # cached samples, LRU accounting
        # read-side integrity (STROM_VERIFY, utils/checksum.py): sample
        # payloads verify against each shard's offset-keyed .crc.json
        # sidecar when one exists.  A mismatch is treated like a failed
        # read — re-read once, then the shard takes the normal
        # quarantine-or-raise path.  Applies to the per-sample formats
        # (wds, tfrecord); the zero-copy paths (fixedrec, wds_raw) never
        # touch payload bytes on the host, so their integrity lives in
        # the offline scrubber (tools/strom_scrub.py).
        from nvme_strom_tpu.utils.checksum import VerifyPolicy
        self._verify = VerifyPolicy()
        self._sidecars: dict = {}      # shard path → Sidecar | None

    def _sidecar(self, path):
        key = str(path)
        if key not in self._sidecars:
            from nvme_strom_tpu.utils.checksum import load_sidecar
            self._sidecars[key] = load_sidecar(key)
        return self._sidecars[key]

    @staticmethod
    def _batch_groups(mesh, axis: str, pi: int) -> tuple[int, int]:
        """(my group index, group count) where a 'group' is the set of
        processes whose devices cover the same batch-axis blocks.

        sp-peers (processes sharing batch rows, differing only in their
        sequence slice) land in one group; dp-separated processes land in
        different groups.  Block membership comes from the mesh's actual
        device→process map, so any axis order works."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        n_blk = mesh.shape[axis]
        sh = NamedSharding(mesh, P(axis))
        blocks: dict[int, set] = {}
        for d, idx in sh.devices_indices_map((n_blk,)).items():
            blocks.setdefault(d.process_index, set()).add(
                idx[0].start or 0)
        return _group_blocks(blocks, n_blk, pi, axis)

    # -- sample iteration (host side) -------------------------------------

    def _index_shard(self, path):
        key = str(path)
        cached = self._shard_index.get(key)
        if cached is not None:
            self._shard_index.move_to_end(key)
            return cached
        if self.fmt in ("wds", "wds_raw"):
            idx = WdsShardIndex(path)
            out = [
                {ext: rng for ext, rng in idx.samples[k].items()
                 if self.exts is None or ext in self.exts}
                for k in idx.order
            ]
        else:
            idx = TFRecordIndex(path)
            out = [{"": (idx.offsets[i], idx.lengths[i])}
                   for i in range(len(idx))]
            if self.config.drop_index_pollution:
                # the Python record walk faulted the file resident; a
                # resident span flips the engine's residency planner to
                # the buffered path for every record read that follows
                try:
                    fd = os.open(key, os.O_RDONLY)
                    try:
                        os.posix_fadvise(fd, 0, 0,
                                         os.POSIX_FADV_DONTNEED)
                    finally:
                        os.close(fd)
                except (OSError, AttributeError):
                    pass
        cap = self.config.index_cache_samples
        if cap > 0:
            self._shard_index[key] = out
            self._shard_index_total += len(out)
            while (self._shard_index_total > cap
                   and len(self._shard_index) > 1):
                _, old = self._shard_index.popitem(last=False)
                self._shard_index_total -= len(old)
        return out

    def _iter_local_samples(self) -> Iterator[np.ndarray]:
        order = list(self.local_shards)
        if self.config.shuffle_buffer:
            perm = shuffled_indices(len(order), self.config.seed, self.epoch)
            order = [order[i] for i in perm]
        for path in order:
            if str(path) in self._quarantined_set:
                continue   # failed a previous epoch; still out
            try:
                yield from self._shard_samples(path)
            except Exception as e:   # GeneratorExit/KeyboardInterrupt pass
                self._quarantine_or_raise(path, e)

    def _quarantine_or_raise(self, path, e: Exception) -> None:
        """The shard-quarantine policy (docs/RESILIENCE.md): under the
        error budget the failing shard is skipped-and-logged (counted,
        traced, excluded from later epochs); at budget the failure is
        loud and carries the full quarantine list."""
        budget = self.config.shard_error_budget
        if budget <= 0:
            raise ShardReadError(path, e) from e
        if len(self.quarantined) >= budget:
            raise ShardReadError(
                path, e,
                f"shard error budget ({budget}) exhausted; already "
                f"quarantined: {self.quarantined}") from e
        self.quarantined.append(str(path))
        self._quarantined_set.add(str(path))
        self._engine.stats.add(shards_quarantined=1)
        tracer = getattr(self._engine, "tracer", None)
        if tracer is not None and tracer.enabled:
            now = time.monotonic_ns()
            tracer.add_span("strom.loader.quarantine", now, now,
                            category="strom.resilient", shard=str(path),
                            error=f"{type(e).__name__}: {e}")
        _log.warning(
            "quarantining shard %s after %s: %s (%d/%d of error budget "
            "used)", path, type(e).__name__, e, len(self.quarantined),
            budget)

    def _shard_samples(self, path) -> Iterator[np.ndarray]:
        """Index → pipelined reads → decode for ONE shard (the unit the
        quarantine policy skips)."""
        eng = self._engine
        samples = self._index_shard(path)
        sample_order = range(len(samples))
        if self.config.shuffle_buffer:
            sample_order = shuffled_indices(
                len(samples), self.config.seed + 1, self.epoch)
        fh = eng.open(path)
        pend: list = []
        policy = self._verify
        sidecar = self._sidecar(path) if policy.enabled else None
        try:
            depth = max(2, eng.config.queue_depth // 2)

            def verify_part(ext, off, ln, payload: bytes) -> bytes:
                """CRC32C the part against the shard sidecar (when the
                span is stamped and the policy samples it), via the
                shared retry-once protocol (utils/checksum.py): a
                mismatch re-reads once — transient in-flight corruption
                heals, counted — and a persistent one raises
                ChecksumError, which the caller's quarantine-or-raise
                policy treats exactly like any other shard failure."""
                expected = sidecar.lookup(off, ln)
                if expected is None or not policy.want():
                    return payload
                from nvme_strom_tpu.io.hostcache import spoil_span
                return policy.check_with_reread(
                    payload, expected,
                    lambda: eng.read(fh, off, ln).tobytes(),
                    eng.stats,
                    where=f"sample part {ext!r} at [{off}:+{ln}] "
                          f"of {path}",
                    spoil=lambda: spoil_span(eng, fh, off, ln,
                                             eng.stats))

            def finish(entry):
                idx_parts, reads = entry
                parts = {}
                try:
                    for ext, pieces in reads.items():
                        # the index promised the bytes inside the shard:
                        # a short read means truncation — loud
                        # (quarantine-able), never a silently short
                        # training sample
                        parts[ext] = b"".join(
                            wait_exact(p).tobytes()  # host copy, decode
                            for p in pieces)
                        for p in pieces:
                            p.release()
                        if sidecar is not None:
                            off, ln = idx_parts[ext]
                            parts[ext] = verify_part(ext, off, ln,
                                                     parts[ext])
                finally:
                    # a mid-sample failure must hand the sample's OTHER
                    # reads back too — the entry already left pend, so
                    # the outer drain cannot see them (release is
                    # idempotent for the ones that got there)
                    for pieces in reads.values():
                        for p in pieces:
                            p.release()
                eng.stats.add(bounce_bytes=sum(
                    len(v) for v in parts.values()))
                return self.decode(parts)

            for si in sample_order:
                # one planned batch per sample: a sample's members are
                # adjacent tar/record ranges, so they coalesce into
                # fewer, larger reads and submit under ONE doorbell
                items = list(samples[si].items())
                planned = plan_and_submit(
                    eng, [(fh, off, ln) for _, (off, ln) in items],
                    klass="prefetch")
                reads = {ext: pieces
                         for (ext, _), pieces in zip(items, planned)}
                pend.append((samples[si], reads))
                if len(pend) >= depth:
                    yield finish(pend.pop(0))
            while pend:
                yield finish(pend.pop(0))
        finally:
            # Drain before close: in-flight reads DMA into pool buffers
            # and must be waited + released, or the pool leaks and the
            # engine teardown would race the I/O.
            for _, reads in pend:
                for pieces in reads.values():
                    for p in pieces:
                        p.release()  # waits if still in flight
            eng.close(fh)

    # -- batching + device placement ---------------------------------------

    def _host_batches(self) -> Iterator:
        import jax
        batch: list = []
        for sample in self._iter_local_samples():
            batch.append(sample)
            if len(batch) == self.local_batch:
                yield jax.tree.map(lambda *xs: np.stack(xs), *batch)
                batch = []
        if batch and not self.config.drop_remainder:
            raise ValueError(
                "partial final batch with drop_remainder=False is not "
                "representable as a fixed global shape; pad your dataset "
                "or use drop_remainder=True")

    def __iter__(self) -> Iterator:
        """Yield pytrees of global jax.Arrays sharded over the mesh axis."""
        import jax
        if self.fmt == "fixedrec":
            yield from self._iter_fixedrec()
            return
        if self.fmt == "wds_raw":
            yield from self._iter_wds_raw()
            return
        sharding = batch_sharding(self.mesh, self.axis)
        if self.seq_axis is not None:
            # long-context batches: samples over `axis`, the sequence dim
            # over `seq_axis` (ring/Ulysses consume this layout); rank-1
            # leaves (per-sample scalars) keep the batch-only sharding
            seq_sharding = batch_sharding(self.mesh, self.axis,
                                          self.seq_axis)
        q: queue.Queue = queue.Queue(maxsize=self.config.prefetch)
        err: list = []
        stop = threading.Event()

        def put_checked(item) -> bool:
            """Blocking put that aborts when the consumer went away."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            gen = self._host_batches()
            try:
                for hb in gen:
                    if not put_checked(hb):
                        break
            except BaseException as e:  # surfaced in the consumer
                err.append(e)
            finally:
                try:
                    gen.close()  # runs the sample iterator's drain/close
                except BaseException as e:
                    # a drain/close failure is a SECOND error — queue it
                    # too, never shadow (or be shadowed by) the first
                    err.append(e)
                put_checked(_SENTINEL)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                hb = q.get()
                if hb is _SENTINEL:
                    if len(err) == 1:
                        raise err[0]
                    if err:   # every queued error propagates, not just
                        raise LoaderErrors(err) from err[0]   # err[0]
                    break
                global_shape_of = (
                    lambda x: (self.global_batch,) + x.shape[1:])
                span_cache: dict = {}
                def put(x):
                    sh = sharding
                    gshape = global_shape_of(x)
                    # exactly rank 2 == (batch, seq): images and other
                    # higher-rank leaves are NOT sequences — batch-only
                    if self.seq_axis is not None and x.ndim == 2:
                        n_sp = self.mesh.shape[self.seq_axis]
                        if x.shape[1] % n_sp:
                            raise ValueError(
                                f"seq_axis={self.seq_axis!r} (size "
                                f"{n_sp}) cannot shard batch leaf of "
                                f"shape {x.shape}: dim 1 not divisible")
                        sh = seq_sharding
                        # Multi-host sp: each process generated the FULL
                        # sequence locally, but make_array_from_process_
                        # local_data wants only this process's addressable
                        # span along dim 1 — slice it out.  The global
                        # shape keeps the full extent; the span depends
                        # only on (sharding, shape) so it is computed once
                        # per leaf shape, not per batch.
                        if gshape not in span_cache:
                            span_cache[gshape] = _process_span(
                                sh, gshape, dim=1,
                                proc=jax.process_index())
                        lo, hi = span_cache[gshape]
                        if (hi - lo) != x.shape[1]:
                            x = x[:, lo:hi]
                    return jax.make_array_from_process_local_data(
                        sh, x, gshape)
                yield jax.tree.map(put, hb)
        finally:
            # Abandoned iterator: unblock and stop the producer, then wait
            # for it — close() must never race a thread still submitting.
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=30)
        self.epoch += 1

    # -- fixedrec: the zero-copy contiguous-batch fast path -----------------

    def _iter_fixedrec(self) -> Iterator:
        """One epoch of fixedrec batches (VERDICT round 1 #2).

        Per batch, per local device: the device's rows are a CONTIGUOUS
        span of one shard file (split only at shard/buffer boundaries),
        so the plan is engine read → staging view (`.view().reshape()`,
        zero copies) → ``device_put`` of the view → assemble with
        ``make_array_from_single_device_arrays``.  No Python-side byte
        copy exists on the accelerator path; record-level shuffling is
        traded away (shuffle shard order per epoch; randomize record
        order at dataset-prep time, the ffcv/ArrayRecord recipe).

        Multi-host note: every process must hold the same local record
        count (equal shards per process) or epochs desynchronize.
        """
        import jax.numpy as jnp
        from nvme_strom_tpu.formats.fixedrec import FixedRecIndex
        from nvme_strom_tpu.ops.bridge import host_to_device

        eng = self._engine
        sharding = batch_sharding(self.mesh, self.axis)
        order = self._epoch_shard_order()
        idxs = [FixedRecIndex(p) for p in order]
        if not idxs:
            self.epoch += 1
            return
        rec_bytes, dtype = idxs[0].record_bytes, idxs[0].dtype
        rshape = idxs[0].shape
        for ix in idxs[1:]:
            if (ix.record_bytes, ix.dtype, ix.shape) != (rec_bytes, dtype,
                                                         rshape):
                raise ValueError(
                    f"{ix.path}: record layout differs from {idxs[0].path}")
        # split size: the ledger-tuned chunk (planner default), floored
        # to whole records so every piece reshapes cleanly; fall back to
        # the engine's full buffer when a record outgrows the tuned size
        split_src = tuned_chunk_bytes(eng)
        if split_src < rec_bytes:
            split_src = eng.config.chunk_bytes
        max_read = (split_src // rec_bytes) * rec_bytes
        if max_read == 0:
            raise ValueError(
                f"record ({rec_bytes}B) exceeds engine chunk_bytes "
                f"({eng.config.chunk_bytes}B); raise EngineConfig."
                "chunk_bytes")

        gshape = (self.global_batch,) + rshape
        dev_spans, lo = self._device_row_spans(sharding, gshape)

        # local record r lives in shard s at record r - base[s]
        base, total = [], 0
        for ix in idxs:
            base.append(total)
            total += ix.count
        n_batches = self._count_batches(total)

        def row_spans(r0, r1):
            """Local records [r0, r1) → [(shard_i, offset, nbytes), ...]
            contiguous per-shard extents (split only at shard bounds —
            the planner owns the buffer-bound split)."""
            out = []
            si = 0
            while r0 < r1:
                while base[si] + idxs[si].count <= r0:
                    si += 1
                take = min(r1, base[si] + idxs[si].count) - r0
                out.append((si, (r0 - base[si]) * rec_bytes,
                            take * rec_bytes))
                r0 += take
            return out

        def span_pieces(r0, r1) -> int:
            """Worst-case staging pieces the planner produces for these
            rows (per-shard extents never coalesce across files, so the
            per-extent ceil is exact-or-over — safe for pool-fit)."""
            return sum(-(-nb // max_read)
                       for _, _, nb in row_spans(r0, r1))

        fhs = [eng.open(p) for p in order]

        def plan_reads(r0, r1):
            """One planned, vectored submission for the rows: pieces
            stay record-aligned (split_unit=rec_bytes) so each staging
            view reshapes to whole records."""
            exts = [(fhs[si], off, nb)
                    for si, off, nb in row_spans(r0, r1)]
            parts = plan_and_submit(eng, exts, split_unit=rec_bytes,
                                    chunk_bytes=split_src,
                                    klass="prefetch")
            return [p for pieces in parts for p in pieces]

        def to_device(dev, prs):
            parts = []
            try:
                for pr in prs:
                    # the plan never crosses EOF, so a short read ==
                    # truncation; the silent alternative is dropped
                    # records and an opaque shape mismatch at assembly
                    v = wait_exact(pr)
                    n = v.nbytes // rec_bytes
                    parts.append(host_to_device(
                        eng, v.view(dtype).reshape((n,) + rshape), dev))
                return (parts[0] if len(parts) == 1
                        else jnp.concatenate(parts))
            except BaseException:
                # a mid-piece failure leaves younger puts in flight;
                # they must retire before the caller releases staging
                _settle(parts)
                raise

        span_list = sorted({sp for sp in dev_spans.values()})
        batch_pieces = sum(
            span_pieces((g0 - lo), (g1 - lo)) for g0, g1 in span_list)
        yield from self._zero_copy_batches(
            sharding, gshape, dev_spans, lo, n_batches, batch_pieces,
            plan_reads, to_device, fhs)

    # -- shared scaffolding of the zero-copy batch paths --------------------

    def _epoch_shard_order(self) -> List:
        """Per-epoch shard order: shuffled at SHARD granularity only —
        both zero-copy paths trade record-level shuffling away (shuffle
        record order at dataset-prep time, the ffcv/ArrayRecord
        recipe)."""
        order = list(self.local_shards)
        if self.config.shuffle_buffer:
            perm = shuffled_indices(len(order), self.config.seed,
                                    self.epoch)
            order = [order[i] for i in perm]
        return order

    def _count_batches(self, total: int) -> int:
        n_batches = total // self.local_batch
        if total % self.local_batch and not self.config.drop_remainder:
            raise ValueError(
                f"{total} local records do not fill batches of "
                f"{self.local_batch}; pad the dataset or set "
                "drop_remainder=True")
        return n_batches

    def _device_row_spans(self, sharding, gshape):
        """device → its contiguous global row span [g0, g1), plus the
        process's own row base ``lo`` (local record = global row − lo)."""
        import jax
        dev_spans = {}
        for d, idx in sharding.devices_indices_map(gshape).items():
            if d.process_index != jax.process_index():
                continue
            s0 = tuple(idx)[0]
            dev_spans[d] = (0 if s0.start is None else int(s0.start),
                            gshape[0] if s0.stop is None
                            else int(s0.stop))
        lo, hi = _process_span(sharding, gshape, dim=0,
                               proc=jax.process_index())
        if (hi - lo) != self.local_batch:
            raise ValueError(
                f"process rows [{lo},{hi}) != local_batch "
                f"{self.local_batch}")
        return dev_spans, lo

    def _zero_copy_batches(self, sharding, gshape, dev_spans, lo,
                           n_batches, batch_pieces, plan_reads,
                           to_device, fhs) -> Iterator:
        """Prefetch/backpressure engine shared by fixedrec and wds_raw.

        ``plan_reads(r0, r1)`` submits engine reads for local rows
        [r0, r1) and returns them as an arbitrarily nested list with
        PendingReads at the leaves; it is called once per DISTINCT
        device row span per batch (replicas along non-batch mesh axes
        share the reads).  ``to_device(dev, reads)`` turns one device's
        read structure into that device's array (calling ``wait()`` —
        idempotent — on each read).  Rules enforced here:

        - the pool is finite and the engine defers (never errors) reads
          past it; releases happen after transfer, so in-flight pieces
          are bounded by the pool or submission would deadlock;
        - staging buffers release even when a wait/transfer throws;
        - ``config.prefetch`` batches are kept in flight.

        Closes ``fhs`` and bumps the epoch on exit."""
        import jax
        eng = self._engine
        if batch_pieces > eng.n_buffers:
            raise ValueError(
                f"one batch needs {batch_pieces} staging buffers but "
                f"the pool has {eng.n_buffers}; raise EngineConfig."
                "chunk_bytes or lower the batch size")

        def entry_reads(entry):
            reads = {}   # id → PendingRead (replicas share the reads)

            def walk(x):
                if isinstance(x, list):
                    for y in x:
                        walk(y)
                else:
                    reads[id(x)] = x
            for _, rs in entry:
                walk(rs)
            return list(reads.values())

        from nvme_strom_tpu.ops.bridge import StagingRetirePool
        depth = max(1, self.config.prefetch)
        # Deferred staging release (round-4): the per-batch
        # block_until_ready finish() used to pay was one link round
        # trip per batch — the same stop-and-wait disease the round-3
        # verdict called on the SQL scan.  ``held`` counts staging
        # buffers from submission until RETIREMENT (not until yield):
        # the submission-side pressure loops below retire completed
        # transfers first and block on the oldest only when the pool
        # is genuinely full.
        retire = StagingRetirePool(depth)
        held = [0]

        def finish(entry):
            per_dev = []
            reads = entry_reads(entry)
            try:
                for dev, rs in entry:
                    per_dev.append(to_device(dev, rs))
            except BaseException:
                # a failed wait/transfer must still hand every staging
                # buffer of this entry back to the pool — but transfers
                # already dispatched out of it must retire FIRST, or
                # the recycled buffer is overwritten under an in-flight
                # H2D read (the module's release-after-ready rule)
                _settle(per_dev)
                for pr in reads:
                    pr.release()
                held[0] -= len(reads)
                raise

            def release_all():
                for pr in reads:
                    pr.release()
                held[0] -= len(reads)

            retire.push(release_all, per_dev)
            return jax.make_array_from_single_device_arrays(
                gshape, sharding, per_dev)

        # Eager dispatch (window-8 diagnosis): finishing an entry only
        # at yield time meant the consumer's per-batch
        # ``block_until_ready`` had NO younger transfers overlapping it
        # — the link ran stop-and-wait at batch granularity (config 3
        # ledgered 0.35 GiB/s on a 1.44 GiB/s link from exactly this).
        # Two stages now run ahead of the consumer, ``depth`` entries
        # across both: ``pending`` holds planned batches whose engine
        # READS are in flight; a batch whose reads all report ready is
        # promoted (``finish`` — transfers dispatch) into ``ready``,
        # opportunistically so younger reads keep the NVMe queue full
        # while promoted transfers ride the link.  The consumer then
        # receives arrays whose successors are already on the wire.
        # Staging-pool pressure is relieved by retiring the oldest
        # TRANSFERS after force-promoting any read-stage entries
        # (retire pool + pending cover all held staging between them).
        pending: list = []      # planned: reads in flight
        ready: list = []        # finished: transfers dispatched
        try:
            for b in range(n_batches):
                b0 = b * self.local_batch
                retire.drain_ready()
                while held[0] + batch_pieces > eng.n_buffers:
                    if pending:
                        ready.append(finish(pending.pop(0)))
                    elif not retire.retire_oldest():
                        break
                span_reads = {}
                entry = []
                for dev, (g0, g1) in dev_spans.items():
                    key = (g0, g1)
                    if key not in span_reads:
                        span_reads[key] = plan_reads(b0 + (g0 - lo),
                                                     b0 + (g1 - lo))
                    entry.append((dev, span_reads[key]))
                pending.append(entry)
                held[0] += len(entry_reads(entry))
                while pending and all(pr.is_ready()
                                      for pr in entry_reads(pending[0])):
                    ready.append(finish(pending.pop(0)))
                if len(pending) + len(ready) > depth:
                    if not ready:
                        ready.append(finish(pending.pop(0)))
                    yield ready.pop(0)
            while pending:
                ready.append(finish(pending.pop(0)))
            while ready:
                yield ready.pop(0)
        finally:
            retire.flush()
            for entry in pending:
                for pr in entry_reads(entry):
                    pr.release()
            for fh in fhs:
                eng.close(fh)
        self.epoch += 1

    # -- wds_raw: batch-coalesced zero-copy WebDataset path -----------------

    def _iter_wds_raw(self) -> Iterator:
        """One epoch of raw-member WebDataset batches (VERDICT r2 #6).

        The standard wds path copies every payload to host
        (``view.tobytes()`` per member) because ``decode`` is arbitrary
        Python.  But config 3's shards — and any raw-tensor wds dataset
        — need no host decode at all: each member's bytes go staging →
        device untouched.  Per batch, per local device: the device's
        rows' member ranges are engine-read as ONE pipelined sequence
        (tar headers between members are never read), each staging view
        is ``device_put`` directly, members concat/stack ON DEVICE, and
        the global array assembles with
        ``make_array_from_single_device_arrays`` — the fixedrec recipe
        applied to tar shards.  Members that need host decode (JPEG…)
        belong on the standard path; this one requires single-part
        samples of one common byte length (uint8 output, reshape/cast
        on device downstream).  Like fixedrec, record-level shuffling
        is traded away: ``shuffle_buffer`` permutes SHARD order only —
        randomize record order at dataset-prep time.
        """
        import jax.numpy as jnp
        from nvme_strom_tpu.ops.bridge import host_to_device

        eng = self._engine
        sharding = batch_sharding(self.mesh, self.axis)
        order = self._epoch_shard_order()
        recs: list = []          # (shard_i, offset, length) per record
        mlen = None
        for si, path in enumerate(order):
            for parts in self._index_shard(path):
                if len(parts) != 1:
                    raise ValueError(
                        f"{path}: wds_raw needs single-part samples "
                        f"(got {sorted(parts)}); restrict with exts= or "
                        "use the standard wds path")
                ((off, ln),) = parts.values()
                if mlen is None:
                    mlen = ln
                elif ln != mlen:
                    raise ValueError(
                        f"{path}: member length {ln} != {mlen}; wds_raw "
                        "stacks fixed-size members — variable-size "
                        "samples need the standard wds path")
                recs.append((si, off, ln))
        if mlen is None or not recs:
            self.epoch += 1
            return
        gshape = (self.global_batch, mlen)
        dev_spans, lo = self._device_row_spans(sharding, gshape)
        n_batches = self._count_batches(len(recs))
        chunk = tuned_chunk_bytes(eng)   # planner split size (≤ buffer)
        fhs = [eng.open(p) for p in order]

        # Span coalescing (window-9): tar members of one fixed payload
        # size sit at a CONSTANT stride (512 B header + padded
        # payload), so a run of consecutive members is ONE strided
        # read and ONE device put — the batch then materializes as
        # reshape(k, stride)[:, :mlen] on device, a single fused
        # program with the SAME shape every batch (no per-batch
        # recompiles).  That moves the loader from 8 × 1 MiB puts per
        # batch to bench's own chunk regime, whose stream rides ≥0.9
        # of ceiling.  The ~512 B/member of header bytes transferred
        # along is 0.05% overhead; reading one header-gap past the
        # last payload is covered by tar's mandatory ≥1024 B
        # end-of-archive zero blocks (checked against file size below).
        stride = None
        uniform = True
        prev = None
        for si, off, _ in recs:
            if prev is not None and prev[0] == si:
                d = off - prev[1]
                if stride is None:
                    stride = d
                elif d != stride:
                    uniform = False
                    break
            prev = (si, off)
        uniform = uniform and stride is not None and stride >= mlen
        if uniform:
            last = {}
            for si, off, _ in recs:
                last[si] = off
            uniform = all(off + stride <= os.path.getsize(order[si])
                          for si, off in last.items())
        if uniform:
            # ONE encoding of the grouping rule, shared by the read
            # planner (span_groups) and the pool-fit piece count
            # (range_pieces below): record r continues a group iff it
            # stays in the same shard at exactly one stride past its
            # predecessor.  brk[r] marks the group STARTS.
            sis = np.fromiter((r[0] for r in recs), np.int64, len(recs))
            offs = np.fromiter((r[1] for r in recs), np.int64,
                               len(recs))
            brk = np.ones(len(recs), bool)
            brk[1:] = (sis[1:] != sis[:-1]) | (offs[1:] != offs[:-1]
                                               + stride)

        class _Span(list):
            """PendingReads of one strided span + its member count
            (a list subclass so _zero_copy_batches' read-walker still
            finds the leaves)."""
            __slots__ = ("k",)

        def span_groups(r0, r1):
            """Runs of stride-consecutive records in one shard, read
            straight off the shared ``brk`` array — the read planner
            and the pool-fit count (range_pieces) consume the SAME
            group boundaries by construction."""
            groups = []
            for r in range(r0, r1):
                si, off, _ = recs[r]
                if groups and not brk[r]:
                    groups[-1][2] += 1
                else:
                    groups.append([si, off, 1])
            return groups

        # BOTH read plans (strided spans and per-member) route through
        # the shared planner: one place owns the chunk-split rule (the
        # two hand-rolled loops here used to drift), near-adjacent
        # ranges coalesce (consecutive tar members sit one 512 B header
        # apart — under the default gap), and the whole range submits
        # as ONE vectored batch.

        def plan_reads_span(r0, r1):
            groups = span_groups(r0, r1)
            planned = plan_and_submit(
                eng, [(fhs[si], off0, k * stride)
                      for si, off0, k in groups],
                chunk_bytes=chunk, klass="prefetch")
            out = []
            for (si, off0, k), pieces in zip(groups, planned):
                prs = _Span(pieces)
                prs.k = k
                out.append(prs)
            return out

        def plan_reads(r0, r1):
            return plan_and_submit(
                eng, [(fhs[recs[r][0]], recs[r][1], recs[r][2])
                      for r in range(r0, r1)],
                chunk_bytes=chunk, klass="prefetch")

        def dispatch_groups(dev, groups, group_block):
            """One batch's groups → device blocks: wait each read, put
            its staging view, concat a multi-chunk group, finish with
            ``group_block``.  On ANY failure, dispatched puts settle
            before the caller releases staging (release-after-ready) —
            one copy of the hazard path for both read plans."""
            blocks = []
            dispatched = []
            try:
                for prs in groups:
                    parts = []
                    for pr in prs:
                        parts.append(host_to_device(
                            eng, wait_exact(pr), dev))
                        dispatched.append(parts[-1])
                    big = (parts[0] if len(parts) == 1
                           else jnp.concatenate(parts))
                    blocks.append(group_block(big, prs))
                return blocks
            except BaseException:
                _settle(dispatched)
                raise

        def to_device_span(dev, groups):
            blocks = dispatch_groups(
                dev, groups,
                lambda big, prs: big.reshape(prs.k, stride)[:, :mlen])
            return (blocks[0] if len(blocks) == 1
                    else jnp.concatenate(blocks))

        def to_device(dev, groups):
            return jnp.stack(dispatch_groups(dev, groups,
                                             lambda big, prs: big))

        if uniform:
            plan_reads, to_device = plan_reads_span, to_device_span
            # EXACT worst-case staging pieces per batch: a "+margin"
            # guess here underestimates datasets of many tiny shards
            # (each shard boundary opens a new group), and an entry
            # needing more buffers than the pool deadlocks finish() —
            # the engine defers the excess reads and only this entry's
            # own transfers could free buffers.  Walk every batch's
            # distinct device spans and take the max — via the shared
            # ``brk`` array (round-4 advisor: re-running the
            # pure-Python span_groups walk per batch cost O(total
            # records) of list-building at every epoch start): a
            # sub-range's groups are its forced start plus the breaks
            # inside it, and the piece count follows from
            # consecutive-start diffs.
            def range_pieces(a, b):
                starts = np.flatnonzero(brk[a:b])
                if starts.size == 0 or starts[0] != 0:
                    starts = np.concatenate(([0], starts))
                k = np.diff(np.append(starts, b - a))
                return int(np.sum(-(-(k * stride) // chunk)))

            span_list = sorted({sp for sp in dev_spans.values()})
            batch_pieces = 1
            for b in range(n_batches):
                b0 = b * self.local_batch
                tot = sum(range_pieces(b0 + (g0 - lo), b0 + (g1 - lo))
                          for g0, g1 in span_list)
                batch_pieces = max(batch_pieces, tot)
        else:
            batch_pieces = self.local_batch * -(-mlen // chunk)

        yield from self._zero_copy_batches(
            sharding, gshape, dev_spans, lo, n_batches, batch_pieces,
            plan_reads, to_device, fhs)

    def close(self) -> None:
        if self._owns_engine:
            self._engine.close_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
