"""Shard-to-host assignment for multi-host input pipelines.

Each host owns a disjoint subset of shard files and reads them from its OWN
local NVMe — the cross-host "communication" is only the implicit agreement
on the assignment (derived from jax process indices), so bulk data never
crosses hosts (SURVEY.md §5 "Distributed comm backend").
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def assign_shards(paths: Sequence, process_index: int,
                  process_count: int) -> List:
    """Deterministic round-robin assignment (sorted for cross-host
    agreement).  Requires len(paths) >= process_count so no host idles."""
    if process_count < 1:
        raise ValueError("process_count must be >= 1")
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} not in [0, {process_count})")
    ordered = sorted(str(p) for p in paths)
    if len(ordered) < process_count:
        raise ValueError(
            f"{len(ordered)} shards < {process_count} processes: "
            "every host needs at least one local shard")
    return ordered[process_index::process_count]


def shuffled_indices(n: int, seed: int, epoch: int = 0) -> np.ndarray:
    """Deterministic per-epoch permutation (same on every host)."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
    return rng.permutation(n)
