"""Weighted multi-dataset mixing over sharded loaders.

LM pretraining feeds a weighted mixture of corpora (code/web/books...)
rather than one dataset; the reference-side analogue is PG-Strom
scanning many tables through one DMA engine (SURVEY.md §3.5 — the
consumer composes sources, the engine stays shared).  ``MixtureLoader``
composes :class:`~nvme_strom_tpu.data.loader.ShardedLoader`s the same
way: one engine underneath, one batch stream out.

Multi-host correctness is the design constraint: every process must
draw the SAME source at the SAME step, or the per-process shard reads
would assemble a global batch from different datasets.  The draw is a
counter-based PRNG on (seed, step) — ``np.random.default_rng(
(seed, step))`` — so processes agree without any cross-host
communication, the same trick the loaders use for shard shuffling
(data/sharding.py).

An exhausted source restarts transparently: re-iterating a
ShardedLoader advances its ``.epoch`` and reshuffles, so the mixture
stream is unbounded even though each underlying epoch is finite
(matching how optimizer steps, not epochs, bound LM training).
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

__all__ = ["MixtureLoader"]


class MixtureLoader:
    """Draw batches from several loaders with fixed weights.

    ``sources``: sequence of (loader, weight) — any iterable yielding
    batches and restartable via ``iter()`` qualifies (ShardedLoader
    does).  Weights are normalized; they need not sum to 1.

    ``max_restarts``: how many times an exhausted source may restart
    (None = unbounded, the LM-pretraining default).  A source whose
    FIRST epoch is empty raises — silently dropping a misconfigured
    corpus would skew the mixture.

    Iteration yields ``(batch, source_index)``; ``counts`` records how
    many batches each source served (observability: the realized
    mixture vs the requested weights).
    """

    def __init__(self, sources: Sequence[tuple], *, seed: int = 0,
                 max_restarts: Optional[int] = None):
        if not sources:
            raise ValueError("MixtureLoader needs at least one source")
        self.loaders = [s for s, _ in sources]
        w = np.asarray([float(wt) for _, wt in sources], np.float64)
        if (w <= 0).any():
            raise ValueError(f"weights must be positive, got {w.tolist()}")
        self.weights = w / w.sum()
        self.seed = int(seed)
        self.max_restarts = max_restarts
        self.counts = [0] * len(self.loaders)
        self.step = 0

    def _draw(self, step: int) -> int:
        """Source index for ``step`` — a pure function of (seed, step),
        identical on every process by construction."""
        rng = np.random.default_rng((self.seed, step))
        return int(rng.choice(len(self.weights), p=self.weights))

    def __iter__(self) -> Iterator:
        iters = [iter(ld) for ld in self.loaders]
        restarts = [0] * len(iters)
        try:
            while True:
                s = self._draw(self.step)
                try:
                    batch = next(iters[s])
                except StopIteration:
                    restarts[s] += 1
                    if (self.max_restarts is not None
                            and restarts[s] > self.max_restarts):
                        return
                    iters[s] = iter(self.loaders[s])  # next epoch,
                    try:                              # reshuffled
                        batch = next(iters[s])
                    except StopIteration:
                        raise ValueError(
                            f"mixture source {s} yielded no batches — "
                            "an empty corpus would silently skew the "
                            "mixture")
                self.counts[s] += 1
                self.step += 1
                yield batch, s
        finally:
            # an abandoned mixture must not leave source producer
            # threads mid-submit: ShardedLoader.__iter__'s generator
            # close() joins its producer before the loader's engine
            # can be torn down
            for it in iters:
                close = getattr(it, "close", None)
                if close is not None:
                    close()
