from nvme_strom_tpu.data.loader import (LoaderErrors, ShardReadError,
                                        ShardedLoader)
from nvme_strom_tpu.data.mixture import MixtureLoader
from nvme_strom_tpu.data.sharding import assign_shards, shuffled_indices

__all__ = ["ShardedLoader", "MixtureLoader", "assign_shards",
           "shuffled_indices", "ShardReadError", "LoaderErrors"]
