"""Multi-file datasets: a directory of Parquet files as ONE table.

PG-Strom's arrow_fdw scans many files behind one foreign table
(SURVEY.md §3.5); the TPU analogue keeps each file on its own
scanner — footer statistics, direct-path eligibility and row-group
pruning all stay per-file — and unions at the AGGREGATE level:

- grouped / scalar aggregates: each file produces RAW foldable
  partials (count/sum/sum2/min/max with segment identities, the same
  `_fold_scan(finalize=False)` body the single-file executors use) and
  one final finalize runs over the cross-file fold — numerically the
  single-table answer, never a concatenated table in memory.
- ORDER BY/LIMIT: per-file `sql_topk` (each with its own
  statistics-driven LIMIT elimination), then a host-side merge of the
  tiny per-file top-k candidate sets.

String-keyed GROUP BY is refused for now: per-file dictionaries would
need a global label-union remap; numeric keys don't have the problem.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["multi_groupby", "multi_scalar_agg", "multi_topk",
           "open_dataset"]


def open_dataset(paths, engine) -> List:
    """Paths (list, or a directory of .parquet files) → scanners."""
    import os
    from nvme_strom_tpu.sql.parquet import ParquetScanner
    if isinstance(paths, (str, bytes, os.PathLike)):
        d = os.fspath(paths)
        paths = sorted(os.path.join(d, f) for f in os.listdir(d)
                       if f.endswith(".parquet"))
        if not paths:
            raise ValueError(f"no .parquet files under {d}")
    return [ParquetScanner(p, engine) for p in paths]


def _check_schemas(scanners, columns) -> None:
    """The referenced columns must exist with one type in every file."""
    ref = None
    for sc in scanners:
        md = sc.metadata
        types = {md.schema.column(i).name:
                 str(md.schema.column(i).physical_type)
                 for i in range(md.num_columns)}
        got = {}
        for c in columns:
            if c not in types:
                raise KeyError(f"column {c!r} missing from {sc.path}")
            got[c] = types[c]
        if ref is None:
            ref = got
        elif got != ref:
            raise ValueError(
                f"schema mismatch across dataset files: {sc.path} has "
                f"{got}, first file has {ref}")


def _union_fold(scanners, key_column, vcols, single, num_groups, aggs,
                method, device, where, where_columns, where_ranges,
                nulls):
    """THE per-scanner fold loop (raw partials, fully-pruned members
    skipped) shared by the multi-file union and the distributed
    executor — three copies of this loop had started to drift (advisor
    round-4).  Each member's scan rides `_fold_scan`, so pushdown
    planning, partition-parallel workers, and late materialization
    (sql/scan_plan.py) apply per file with no code here.  Returns the
    folded partials, or None when no member produced any row group."""
    from nvme_strom_tpu.sql.groupby import _fold, _fold_scan
    folds = None
    for sc in scanners:
        try:
            part = _fold_scan(sc, key_column, vcols, single, num_groups,
                              aggs, method, device, where, where_columns,
                              where_ranges, nulls, finalize=False)
        except ValueError as e:
            if "empty table" in str(e):   # a zero-row-group member
                continue                  # must not kill the union
            raise
        folds = part if folds is None else _fold(folds, part)
    return folds


def multi_groupby(scanners: Sequence, key_column: str, value_column,
                  num_groups: int,
                  aggs: Sequence[str] = ("count", "sum", "mean"),
                  method: str = "matmul", device=None,
                  where=None, where_columns: Sequence[str] = (),
                  where_ranges: Sequence[tuple] = (),
                  nulls: str = "forbid") -> Dict[str, object]:
    """`sql_groupby` over a file union — one fold, one finalize."""
    from nvme_strom_tpu.sql.groupby import (_validate_nulls,
                                            _validate_query, _value_cols,
                                            finalize_folds)
    _validate_query(aggs, method)
    where_ranges = list(where_ranges)   # a generator must not exhaust
    vcols, single = _value_cols(value_column)   # after file 0
    _validate_nulls(nulls, single)
    _check_schemas(scanners, [key_column, *vcols])
    folds = _union_fold(scanners, key_column, vcols, single, num_groups,
                        aggs, method, device, where, where_columns,
                        where_ranges, nulls)
    if folds is None:
        raise ValueError("empty dataset (no rows in any file)")
    return finalize_folds(folds, aggs)


def multi_scalar_agg(scanners: Sequence, value_column,
                     aggs: Sequence[str] = ("count", "sum", "mean"),
                     method: str = "matmul", device=None,
                     where=None, where_columns: Sequence[str] = (),
                     where_ranges: Sequence[tuple] = (),
                     nulls: str = "forbid") -> Dict[str, object]:
    """`sql_scalar_agg` over a file union."""
    from nvme_strom_tpu.sql.groupby import (_validate_nulls,
                                            _validate_query, _value_cols,
                                            finalize_folds)
    _validate_query(aggs, method)
    where_ranges = list(where_ranges)   # a generator must not exhaust
    vcols, single = _value_cols(value_column)   # after file 0
    _validate_nulls(nulls, single)
    _check_schemas(scanners, vcols)
    folds = _union_fold(scanners, None, vcols, single, 1, aggs, method,
                        device, where, where_columns, where_ranges,
                        nulls)
    if folds is None:
        raise ValueError("empty dataset (no rows in any file)")
    res = finalize_folds(folds, aggs)
    return {a: res[a][0] for a in res}


def multi_topk(scanners: Sequence, by: str,
               columns: Sequence[str] = (), k: int = 10,
               descending: bool = True, device=None,
               where=None, where_columns: Sequence[str] = (),
               where_ranges: Sequence[tuple] = (),
               nulls: str = "forbid") -> Dict[str, np.ndarray]:
    """`sql_topk` over a file union: per-file top-k (each with its own
    LIMIT scan-elimination), merged host-side.  ``_file`` joins
    ``_row`` in the provenance columns; ``_skipped_row_groups`` sums.

    Tie order: rows with equal keys rank by (_file, _row) ascending in
    both sort directions — deterministic where single-file ``sql_topk``
    leaves ties unspecified (its streamed merge carries no provenance
    to break them with)."""
    from nvme_strom_tpu.sql.topk import sql_topk
    where_ranges = list(where_ranges)   # a generator must not exhaust
    _check_schemas(scanners, [by, *columns])   # after file 0
    parts = []
    skipped = 0
    for fi, sc in enumerate(scanners):
        try:
            r = sql_topk(sc, by, columns=columns, k=k,
                         descending=descending, device=device,
                         where=where, where_columns=where_columns,
                         where_ranges=where_ranges, nulls=nulls)
        except ValueError as e:
            if "empty table" in str(e):   # member fully pruned: the
                continue                  # union answers from the rest
            raise
        skipped += int(r.pop("_skipped_row_groups"))
        r["_file"] = np.full(len(r["_row"]), fi, np.int32)
        parts.append(r)
    if not parts:
        raise ValueError("empty dataset (every file pruned away)")
    names = [by, *[c for c in columns if c != by], "_row", "_file"]
    merged = {n: np.concatenate([p[n] for p in parts]) for n in names}
    # Explicit tie-break on (_file, _row) ascending in BOTH directions
    # (advisor round-3: a reversed stable sort returned descending ties
    # in reverse file/row order).  The KEY column is never negated —
    # that would wrap unsigned dtypes and INT64_MIN (the per-file merge
    # kernel avoids negation the same way) — but the provenance columns
    # are non-negative ordinals, so negating them to pre-reverse the
    # tie order is safe.
    if descending:
        order = np.lexsort((-merged["_row"], -merged["_file"],
                            merged[by]))[::-1]
    else:
        order = np.lexsort((merged["_row"], merged["_file"], merged[by]))
    order = order[:k]
    out = {n: merged[n][order] for n in names}
    out["_skipped_row_groups"] = skipped
    return out
