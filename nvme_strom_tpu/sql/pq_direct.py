"""Direct Parquet column decode: NVMe pages → device, no pyarrow on the hot
path.

PG-Strom's distinguishing move is decoding table blocks ON the accelerator
(SURVEY.md §3.5) — the CPU plans, the device decodes.  The Parquet analogue
for uncompressed, fixed-width columns, two page shapes:

- **PLAIN** data pages: host (metadata-class I/O, tiny) parses the footer
  (already held by the scanner) and each data-page header — a minimal
  Thrift compact-protocol reader, ~40 bytes per page — to compute the
  exact byte spans of raw little-endian values inside the file; the spans
  stream through the O_DIRECT engine and DeviceStream (staging → HBM, zero
  host-side payload copies), and the 'decode' is an on-device bitcast +
  concatenate.  Optional columns with no nulls carry an RLE
  definition-level block per page; its length is read host-side (8 bytes)
  and the span simply starts after it.
- **Dictionary-encoded** (PLAIN_DICTIONARY / RLE_DICTIONARY) chunks, the
  PG-Strom dictionary pattern: the dictionary page's PLAIN values stream
  O_DIRECT → device exactly like a plain span, the data pages'
  RLE/bit-packed index stream is read through the engine and expanded
  host-side with a vectorized numpy decoder (runs are sequential
  bitstream control flow — host work by nature; the decoded index array
  is honestly counted as bounce), and the final decode is an on-device
  ``take(dictionary, indices)`` gather.  Chunks where the writer fell
  back to PLAIN mid-stream (dictionary overflow) assemble both kinds in
  page order.

Everything else — compression, nulls, strings, nested schemas — falls
back to the pyarrow path in :mod:`.parquet`, which decodes on host and
honestly counts the handoff copy as bounce.

Why not decode the index bitstream on device too?  RLE runs are
variable-length sequential control flow; a Pallas cursor over them would
serialize (one varint at a time) — exactly what the MXU/VPU are worst
at.  The expensive expansion (indices → values) IS on device: the gather
reads only index ints host-side, never payload values.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

# Parquet physical types that are raw fixed-width little-endian under PLAIN
_WIDTHS = {"INT32": 4, "INT64": 8, "FLOAT": 4, "DOUBLE": 8}
_NP_DTYPES = {"INT32": "<i4", "INT64": "<i8", "FLOAT": "<f4",
              "DOUBLE": "<f8"}

# Thrift compact-protocol wire types
_CT_STOP = 0
_CT_BOOL_TRUE = 1
_CT_BOOL_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12

# parquet-format enums
_PAGE_DATA = 0
_PAGE_DICTIONARY = 2
_PAGE_DATA_V2 = 3
_ENC_PLAIN = 0
_ENC_PLAIN_DICTIONARY = 2
_ENC_RLE = 3
_ENC_RLE_DICTIONARY = 8
_ENC_BYTE_STREAM_SPLIT = 9
_DICT_ENCODINGS = (_ENC_PLAIN_DICTIONARY, _ENC_RLE_DICTIONARY)


class ThriftError(ValueError):
    """Malformed/truncated Thrift compact data (or not enough bytes read —
    callers retry with a bigger window before giving up)."""


class _Compact:
    """Just enough of the Thrift compact protocol to read a Parquet
    PageHeader: varints, zigzag, field headers, and recursive skip.
    parquet-format/src/main/thrift/parquet.thrift defines the schema; the
    reference consumes the same metadata via its SQL host code."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _byte(self) -> int:
        if self.pos >= len(self.buf):
            raise ThriftError("truncated")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        out = shift = 0
        while True:
            b = self._byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7
            if shift > 63:
                raise ThriftError("varint overflow")

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_field_header(self, last_id: int) -> Tuple[int, int]:
        """→ (wire_type, field_id); wire_type 0 = stop."""
        b = self._byte()
        if b == _CT_STOP:
            return 0, 0
        delta, ctype = b >> 4, b & 0x0F
        fid = last_id + delta if delta else self.zigzag()
        return ctype, fid

    def skip(self, ctype: int) -> None:
        if ctype in (_CT_BOOL_TRUE, _CT_BOOL_FALSE):
            return
        if ctype == _CT_BYTE:
            self._byte()
        elif ctype in (_CT_I16, _CT_I32, _CT_I64):
            self.varint()
        elif ctype == _CT_DOUBLE:
            self.pos += 8
            if self.pos > len(self.buf):
                raise ThriftError("truncated")
        elif ctype == _CT_BINARY:
            n = self.varint()
            self.pos += n
            if self.pos > len(self.buf):
                raise ThriftError("truncated")
        elif ctype in (_CT_LIST, _CT_SET):
            b = self._byte()
            n, et = b >> 4, b & 0x0F
            if n == 15:
                n = self.varint()
            # bool elements consume ZERO bytes per skip — an unbounded
            # count from malformed input would spin forever; any honest
            # collection needs at least... well, bools need nothing, so
            # bound by what the buffer could possibly hold
            if n > len(self.buf) - self.pos:
                raise ThriftError(f"collection count {n} exceeds buffer")
            for _ in range(n):
                self.skip(et)
        elif ctype == _CT_MAP:
            n = self.varint()
            if n > len(self.buf) - self.pos:
                raise ThriftError(f"map count {n} exceeds buffer")
            if n:
                b = self._byte()
                kt, vt = b >> 4, b & 0x0F
                for _ in range(n):
                    self.skip(kt)
                    self.skip(vt)
        elif ctype == _CT_STRUCT:
            last = 0
            while True:
                t, fid = self.read_field_header(last)
                if t == 0:
                    return
                last = fid
                self.skip(t)
        else:
            raise ThriftError(f"bad compact type {ctype}")


@dataclass(frozen=True)
class PageHeader:
    type: int
    compressed_size: int
    uncompressed_size: int
    num_values: int          # data/dictionary pages (0 otherwise)
    encoding: int            # data/dictionary pages (-1 otherwise)
    header_len: int          # bytes the Thrift header itself occupies
    # DataPageHeaderV2 states the level-block lengths explicitly (a v1
    # reader must instead parse RLE length prefixes from the page body)
    def_levels_len: int = 0
    rep_levels_len: int = 0


def parse_page_header(buf: bytes) -> PageHeader:
    """Parse a PageHeader at buf[0].  Raises ThriftError if ``buf`` is too
    short (callers re-read with a larger window)."""
    c = _Compact(buf)
    ptype = comp = uncomp = -1
    num_values, encoding = 0, -1
    def_len = rep_len = 0
    last = 0
    while True:
        t, fid = c.read_field_header(last)
        if t == 0:
            break
        last = fid
        if fid == 1 and t == _CT_I32:
            ptype = c.zigzag()
        elif fid == 2 and t == _CT_I32:
            uncomp = c.zigzag()
        elif fid == 3 and t == _CT_I32:
            comp = c.zigzag()
        elif fid in (5, 7, 8) and t == _CT_STRUCT:
            # DataPageHeader (v1) / DictionaryPageHeader / DataPageHeaderV2
            inner_last = 0
            while True:
                it, ifid = c.read_field_header(inner_last)
                if it == 0:
                    break
                inner_last = ifid
                if ifid == 1 and it == _CT_I32:
                    num_values = c.zigzag()
                elif ifid == 2 and it == _CT_I32 and fid in (5, 7):
                    encoding = c.zigzag()
                elif ifid == 4 and it == _CT_I32 and fid == 8:
                    encoding = c.zigzag()
                elif ifid == 5 and it == _CT_I32 and fid == 8:
                    def_len = c.zigzag()
                elif ifid == 6 and it == _CT_I32 and fid == 8:
                    rep_len = c.zigzag()
                else:
                    c.skip(it)
        else:
            c.skip(t)
    if ptype < 0 or comp < 0:
        raise ThriftError("missing required PageHeader fields")
    return PageHeader(ptype, comp, uncomp, num_values, encoding, c.pos,
                      def_len, rep_len)


@dataclass(frozen=True)
class PagePart:
    """One data page's decodable payload within a column chunk.

    kind "plain": ``span`` covers raw little-endian values (on-device
    bitcast).  kind "dict": ``span`` covers the RLE/bit-packed index
    stream (host-expanded, then on-device gather against the chunk's
    dictionary); ``bit_width`` is the stream's index width.  kind
    "bss": BYTE_STREAM_SPLIT — ``span`` covers the byte-transposed
    values (decode is an on-device reshape/transpose/bitcast, zero
    host-touched payload like plain).
    """
    kind: str                              # "plain" | "dict"
    span: Tuple[int, int]                  # (offset, length) into the file
    num_values: int
    bit_width: int = 0                     # dict parts only


@dataclass(frozen=True)
class ColumnPlan:
    """Decodable page layout of one column chunk (one row group)."""
    parts: Tuple[PagePart, ...]            # in file/page order
    num_values: int
    physical_type: str
    dict_span: Optional[Tuple[int, int]] = None   # PLAIN dictionary values
    dict_count: int = 0

    @property
    def spans(self) -> Tuple[Tuple[int, int], ...]:
        """Plain value-byte spans (the pre-dictionary API surface)."""
        return tuple(p.span for p in self.parts if p.kind == "plain")


def eligible_chunk(meta, rg: int, ci: int) -> Optional[str]:
    """None if the (row group, column) chunk can decode on device, else a
    human-readable reason for the pyarrow fallback (surfaced in stats)."""
    col = meta.row_group(rg).column(ci)
    sc = meta.schema.column(ci)
    if col.physical_type not in _WIDTHS:
        return f"physical type {col.physical_type}"
    if _WIDTHS[col.physical_type] == 8:
        import jax
        if not jax.config.jax_enable_x64:
            # the on-device bitcast would silently truncate i64/f64
            return (f"{col.physical_type} needs jax_enable_x64 "
                    f"(bitcast would truncate)")
    if (col.compression or "UNCOMPRESSED") != "UNCOMPRESSED":
        return f"compression {col.compression}"
    encs = set(col.encodings)
    if not encs <= {"PLAIN", "RLE", "PLAIN_DICTIONARY", "RLE_DICTIONARY",
                    "BYTE_STREAM_SPLIT"}:
        return f"encodings {sorted(encs)}"
    if sc.max_repetition_level != 0:
        return "repeated field"
    if sc.max_definition_level > 0:
        st = col.statistics
        if st is None or st.null_count is None:
            return "no null statistics"
        if st.null_count != 0:
            return f"{st.null_count} nulls"
    return None


def _walk_pages(col, raw_read):
    """Yield (pos, PageHeader) for every page of a column chunk, until
    the data pages' value counts cover ``col.num_values``.

    ``raw_read(offset, length) -> bytes`` serves page headers —
    metadata-class reads (≤ ~1 KiB per page, via buffered I/O like the
    footer), never payload."""
    pos = col.data_page_offset
    if (col.dictionary_page_offset or 0) > 0:
        # the dictionary page precedes the data pages in the chunk
        pos = min(pos, col.dictionary_page_offset)
    end = pos + col.total_compressed_size
    remaining = col.num_values
    window = 1 << 10
    while remaining > 0:
        if pos >= end:
            raise ValueError(f"page walk ran past chunk end at {pos}")
        buf = raw_read(pos, min(window, end - pos))
        while True:
            try:
                ph = parse_page_header(buf)
                break
            except ThriftError:
                if len(buf) >= end - pos:
                    raise
                buf = raw_read(pos, min(len(buf) * 2, end - pos))
        if ph.type in (_PAGE_DATA, _PAGE_DATA_V2):
            if ph.num_values > remaining:
                # RLE can legally pack huge claimed counts into a few
                # bytes — an unbounded count would drive a huge host
                # allocation in the index decoder (and silently
                # over-long plain output)
                raise ValueError(
                    f"page at {pos}: {ph.num_values} values exceeds "
                    f"chunk remainder {remaining}")
            remaining -= ph.num_values
        yield pos, ph
        pos += ph.header_len + ph.compressed_size


def _level_bytes(pos, ph, has_def: bool, raw_read) -> int:
    """Bytes the definition/repetition-level block occupies at the page
    body's start (v2: stated in the header; v1: ``<u32 len><RLE>``)."""
    if ph.type == _PAGE_DATA_V2:
        return ph.def_levels_len + ph.rep_levels_len
    if has_def:
        (n,) = struct.unpack("<I", raw_read(pos + ph.header_len, 4))
        return 4 + n
    return 0


def _index_stream_part(pos, ph, level_bytes: int, raw_read) -> PagePart:
    """Dict-encoded data-page body → index-stream PagePart.

    Body after levels: ``<bit_width: 1 byte><RLE-hybrid runs>`` — the
    one layout rule both the numeric and byte-array walks share."""
    val_off = pos + ph.header_len + level_bytes
    (bw,) = raw_read(val_off, 1)
    if bw > 32:
        raise ValueError(f"page at {pos}: bit width {bw} > 32")
    idx_len = ph.compressed_size - level_bytes - 1
    if idx_len < 0:
        raise ValueError(f"page at {pos}: negative index span")
    return PagePart("dict", (val_off + 1, idx_len), ph.num_values,
                    bit_width=bw)


def _check_dict_page(pos, ph, already_seen: bool) -> None:
    """Shared dictionary-page validity rules (one per chunk, PLAIN)."""
    if already_seen:
        raise ValueError(f"second dictionary page at {pos}")
    if ph.encoding not in (_ENC_PLAIN, _ENC_PLAIN_DICTIONARY):
        raise ValueError(
            f"dictionary page encoding {ph.encoding} not PLAIN")


def plan_chunk(meta, rg: int, ci: int, raw_read) -> ColumnPlan:
    """Walk the chunk's data pages, returning exact value-byte spans.

    ``raw_read`` as in :func:`_walk_pages`; it additionally serves the
    v1 RLE level-length prefixes (8 bytes per page)."""
    col = meta.row_group(rg).column(ci)
    sc = meta.schema.column(ci)
    width = _WIDTHS[col.physical_type]
    has_def = sc.max_definition_level > 0
    parts: List[PagePart] = []
    dict_span: Optional[Tuple[int, int]] = None
    dict_count = 0
    for pos, ph in _walk_pages(col, raw_read):
        if ph.type in (_PAGE_DATA, _PAGE_DATA_V2):
            lb = _level_bytes(pos, ph, has_def, raw_read)
            if ph.encoding in (_ENC_PLAIN, _ENC_BYTE_STREAM_SPLIT):
                val_off = pos + ph.header_len + lb
                val_len = ph.num_values * width
                if val_len + lb > ph.compressed_size:
                    raise ValueError(
                        f"page at {pos}: {ph.num_values} values x {width} "
                        f"+ {lb} level bytes > page size "
                        f"{ph.compressed_size}")
                kind = ("plain" if ph.encoding == _ENC_PLAIN else "bss")
                parts.append(PagePart(kind, (val_off, val_len),
                                      ph.num_values))
            elif ph.encoding in _DICT_ENCODINGS:
                if dict_span is None:
                    raise ValueError(
                        f"page at {pos}: dict-encoded data page before "
                        f"any dictionary page")
                parts.append(_index_stream_part(pos, ph, lb, raw_read))
            else:
                raise ValueError(
                    f"page at {pos}: unsupported encoding {ph.encoding}")
        elif ph.type == _PAGE_DICTIONARY:
            _check_dict_page(pos, ph, dict_span is not None)
            val_len = ph.num_values * width
            if val_len > ph.compressed_size:
                raise ValueError(
                    f"dictionary page at {pos}: {ph.num_values} values x "
                    f"{width} > page size {ph.compressed_size}")
            dict_span = (pos + ph.header_len, val_len)
            dict_count = ph.num_values
        # INDEX pages are skipped silently
    return ColumnPlan(tuple(parts), col.num_values, col.physical_type,
                      dict_span=dict_span, dict_count=dict_count)


def decode_rle_hybrid(buf: bytes, bit_width: int, count: int):
    """Parquet RLE/bit-packed hybrid stream → int32 index array (host).

    The stream is a sequence of runs, each headed by a varint: low bit 1
    → bit-packed run of ``(header >> 1) * 8`` values (``bit_width`` bits
    each, LSB-first little-endian — decoded vectorized via
    ``np.unpackbits``); low bit 0 → RLE run of ``header >> 1`` copies of
    one ``ceil(bit_width / 8)``-byte value.  The final run may carry
    padding values past ``count``; they are discarded per the spec.
    """
    import numpy as np
    out = np.empty(count, np.int32)
    if bit_width == 0:
        # zero-width indices: a single-entry dictionary, all index 0
        out[:] = 0
        return out
    byte_w = (bit_width + 7) // 8
    weights = (np.int64(1) << np.arange(bit_width, dtype=np.int64))
    pos, filled, n = 0, 0, len(buf)
    while filled < count:
        header = shift = 0
        while True:
            if pos >= n:
                raise ValueError("truncated RLE stream header")
            b = buf[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 35:
                raise ValueError("RLE header varint overflow")
        if header & 1:                       # bit-packed run
            groups = header >> 1
            nbytes = groups * bit_width      # groups of 8 values
            if pos + nbytes > n:
                raise ValueError("truncated bit-packed run")
            bits = np.unpackbits(
                np.frombuffer(buf, np.uint8, nbytes, pos),
                bitorder="little")
            vals = bits.reshape(-1, bit_width).astype(np.int64) @ weights
            take = min(groups * 8, count - filled)
            out[filled:filled + take] = vals[:take]
            filled += take
            pos += nbytes
        else:                                # RLE run
            run = header >> 1
            if run == 0:
                raise ValueError("zero-length RLE run")
            if pos + byte_w > n:
                raise ValueError("truncated RLE run value")
            v = int.from_bytes(buf[pos:pos + byte_w], "little")
            pos += byte_w
            take = min(run, count - filled)
            out[filled:filled + take] = v
            filled += take
    return out


def plan_columns(scanner, columns: Sequence[str]
                 ) -> Dict[str, List[ColumnPlan]]:
    """Page-walk every (row group, column) chunk → value spans.  Raises
    ValueError naming the first non-eligible chunk — callers wanting a
    soft answer use :func:`eligible_chunk` first."""
    import os
    meta = scanner.metadata
    name_to_ci = {meta.schema.column(i).name: i
                  for i in range(meta.num_columns)}
    with open(scanner.path, "rb") as f:
        def raw_read(off: int, ln: int) -> bytes:
            return os.pread(f.fileno(), ln, off)

        plans: Dict[str, List[ColumnPlan]] = {c: [] for c in columns}
        for rg in range(meta.num_row_groups):
            for c in columns:
                ci = name_to_ci[c]
                why = eligible_chunk(meta, rg, ci)
                if why is not None:
                    raise ValueError(
                        f"rg{rg}.{c} not direct-eligible: {why}")
                plans[c].append(plan_chunk(meta, rg, ci, raw_read))
    return plans


def _stream_spans(scanner, ds, fh, spans, physical_type):
    """spans → one device array (on-device concat + bitcast).

    Spans larger than the engine's staging-buffer size are split into
    chunk-sized sub-ranges first (writers like parquet-mr can emit pages
    bigger than chunk_bytes; the on-device concat makes the split
    invisible)."""
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bridge import split_ranges
    ranges, _ = split_ranges(spans, scanner.engine.config.chunk_bytes)
    parts = list(ds.stream_ranges(fh, ranges))
    if not parts:    # zero-row chunk: no spans to stream
        return jnp.zeros((0,), dtype=np.dtype(_NP_DTYPES[physical_type]))
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return flat.view(np.dtype(_NP_DTYPES[physical_type]))


def _stream_raw_groups(scanner, ds, fh, spans):
    """spans → one uint8 device array PER SPAN, all spans streamed as a
    single pipelined range sequence (sub-chunk split like
    :func:`_stream_spans`, but span boundaries preserved — BSS pages
    decode per page)."""
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bridge import split_ranges
    flat, counts = split_ranges(spans, scanner.engine.config.chunk_bytes)
    it = ds.stream_ranges(fh, flat)
    outs = []
    for n in counts:
        group = [next(it) for _ in range(n)]
        if not group:            # zero-length span (0-value page)
            outs.append(jnp.zeros((0,), dtype=np.uint8))
        else:
            outs.append(group[0] if n == 1 else jnp.concatenate(group))
    return outs


def _decode_indices(eng, fh, parts, dict_count: int, dev):
    """Dict-kind PageParts → one validated int32 host index array.

    Applies the module's accounting policy: raw index-stream bytes are
    counted by the engine read; the decoded array is host-materialized
    payload-derived data → bounce (on CPU ``host_to_device`` counts that
    same buffer via its alias-protection copy, so only non-CPU adds it
    here).  Validation is range-only — ``jnp.take`` would silently clip
    a corrupt stream into wrong rows."""
    import numpy as np
    idx_parts = [
        decode_rle_hybrid(_read_span_bytes(eng, fh, *p.span),
                          p.bit_width, p.num_values)
        for p in parts]
    if not idx_parts:          # zero-row chunk
        return np.empty(0, np.int32)
    idx = (idx_parts[0] if len(idx_parts) == 1
           else np.concatenate(idx_parts))
    if idx.size:
        lo, hi = int(idx.min()), int(idx.max())
        if lo < 0 or hi >= dict_count:
            raise ValueError(
                f"dictionary index {lo if lo < 0 else hi} out of range "
                f"[0, {dict_count})")
    if dev.platform != "cpu":
        eng.stats.add(bounce_bytes=int(idx.nbytes))
    return idx


def _read_span_bytes(engine, fh, off: int, ln: int) -> bytes:
    """Direct-engine read of a small control-stream span → host bytes.

    ``engine.read`` counts the staging→host copy as bounce — same rule
    as the pyarrow handoff (`parquet.EngineFile.readinto`): payload-class
    bytes a host decoder must touch.  Index streams are the small side of
    a dictionary chunk (≤ ~bit_width/8 bytes per value vs the full value
    width for the gathered output, which never exists host-side).
    """
    eng_chunk = engine.config.chunk_bytes
    parts = [engine.read(fh, pos, min(eng_chunk, off + ln - pos)).tobytes()
             for pos in range(off, off + ln, eng_chunk)]
    return parts[0] if len(parts) == 1 else b"".join(parts)


def _assemble_chunk(scanner, ds, fh, plan: ColumnPlan, dev):
    """One column chunk → one device array, pages assembled in order.

    Plain pages stream O_DIRECT→device and bitcast there.  Dict-encoded
    pages: the dictionary's PLAIN values stream the same zero-copy path,
    index streams are host-expanded (:func:`decode_rle_hybrid`) and the
    decode is an on-device ``take`` — values never materialize on host.
    Adjacent dict pages share one gather.
    """
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bridge import host_to_device

    eng = scanner.engine
    dict_dev = None
    if any(p.kind == "dict" for p in plan.parts):
        dict_dev = _stream_spans(scanner, ds, fh, [plan.dict_span],
                                 plan.physical_type)
    segs = []            # device arrays in page order
    pending_dict = []    # adjacent dict pages' index-stream parts
    pending_plain = []   # value spans of adjacent plain pages
    pending_bss = []     # value spans of adjacent BYTE_STREAM_SPLIT pages

    def flush_dict():
        if pending_dict:
            idx = _decode_indices(eng, fh, pending_dict,
                                  plan.dict_count, dev)
            segs.append(jnp.take(dict_dev, host_to_device(eng, idx, dev)))
            pending_dict.clear()

    def flush_plain():
        if pending_plain:
            # one pipelined stream over the adjacent spans — per-page
            # calls would collapse the queue to depth 1
            segs.append(_stream_spans(scanner, ds, fh, list(pending_plain),
                                      plan.physical_type))
            pending_plain.clear()

    def flush_bss():
        if pending_bss:
            width = _WIDTHS[plan.physical_type]
            np_dtype = np.dtype(_NP_DTYPES[plan.physical_type])
            for raw in _stream_raw_groups(scanner, ds, fh,
                                          list(pending_bss)):
                # BYTE_STREAM_SPLIT: page bytes are transposed
                # (width, n) — undo ON DEVICE, then bitcast
                n = raw.shape[0] // width
                segs.append(
                    raw.reshape(width, n).T.reshape(-1).view(np_dtype))
            pending_bss.clear()

    flushes = {"plain": (flush_dict, flush_bss),
               "dict": (flush_plain, flush_bss),
               "bss": (flush_dict, flush_plain)}
    for p in plan.parts:
        for fl in flushes[p.kind]:   # close the other kinds' runs
            fl()
        if p.kind == "plain":
            pending_plain.append(p.span)
        elif p.kind == "bss":
            pending_bss.append(p.span)
        else:
            pending_dict.append(p)
    flush_dict()
    flush_plain()
    flush_bss()
    if not segs:     # zero-row chunk
        return jnp.zeros((0,),
                         dtype=np.dtype(_NP_DTYPES[plan.physical_type]))
    return segs[0] if len(segs) == 1 else jnp.concatenate(segs)


def _plain_only(plans: Sequence[ColumnPlan]) -> bool:
    return all(p.kind == "plain" for plan in plans for p in plan.parts)


def read_plain_columns_to_device(scanner, columns: Sequence[str],
                                 device=None, plans=None
                                 ) -> Dict[str, "object"]:
    """Direct scan of the whole file: {name: device array}, row groups
    concatenated ON DEVICE.  Payload bytes (PLAIN values and dictionary
    values) ride O_DIRECT → staging → device; the host reads only
    headers and dict index streams.  ``plans`` lets callers reuse a
    prior :func:`plan_columns` walk."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bridge import DeviceStream

    dev = device or jax.local_devices()[0]
    plans = plans or plan_columns(scanner, columns)
    ds = DeviceStream(scanner.engine, device=dev,
                      depth=scanner.engine.config.queue_depth)
    out = {}
    meta = scanner.metadata
    name_to_ci = {meta.schema.column(i).name: i
                  for i in range(meta.num_columns)}
    fh = scanner.engine.open(scanner.path)
    try:
        for c in columns:
            if not plans[c]:   # zero row groups: empty typed column
                pt = meta.schema.column(name_to_ci[c]).physical_type
                out[c] = jnp.zeros((0,),
                                   dtype=np.dtype(_NP_DTYPES[pt]))
            elif _plain_only(plans[c]):
                # one pipelined stream across every row group's spans
                out[c] = _stream_spans(
                    scanner, ds, fh,
                    (s for p in plans[c] for s in p.spans),
                    plans[c][0].physical_type)
            else:
                parts = [_assemble_chunk(scanner, ds, fh, plan, dev)
                         for plan in plans[c]]
                out[c] = (parts[0] if len(parts) == 1
                          else jnp.concatenate(parts))
    finally:
        scanner.engine.close(fh)
    return out


# ---------------------------------------------------------------------------
# dictionary-code scans of BYTE_ARRAY (string) columns
#
# PG-Strom's trick for GROUP BY over strings: never materialize the
# strings on the accelerator — group by the dictionary CODE (an int32)
# and map codes back to labels on the host, where the dictionary page
# (tiny, one per chunk) already lives.  Payload economics: the device
# sees 4 bytes per row regardless of string length.


@dataclass(frozen=True)
class DictCodeChunk:
    """One chunk of a dictionary-coded BYTE_ARRAY column."""
    parts: Tuple[PagePart, ...]            # all kind "dict"
    num_values: int
    dict_span: Tuple[int, int]             # raw dictionary page body
    dict_count: int


def dict_code_eligible(meta, rg: int, ci: int) -> Optional[str]:
    """None if the chunk can scan as dictionary codes, else the reason.

    A footer-level check only — a chunk whose writer overflowed to
    PLAIN BYTE_ARRAY data pages (undetectable from the footer) fails
    later in :func:`plan_dict_code_chunk`."""
    col = meta.row_group(rg).column(ci)
    sc = meta.schema.column(ci)
    if col.physical_type != "BYTE_ARRAY":
        return f"physical type {col.physical_type} (need BYTE_ARRAY)"
    if (col.compression or "UNCOMPRESSED") != "UNCOMPRESSED":
        return f"compression {col.compression}"
    encs = set(col.encodings)
    if not encs <= {"PLAIN", "RLE", "PLAIN_DICTIONARY", "RLE_DICTIONARY"}:
        return f"encodings {sorted(encs)}"
    if (col.dictionary_page_offset or 0) <= 0:
        return "no dictionary page"
    if sc.max_repetition_level != 0:
        return "repeated field"
    if sc.max_definition_level > 0:
        st = col.statistics
        if st is None or st.null_count is None:
            return "no null statistics"
        if st.null_count != 0:
            return f"{st.null_count} nulls"
    return None


def plan_dict_code_chunk(meta, rg: int, ci: int, raw_read) -> DictCodeChunk:
    """Page-walk a BYTE_ARRAY chunk: dictionary page body span + index
    stream spans.  Raises ValueError on any PLAIN data page (dictionary
    overflow) — string bytes cannot decode on device."""
    col = meta.row_group(rg).column(ci)
    sc = meta.schema.column(ci)
    has_def = sc.max_definition_level > 0
    parts: List[PagePart] = []
    dict_span = None
    dict_count = 0
    for pos, ph in _walk_pages(col, raw_read):
        if ph.type in (_PAGE_DATA, _PAGE_DATA_V2):
            if ph.encoding not in _DICT_ENCODINGS:
                raise ValueError(
                    f"page at {pos}: encoding {ph.encoding} — string "
                    f"chunk fell back from dictionary (overflow?)")
            if dict_span is None:
                raise ValueError(
                    f"page at {pos}: dict-encoded data page before "
                    f"any dictionary page")
            lb = _level_bytes(pos, ph, has_def, raw_read)
            parts.append(_index_stream_part(pos, ph, lb, raw_read))
        elif ph.type == _PAGE_DICTIONARY:
            _check_dict_page(pos, ph, dict_span is not None)
            # var-len strings: the span is the whole page body; entry
            # lengths are parsed from it host-side
            dict_span = (pos + ph.header_len, ph.compressed_size)
            dict_count = ph.num_values
    if dict_span is None:
        raise ValueError(f"rg{rg} col{ci}: no dictionary page")
    return DictCodeChunk(tuple(parts), col.num_values, dict_span,
                         dict_count)


def parse_byte_array_dict(buf: bytes, count: int) -> List[bytes]:
    """PLAIN BYTE_ARRAY dictionary page body → label list
    (``<u32 len><bytes>`` repeated ``count`` times)."""
    out: List[bytes] = []
    pos = 0
    for _ in range(count):
        if pos + 4 > len(buf):
            raise ValueError("truncated dictionary page (length prefix)")
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        if pos + n > len(buf):
            raise ValueError("truncated dictionary page (entry bytes)")
        out.append(bytes(buf[pos:pos + n]))
        pos += n
    return out


def read_dict_key_column(scanner, column: str, device=None,
                         row_groups=None):
    """Prepare a BYTE_ARRAY column for on-device GROUP BY by code.

    Returns ``(labels, iter_codes)``: ``labels`` is the GLOBAL label
    list (union of EVERY row group's dictionary, first-seen order;
    bytes objects — stable across pruned and unpruned queries),
    ``iter_codes()`` yields one int32 device array of global codes per
    row group in ``row_groups`` (default: all).

    Two-pass: dictionary pages are read first (through the engine,
    host-touched by design → counted as bounce) so the global label
    space is known before any data streams — per-row-group dictionaries
    are remapped to global codes ON DEVICE via a gather.
    """
    import jax
    from nvme_strom_tpu.ops.bridge import host_to_device

    meta = scanner.metadata
    name_to_ci = {meta.schema.column(i).name: i
                  for i in range(meta.num_columns)}
    if column not in name_to_ci:
        raise KeyError(f"column {column!r} not in schema")
    ci = name_to_ci[column]
    import os
    with open(scanner.path, "rb") as f:
        def raw_read(off: int, ln: int) -> bytes:
            return os.pread(f.fileno(), ln, off)

        chunks = []
        for rg in range(meta.num_row_groups):
            why = dict_code_eligible(meta, rg, ci)
            if why is not None:
                raise ValueError(
                    f"rg{rg}.{column} not dict-code-eligible: {why}")
            chunks.append(plan_dict_code_chunk(meta, rg, ci, raw_read))

    dev = device or jax.local_devices()[0]
    eng = scanner.engine
    labels: List[bytes] = []
    gid: Dict[bytes, int] = {}
    remaps: List["object"] = []       # per-rg int32 device remap arrays
    import numpy as np
    fh = eng.open(scanner.path)
    try:
        for ch in chunks:
            body = _read_span_bytes(eng, fh, *ch.dict_span)
            local = parse_byte_array_dict(body, ch.dict_count)
            remap = np.empty(max(ch.dict_count, 1), np.int32)
            for i, lab in enumerate(local):
                if lab not in gid:
                    gid[lab] = len(labels)
                    labels.append(lab)
                remap[i] = gid[lab]
            remaps.append(host_to_device(eng, remap, dev))
    finally:
        eng.close(fh)

    selected = (range(len(chunks)) if row_groups is None
                else list(row_groups))

    def iter_codes():
        import jax.numpy as jnp
        fh = eng.open(scanner.path)
        try:
            for rg in selected:
                ch, remap_dev = chunks[rg], remaps[rg]
                idx = _decode_indices(eng, fh, ch.parts, ch.dict_count,
                                      dev)
                # local code → global code, on device
                yield jnp.take(remap_dev, host_to_device(eng, idx, dev))
        finally:
            eng.close(fh)

    return labels, iter_codes


def iter_plain_row_groups_to_device(scanner, columns: Sequence[str],
                                    device=None, plans=None,
                                    row_groups=None):
    """Yield {name: device array} per (selected) row group — the
    incremental form sql_groupby folds over, so device memory holds one
    row group of columns at a time regardless of table size.  ``plans``
    lets callers reuse a prior :func:`plan_columns` walk;
    ``row_groups`` restricts to a pruned subset (statistics-based scan
    elimination — skipped chunks never leave the SSD)."""
    import jax
    from nvme_strom_tpu.ops.bridge import DeviceStream

    dev = device or jax.local_devices()[0]
    plans = plans or plan_columns(scanner, columns)
    ds = DeviceStream(scanner.engine, device=dev,
                      depth=scanner.engine.config.queue_depth)
    fh = scanner.engine.open(scanner.path)
    try:
        groups = (range(scanner.metadata.num_row_groups)
                  if row_groups is None else row_groups)
        for rg in groups:
            out = {}
            for c in columns:
                plan = plans[c][rg]
                if _plain_only([plan]):
                    out[c] = _stream_spans(scanner, ds, fh, plan.spans,
                                           plan.physical_type)
                else:
                    out[c] = _assemble_chunk(scanner, ds, fh, plan, dev)
            yield out
    finally:
        scanner.engine.close(fh)
