"""Direct Parquet column decode: NVMe pages → device, no pyarrow on the hot
path.

PG-Strom's distinguishing move is decoding table blocks ON the accelerator
(SURVEY.md §3.5) — the CPU plans, the device decodes.  The Parquet analogue
for uncompressed, fixed-width columns, two page shapes:

- **PLAIN** data pages: host (metadata-class I/O, tiny) parses the footer
  (already held by the scanner) and each data-page header — a minimal
  Thrift compact-protocol reader, ~40 bytes per page — to compute the
  exact byte spans of raw little-endian values inside the file; the spans
  stream through the O_DIRECT engine and DeviceStream (staging → HBM, zero
  host-side payload copies), and the 'decode' is an on-device bitcast +
  concatenate.  Optional columns with no nulls carry an RLE
  definition-level block per page; its length is read host-side (8 bytes)
  and the span simply starts after it.
- **Dictionary-encoded** (PLAIN_DICTIONARY / RLE_DICTIONARY) chunks, the
  PG-Strom dictionary pattern: the dictionary page's PLAIN values stream
  O_DIRECT → device exactly like a plain span, the data pages'
  RLE/bit-packed index stream is read through the engine and expanded
  host-side with a vectorized numpy decoder (runs are sequential
  bitstream control flow — host work by nature; the decoded index array
  is honestly counted as bounce), and the final decode is an on-device
  ``take(dictionary, indices)`` gather.  Chunks where the writer fell
  back to PLAIN mid-stream (dictionary overflow) assemble both kinds in
  page order.

- **Compressed** chunks (SNAPPY / ZSTD / GZIP / BROTLI / LZ4_RAW) stay on
  the direct path: the compressed page spans ride O_DIRECT through the
  engine exactly like plain spans (less disk traffic — compressed size),
  the host decompresses each page body (pyarrow's codec library; the
  decompressed bytes are honestly counted as bounce — codecs are
  sequential bitstream control flow, host work by nature), and the value
  decode (bitcast / dictionary gather) still happens on device.  v2 data
  pages keep their level blocks uncompressed ahead of the values region
  (and may mark individual pages ``is_compressed=false``); v1 pages
  compress levels+values together, so their levels parse from the
  decompressed body.
- **Nulls** (``nulls="mask"``): definition levels decode host-side
  (plan time when raw, decode time inside compressed v1 bodies) into a
  per-page validity mask; dense non-null values take their normal path
  (zero-copy stream when uncompressed!) and a cumsum-gather ON DEVICE
  scatters them to full page length, null slots zero-filled.  Consumers
  get ``(values, mask)`` pairs.

Everything else — exotic codecs (legacy framed LZ4), strings outside the
dict-code scan, nested/repeated schemas — falls back to the pyarrow path
in :mod:`.parquet`, which decodes on host and honestly counts the
handoff copy as bounce.

Why not decode the index bitstream on device too?  RLE runs are
variable-length sequential control flow; a Pallas cursor over them would
serialize (one varint at a time) — exactly what the MXU/VPU are worst
at.  The expensive expansion (indices → values) IS on device: the gather
reads only index ints host-side, never payload values.
"""

from __future__ import annotations

import functools
import struct
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

#: the QoS latency class every sql/ payload read rides (io/sched.py):
#: analytics scans dispatch below serving decode/restore/prefetch and
#: above scrub, so a partition-parallel table scan is governed by the
#: scheduler's fair-share instead of competing as anonymous bulk
SCAN_CLASS = "scan"

# Parquet physical types that are raw fixed-width little-endian under PLAIN
_WIDTHS = {"INT32": 4, "INT64": 8, "FLOAT": 4, "DOUBLE": 8}
_NP_DTYPES = {"INT32": "<i4", "INT64": "<i8", "FLOAT": "<f4",
              "DOUBLE": "<f8"}

# Thrift compact-protocol wire types
_CT_STOP = 0
_CT_BOOL_TRUE = 1
_CT_BOOL_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12

# parquet-format enums
_PAGE_DATA = 0
_PAGE_DICTIONARY = 2
_PAGE_DATA_V2 = 3
_ENC_PLAIN = 0
_ENC_PLAIN_DICTIONARY = 2
_ENC_RLE = 3
_ENC_RLE_DICTIONARY = 8
_ENC_BYTE_STREAM_SPLIT = 9
_DICT_ENCODINGS = (_ENC_PLAIN_DICTIONARY, _ENC_RLE_DICTIONARY)


class ThriftError(ValueError):
    """Malformed/truncated Thrift compact data (or not enough bytes read —
    callers retry with a bigger window before giving up)."""


class _Compact:
    """Just enough of the Thrift compact protocol to read a Parquet
    PageHeader: varints, zigzag, field headers, and recursive skip.
    parquet-format/src/main/thrift/parquet.thrift defines the schema; the
    reference consumes the same metadata via its SQL host code."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _byte(self) -> int:
        if self.pos >= len(self.buf):
            raise ThriftError("truncated")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        out = shift = 0
        while True:
            b = self._byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7
            if shift > 63:
                raise ThriftError("varint overflow")

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_field_header(self, last_id: int) -> Tuple[int, int]:
        """→ (wire_type, field_id); wire_type 0 = stop."""
        b = self._byte()
        if b == _CT_STOP:
            return 0, 0
        delta, ctype = b >> 4, b & 0x0F
        fid = last_id + delta if delta else self.zigzag()
        return ctype, fid

    def skip(self, ctype: int) -> None:
        if ctype in (_CT_BOOL_TRUE, _CT_BOOL_FALSE):
            return
        if ctype == _CT_BYTE:
            self._byte()
        elif ctype in (_CT_I16, _CT_I32, _CT_I64):
            self.varint()
        elif ctype == _CT_DOUBLE:
            self.pos += 8
            if self.pos > len(self.buf):
                raise ThriftError("truncated")
        elif ctype == _CT_BINARY:
            n = self.varint()
            self.pos += n
            if self.pos > len(self.buf):
                raise ThriftError("truncated")
        elif ctype in (_CT_LIST, _CT_SET):
            b = self._byte()
            n, et = b >> 4, b & 0x0F
            if n == 15:
                n = self.varint()
            # bool elements consume ZERO bytes per skip — an unbounded
            # count from malformed input would spin forever; any honest
            # collection needs at least... well, bools need nothing, so
            # bound by what the buffer could possibly hold
            if n > len(self.buf) - self.pos:
                raise ThriftError(f"collection count {n} exceeds buffer")
            for _ in range(n):
                self.skip(et)
        elif ctype == _CT_MAP:
            n = self.varint()
            if n > len(self.buf) - self.pos:
                raise ThriftError(f"map count {n} exceeds buffer")
            if n:
                b = self._byte()
                kt, vt = b >> 4, b & 0x0F
                for _ in range(n):
                    self.skip(kt)
                    self.skip(vt)
        elif ctype == _CT_STRUCT:
            last = 0
            while True:
                t, fid = self.read_field_header(last)
                if t == 0:
                    return
                last = fid
                self.skip(t)
        else:
            raise ThriftError(f"bad compact type {ctype}")


@dataclass(frozen=True)
class PageHeader:
    type: int
    compressed_size: int
    uncompressed_size: int
    num_values: int          # data/dictionary pages (0 otherwise)
    encoding: int            # data/dictionary pages (-1 otherwise)
    header_len: int          # bytes the Thrift header itself occupies
    # DataPageHeaderV2 states the level-block lengths explicitly (a v1
    # reader must instead parse RLE length prefixes from the page body)
    def_levels_len: int = 0
    rep_levels_len: int = 0
    # DataPageHeaderV2 field 7: false = the values region is stored RAW
    # even though the chunk declares a codec (writers skip codecs that
    # don't pay — pyarrow does this routinely for dict index streams)
    v2_is_compressed: bool = True


def parse_page_header(buf: bytes) -> PageHeader:
    """Parse a PageHeader at buf[0].  Raises ThriftError if ``buf`` is too
    short (callers re-read with a larger window)."""
    c = _Compact(buf)
    ptype = comp = uncomp = -1
    num_values, encoding = 0, -1
    def_len = rep_len = 0
    v2_compressed = True
    last = 0
    while True:
        t, fid = c.read_field_header(last)
        if t == 0:
            break
        last = fid
        if fid == 1 and t == _CT_I32:
            ptype = c.zigzag()
        elif fid == 2 and t == _CT_I32:
            uncomp = c.zigzag()
        elif fid == 3 and t == _CT_I32:
            comp = c.zigzag()
        elif fid in (5, 7, 8) and t == _CT_STRUCT:
            # DataPageHeader (v1) / DictionaryPageHeader / DataPageHeaderV2
            inner_last = 0
            while True:
                it, ifid = c.read_field_header(inner_last)
                if it == 0:
                    break
                inner_last = ifid
                if ifid == 1 and it == _CT_I32:
                    num_values = c.zigzag()
                elif ifid == 2 and it == _CT_I32 and fid in (5, 7):
                    encoding = c.zigzag()
                elif ifid == 4 and it == _CT_I32 and fid == 8:
                    encoding = c.zigzag()
                elif ifid == 5 and it == _CT_I32 and fid == 8:
                    def_len = c.zigzag()
                elif ifid == 6 and it == _CT_I32 and fid == 8:
                    rep_len = c.zigzag()
                elif (ifid == 7 and fid == 8
                      and it in (_CT_BOOL_TRUE, _CT_BOOL_FALSE)):
                    # bool struct fields carry the value in the type nibble
                    v2_compressed = it == _CT_BOOL_TRUE
                else:
                    c.skip(it)
        else:
            c.skip(t)
    if ptype < 0 or comp < 0:
        raise ThriftError("missing required PageHeader fields")
    return PageHeader(ptype, comp, uncomp, num_values, encoding, c.pos,
                      def_len, rep_len, v2_compressed)


@dataclass(frozen=True)
class PagePart:
    """One data page's decodable payload within a column chunk.

    kind "plain": ``span`` covers raw little-endian values (on-device
    bitcast).  kind "dict": ``span`` covers the RLE/bit-packed index
    stream (host-expanded, then on-device gather against the chunk's
    dictionary); ``bit_width`` is the stream's index width.  kind
    "bss": BYTE_STREAM_SPLIT — ``span`` covers the byte-transposed
    values (decode is an on-device reshape/transpose/bitcast, zero
    host-touched payload like plain).

    ``codec`` != None: ``span`` covers COMPRESSED bytes — the engine
    still reads them O_DIRECT, but the host must decompress before the
    on-device decode (counted as bounce; see module docstring).  v1
    pages compress levels+values together, so a compressed v1 page with
    definition levels sets ``inline_levels`` and its levels are parsed
    from the decompressed body; every other layout resolves its levels
    at PLAN time into ``mask``/``n_valid``.  ``mask`` (len num_values,
    True = non-null) is None when every value is present; masked pages
    scatter their dense values on device.
    """
    kind: str                              # "plain" | "dict" | "bss"
    span: Tuple[int, int]                  # (offset, length) into the file
    num_values: int                        # values INCLUDING nulls
    bit_width: int = 0                     # dict parts (-1 = in codec body)
    codec: Optional[str] = None            # Parquet codec name
    uncompressed_len: int = 0              # decompressed span length
    inline_levels: bool = False            # v1+codec: levels in the body
    max_def: int = 0                       # schema max definition level
    n_valid: int = -1                      # -1 = num_values (no nulls)
    mask: Optional[object] = None          # np.bool_ mask, plan-time known

    @property
    def valid_count(self) -> int:
        return self.num_values if self.n_valid < 0 else self.n_valid

    @property
    def is_raw(self) -> bool:
        """Payload can ride staging→device untouched (no host decode)."""
        return self.codec is None and self.mask is None


@dataclass(frozen=True)
class ColumnPlan:
    """Decodable page layout of one column chunk (one row group)."""
    parts: Tuple[PagePart, ...]            # in file/page order
    num_values: int
    physical_type: str
    dict_span: Optional[Tuple[int, int]] = None   # PLAIN dictionary values
    dict_count: int = 0
    dict_codec: Optional[str] = None       # dictionary page's codec
    dict_uncompressed_len: int = 0

    @property
    def spans(self) -> Tuple[Tuple[int, int], ...]:
        """Plain value-byte spans (the pre-dictionary API surface)."""
        return tuple(p.span for p in self.parts if p.kind == "plain")


# Parquet codec name → pyarrow codec name.  pyarrow here is a CODEC
# LIBRARY only (snappy/zstd/... C++ decompressors) — the page walk,
# span planning, and value decode stay this module's own.  Legacy
# hadoop-framed "LZ4" is intentionally absent (ambiguous framing);
# it falls back to the pyarrow reader path.
_CODECS = {"SNAPPY": "snappy", "GZIP": "gzip", "ZSTD": "zstd",
           "BROTLI": "brotli", "LZ4_RAW": "lz4_raw"}


def _codec_of(col) -> Optional[str]:
    """Column chunk's codec name, None when uncompressed."""
    name = col.compression or "UNCOMPRESSED"
    return None if name == "UNCOMPRESSED" else name


def _codec_available(name: str) -> bool:
    if name not in _CODECS:
        return False
    import pyarrow as pa
    return pa.Codec.is_available(_CODECS[name])


def _decompress(codec: str, buf, out_len: int) -> memoryview:
    """Host page decompression via the pyarrow codec library.  Returns a
    memoryview over the codec's output buffer (no extra copy)."""
    import pyarrow as pa
    out = pa.Codec(_CODECS[codec]).decompress(bytes(buf), out_len)
    mv = memoryview(out)
    if mv.nbytes != out_len:
        raise ValueError(
            f"codec {codec}: decompressed {mv.nbytes} bytes, header "
            f"promised {out_len}")
    return mv


def eligible_chunk(meta, rg: int, ci: int,
                   allow_nulls: bool = False) -> Optional[str]:
    """None if the (row group, column) chunk can decode on device, else a
    human-readable reason for the pyarrow fallback (surfaced in stats).

    ``allow_nulls``: chunks with (possible) nulls are eligible — the
    plan decodes definition levels and decode scatters on device; the
    caller must consume (values, mask) pairs."""
    col = meta.row_group(rg).column(ci)
    sc = meta.schema.column(ci)
    if col.physical_type not in _WIDTHS:
        return f"physical type {col.physical_type}"
    if _WIDTHS[col.physical_type] == 8:
        import jax
        if not jax.config.jax_enable_x64:
            # the on-device bitcast would silently truncate i64/f64
            return (f"{col.physical_type} needs jax_enable_x64 "
                    f"(bitcast would truncate)")
    codec = _codec_of(col)
    if codec is not None and not _codec_available(codec):
        return f"compression {col.compression}"
    encs = set(col.encodings)
    if not encs <= {"PLAIN", "RLE", "PLAIN_DICTIONARY", "RLE_DICTIONARY",
                    "BYTE_STREAM_SPLIT"}:
        return f"encodings {sorted(encs)}"
    if sc.max_repetition_level != 0:
        return "repeated field"
    if sc.max_definition_level > 0 and not allow_nulls:
        st = col.statistics
        if st is None or st.null_count is None:
            return "no null statistics"
        if st.null_count != 0:
            return f"{st.null_count} nulls (pass nulls='mask')"
    return None


def _walk_pages(col, raw_read):
    """Yield (pos, PageHeader) for every page of a column chunk, until
    the data pages' value counts cover ``col.num_values``.

    ``raw_read(offset, length) -> bytes`` serves page headers —
    metadata-class reads (≤ ~1 KiB per page, via buffered I/O like the
    footer), never payload."""
    pos = col.data_page_offset
    if (col.dictionary_page_offset or 0) > 0:
        # the dictionary page precedes the data pages in the chunk
        pos = min(pos, col.dictionary_page_offset)
    end = pos + col.total_compressed_size
    remaining = col.num_values
    window = 1 << 10
    while remaining > 0:
        if pos >= end:
            raise ValueError(f"page walk ran past chunk end at {pos}")
        buf = raw_read(pos, min(window, end - pos))
        while True:
            try:
                ph = parse_page_header(buf)
                break
            except ThriftError:
                if len(buf) >= end - pos:
                    raise
                buf = raw_read(pos, min(len(buf) * 2, end - pos))
        if ph.type in (_PAGE_DATA, _PAGE_DATA_V2):
            if ph.num_values > remaining:
                # RLE can legally pack huge claimed counts into a few
                # bytes — an unbounded count would drive a huge host
                # allocation in the index decoder (and silently
                # over-long plain output)
                raise ValueError(
                    f"page at {pos}: {ph.num_values} values exceeds "
                    f"chunk remainder {remaining}")
            remaining -= ph.num_values
        yield pos, ph
        pos += ph.header_len + ph.compressed_size


def _plan_levels(pos, ph, max_def: int, raw_read, may_null: bool):
    """Levels of an UNCOMPRESSED-levels page → (level_bytes, mask|None).

    v2 stores levels uncompressed regardless of the chunk codec; v1
    callers must only pass pages whose body is raw (a compressed v1
    page parses its levels from the decompressed body instead —
    ``inline_levels``).  ``may_null`` False skips the decode (statistics
    already proved every value present).  mask is None when all valid.
    """
    import numpy as np
    bw = max_def.bit_length()
    if ph.type == _PAGE_DATA_V2:
        lb = ph.def_levels_len + ph.rep_levels_len
        if not (may_null and ph.def_levels_len):
            return lb, None
        buf = raw_read(pos + ph.header_len + ph.rep_levels_len,
                       ph.def_levels_len)
        lev = decode_rle_hybrid(buf, bw, ph.num_values)
    else:
        if max_def == 0:
            return 0, None
        (n,) = struct.unpack("<I", raw_read(pos + ph.header_len, 4))
        lb = 4 + n
        if not may_null:
            return lb, None
        lev = decode_rle_hybrid(raw_read(pos + ph.header_len + 4, n),
                                bw, ph.num_values)
    mask = lev == max_def
    return lb, (None if mask.all() else np.asarray(mask))


def _index_stream_part(pos, ph, level_bytes: int, raw_read,
                       max_def: int = 0, n_valid: int = -1,
                       mask=None) -> PagePart:
    """Dict-encoded data-page body → index-stream PagePart.

    Body after levels: ``<bit_width: 1 byte><RLE-hybrid runs>`` — the
    one layout rule both the numeric and byte-array walks share.  Only
    valid for RAW bodies (compressed pages read their bit-width from
    the decompressed body at decode time)."""
    val_off = pos + ph.header_len + level_bytes
    (bw,) = raw_read(val_off, 1)
    if bw > 32:
        raise ValueError(f"page at {pos}: bit width {bw} > 32")
    idx_len = ph.compressed_size - level_bytes - 1
    if idx_len < 0:
        raise ValueError(f"page at {pos}: negative index span")
    return PagePart("dict", (val_off + 1, idx_len), ph.num_values,
                    bit_width=bw, max_def=max_def, n_valid=n_valid,
                    mask=mask)


def _check_dict_page(pos, ph, already_seen: bool) -> None:
    """Shared dictionary-page validity rules (one per chunk, PLAIN)."""
    if already_seen:
        raise ValueError(f"second dictionary page at {pos}")
    if ph.encoding not in (_ENC_PLAIN, _ENC_PLAIN_DICTIONARY):
        raise ValueError(
            f"dictionary page encoding {ph.encoding} not PLAIN")


def plan_chunk(meta, rg: int, ci: int, raw_read,
               allow_nulls: bool = False) -> ColumnPlan:
    """Walk the chunk's data pages, returning exact value-byte spans.

    ``raw_read`` as in :func:`_walk_pages`; it additionally serves the
    v1 RLE level-length prefixes and — when nulls are possible and
    allowed — the (always-uncompressed-accessible) level blocks, which
    decode to per-page masks at plan time.  Compressed chunks emit
    codec-tagged parts whose spans cover the compressed bytes; a
    compressed v1 page with definition levels defers its level parse to
    decode time (``inline_levels`` — v1 compresses levels and values
    together)."""
    col = meta.row_group(rg).column(ci)
    sc = meta.schema.column(ci)
    width = _WIDTHS[col.physical_type]
    max_def = sc.max_definition_level
    codec = _codec_of(col)
    st = col.statistics
    # statistics can PROVE the chunk null-free; anything else (nulls
    # recorded, or no stats at all) must consult the levels
    may_null = (max_def > 0
                and (st is None or st.null_count is None
                     or st.null_count != 0))
    if may_null and not allow_nulls:
        raise ValueError(
            f"rg{rg} col{ci}: possible nulls (pass nulls='mask')")
    parts: List[PagePart] = []
    dict_span: Optional[Tuple[int, int]] = None
    dict_count = 0
    dict_codec: Optional[str] = None
    dict_ulen = 0
    for pos, ph in _walk_pages(col, raw_read):
        if ph.type in (_PAGE_DATA, _PAGE_DATA_V2):
            v2 = ph.type == _PAGE_DATA_V2
            page_codec = codec
            if v2 and not ph.v2_is_compressed:
                page_codec = None
            if ph.encoding not in (_ENC_PLAIN, _ENC_BYTE_STREAM_SPLIT,
                                   *_DICT_ENCODINGS):
                raise ValueError(
                    f"page at {pos}: unsupported encoding {ph.encoding}")
            kind = {_ENC_PLAIN: "plain",
                    _ENC_BYTE_STREAM_SPLIT: "bss"}.get(ph.encoding, "dict")
            if kind == "dict" and dict_span is None:
                raise ValueError(
                    f"page at {pos}: dict-encoded data page before "
                    f"any dictionary page")
            if page_codec is not None and not v2:
                # v1: levels+values compressed as one body — the span is
                # the whole body, levels resolve after decompression
                # inline_levels whenever the schema has def levels: even
                # a proven null-free page carries the level block and the
                # decoder must parse past it (mask collapses to None)
                parts.append(PagePart(
                    kind, (pos + ph.header_len, ph.compressed_size),
                    ph.num_values, bit_width=-1, codec=page_codec,
                    uncompressed_len=ph.uncompressed_size,
                    inline_levels=max_def > 0, max_def=max_def))
                continue
            # levels are addressable raw: v1-uncompressed in the body,
            # v2 always uncompressed ahead of the values region
            lb, mask = _plan_levels(pos, ph, max_def, raw_read, may_null)
            n_valid = int(mask.sum()) if mask is not None else -1
            vc = ph.num_values if n_valid < 0 else n_valid
            val_off = pos + ph.header_len + lb
            val_len = ph.compressed_size - lb
            if page_codec is not None:      # compressed v2 values region
                parts.append(PagePart(
                    kind, (val_off, val_len), ph.num_values,
                    bit_width=-1, codec=page_codec,
                    uncompressed_len=ph.uncompressed_size - lb,
                    max_def=max_def, n_valid=n_valid, mask=mask))
                continue
            if kind in ("plain", "bss"):
                want = vc * width
                if want + lb > ph.compressed_size:
                    raise ValueError(
                        f"page at {pos}: {vc} values x {width} + {lb} "
                        f"level bytes > page size {ph.compressed_size}")
                parts.append(PagePart(kind, (val_off, want),
                                      ph.num_values, max_def=max_def,
                                      n_valid=n_valid, mask=mask))
            else:
                parts.append(_index_stream_part(
                    pos, ph, lb, raw_read, max_def=max_def,
                    n_valid=n_valid, mask=mask))
        elif ph.type == _PAGE_DICTIONARY:
            _check_dict_page(pos, ph, dict_span is not None)
            if codec is not None:
                dict_span = (pos + ph.header_len, ph.compressed_size)
                dict_codec = codec
                dict_ulen = ph.uncompressed_size
                if ph.num_values * width > ph.uncompressed_size:
                    raise ValueError(
                        f"dictionary page at {pos}: {ph.num_values} "
                        f"values x {width} > uncompressed size "
                        f"{ph.uncompressed_size}")
            else:
                val_len = ph.num_values * width
                if val_len > ph.compressed_size:
                    raise ValueError(
                        f"dictionary page at {pos}: {ph.num_values} "
                        f"values x {width} > page size "
                        f"{ph.compressed_size}")
                dict_span = (pos + ph.header_len, val_len)
            dict_count = ph.num_values
        # INDEX pages are skipped silently
    return ColumnPlan(tuple(parts), col.num_values, col.physical_type,
                      dict_span=dict_span, dict_count=dict_count,
                      dict_codec=dict_codec,
                      dict_uncompressed_len=dict_ulen)


def decode_rle_hybrid(buf: bytes, bit_width: int, count: int):
    """Parquet RLE/bit-packed hybrid stream → int32 index array (host).

    The stream is a sequence of runs, each headed by a varint: low bit 1
    → bit-packed run of ``(header >> 1) * 8`` values (``bit_width`` bits
    each, LSB-first little-endian — decoded vectorized via
    ``np.unpackbits``); low bit 0 → RLE run of ``header >> 1`` copies of
    one ``ceil(bit_width / 8)``-byte value.  The final run may carry
    padding values past ``count``; they are discarded per the spec.
    """
    import numpy as np
    out = np.empty(count, np.int32)
    if bit_width == 0:
        # zero-width indices: a single-entry dictionary, all index 0
        out[:] = 0
        return out
    byte_w = (bit_width + 7) // 8
    weights = (np.int64(1) << np.arange(bit_width, dtype=np.int64))
    pos, filled, n = 0, 0, len(buf)
    while filled < count:
        header = shift = 0
        while True:
            if pos >= n:
                raise ValueError("truncated RLE stream header")
            b = buf[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 35:
                raise ValueError("RLE header varint overflow")
        if header & 1:                       # bit-packed run
            groups = header >> 1
            nbytes = groups * bit_width      # groups of 8 values
            if pos + nbytes > n:
                raise ValueError("truncated bit-packed run")
            bits = np.unpackbits(
                np.frombuffer(buf, np.uint8, nbytes, pos),
                bitorder="little")
            vals = bits.reshape(-1, bit_width).astype(np.int64) @ weights
            take = min(groups * 8, count - filled)
            out[filled:filled + take] = vals[:take]
            filled += take
            pos += nbytes
        else:                                # RLE run
            run = header >> 1
            if run == 0:
                raise ValueError("zero-length RLE run")
            if pos + byte_w > n:
                raise ValueError("truncated RLE run value")
            v = int.from_bytes(buf[pos:pos + byte_w], "little")
            pos += byte_w
            take = min(run, count - filled)
            out[filled:filled + take] = v
            filled += take
    return out


def plan_columns(scanner, columns: Sequence[str],
                 allow_nulls: bool = False
                 ) -> Dict[str, List[ColumnPlan]]:
    """Page-walk every (row group, column) chunk → value spans.  Raises
    ValueError naming the first non-eligible chunk — callers wanting a
    soft answer use :func:`eligible_chunk` first."""
    import os
    meta = scanner.metadata
    name_to_ci = {meta.schema.column(i).name: i
                  for i in range(meta.num_columns)}
    with open(scanner.path, "rb") as f:
        def raw_read(off: int, ln: int) -> bytes:
            return os.pread(f.fileno(), ln, off)

        plans: Dict[str, List[ColumnPlan]] = {c: [] for c in columns}
        for rg in range(meta.num_row_groups):
            for c in columns:
                ci = name_to_ci[c]
                why = eligible_chunk(meta, rg, ci,
                                     allow_nulls=allow_nulls)
                if why is not None:
                    raise ValueError(
                        f"rg{rg}.{c} not direct-eligible: {why}")
                plans[c].append(plan_chunk(meta, rg, ci, raw_read,
                                           allow_nulls=allow_nulls))
    return plans


def _stream_spans(scanner, ds, fh, spans, physical_type):
    """spans → one device array (on-device concat + bitcast).

    Spans larger than the engine's staging-buffer size are split into
    chunk-sized sub-ranges first (writers like parquet-mr can emit pages
    bigger than chunk_bytes; the on-device concat makes the split
    invisible)."""
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bridge import split_ranges
    ranges, _ = split_ranges(spans, scanner.engine.config.chunk_bytes)
    parts = list(ds.stream_ranges(fh, ranges))
    if not parts:    # zero-row chunk: no spans to stream
        return jnp.zeros((0,), dtype=np.dtype(_NP_DTYPES[physical_type]))
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return flat.view(np.dtype(_NP_DTYPES[physical_type]))


def _stream_raw_groups(scanner, ds, fh, spans):
    """spans → one uint8 device array PER SPAN, all spans streamed as a
    single pipelined range sequence (sub-chunk split like
    :func:`_stream_spans`, but span boundaries preserved — BSS pages
    decode per page)."""
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bridge import split_ranges
    flat, counts = split_ranges(spans, scanner.engine.config.chunk_bytes)
    it = ds.stream_ranges(fh, flat)
    outs = []
    for n in counts:
        group = [next(it) for _ in range(n)]
        if not group:            # zero-length span (0-value page)
            outs.append(jnp.zeros((0,), dtype=np.uint8))
        else:
            outs.append(group[0] if n == 1 else jnp.concatenate(group))
    return outs


def _index_from_body(body, count: int):
    """Dict index stream after levels: ``<bit_width byte><RLE runs>`` —
    the one decode rule every compressed-body consumer shares."""
    bw = body[0]
    if bw > 32:
        raise ValueError(f"bit width {bw} > 32")
    return decode_rle_hybrid(bytes(body[1:]), bw, count)


def _decode_one_index_stream(eng, fh, p: PagePart, dev):
    """One dict-kind PagePart → int32 host index array, handling raw
    spans (bit_width known at plan time) and compressed bodies
    (decompress, parse the v1 inline level block, read bit_width from
    the body).  Nulls are rejected — callers on this path planned the
    chunk null-free (masked dict parts go through
    :func:`_decode_special_part`)."""
    buf = _read_span_bytes(eng, fh, *p.span)
    if p.codec is None:
        return decode_rle_hybrid(buf, p.bit_width, p.valid_count)
    body = _decompress(p.codec, buf, p.uncompressed_len)
    if dev.platform != "cpu":
        eng.stats.add(bounce_bytes=p.uncompressed_len)
    n_valid = p.valid_count
    if p.inline_levels:
        body, mask, n_valid = _inline_levels(body, p)
        if mask is not None:
            raise ValueError(
                "unexpected nulls in a chunk planned null-free")
    return _index_from_body(body, n_valid)


def _indices_to_device(eng, fh, parts, dict_count: int, dev):
    """Dict-kind PageParts → one validated int32 DEVICE index array.

    Prefers the on-device bit-unpack (ops/bitunpack.py — round-2
    verdict #5): the host parses only run headers, bit-packed bytes
    unpack with shifts/masks on the VPU, RLE runs are ``jnp.full`` —
    no expanded index array ever exists host-side, so the only
    payload-class host traffic is the engine read of the raw stream.
    Each span is read ONCE: pages the device path declines
    (pathological run counts, bw > 24) host-decode from the same
    buffer; compressed bodies go through
    :func:`_decode_one_index_stream`.  Host-expanded arrays keep the
    module's accounting policy (bounce on non-CPU; the CPU device_put
    alias copy counts it there).  The range check (corrupt-stream
    honesty — ``jnp.take`` would silently clip into wrong rows) costs
    one scalar sync per chunk."""
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bitunpack import rle_hybrid_batch_to_device
    from nvme_strom_tpu.ops.bridge import host_to_device

    def put_host_idx(idx):
        if dev.platform != "cpu":
            eng.stats.add(bounce_bytes=int(idx.nbytes))
        return host_to_device(eng, idx, dev)

    dev_parts = []
    raw_batch = []     # consecutive raw pages decode as ONE program

    def flush_raw():
        # three device ops for the whole run of adjacent raw pages,
        # instead of puts per run — a chunk that mixes raw and
        # compressed pages still batches each raw stretch
        if not raw_batch:
            return
        d = rle_hybrid_batch_to_device(raw_batch, dev, engine=eng)
        if d is not None:
            dev_parts.append(d)
        else:              # declined: host decode the same buffers
            dev_parts.extend(put_host_idx(decode_rle_hybrid(b, bw, c))
                             for b, bw, c in raw_batch)
        raw_batch.clear()

    for p in parts:
        if p.is_raw:
            raw_batch.append((_read_span_bytes(eng, fh, *p.span),
                              p.bit_width, p.valid_count))
        else:
            flush_raw()
            dev_parts.append(put_host_idx(
                _decode_one_index_stream(eng, fh, p, dev)))
    flush_raw()
    if not dev_parts:          # zero-row chunk
        return jnp.zeros((0,), jnp.int32)
    idx = (dev_parts[0] if len(dev_parts) == 1
           else jnp.concatenate(dev_parts))
    if idx.shape[0]:
        lo, hi = np.asarray(jnp.stack([idx.min(), idx.max()]))
        if lo < 0 or hi >= dict_count:
            raise ValueError(
                f"dictionary index {lo if lo < 0 else hi} out of range "
                f"[0, {dict_count})")
    return idx


def _read_span_bytes(engine, fh, off: int, ln: int) -> bytes:
    """Direct-engine read of a small control-stream span → host bytes.

    ``engine.read`` counts the staging→host copy as bounce — same rule
    as the pyarrow handoff (`parquet.EngineFile.readinto`): payload-class
    bytes a host decoder must touch.  Index streams are the small side of
    a dictionary chunk (≤ ~bit_width/8 bytes per value vs the full value
    width for the gathered output, which never exists host-side).
    """
    eng_chunk = engine.config.chunk_bytes
    parts = [engine.read(fh, pos, min(eng_chunk, off + ln - pos)).tobytes()
             for pos in range(off, off + ln, eng_chunk)]
    return parts[0] if len(parts) == 1 else b"".join(parts)


def _put_control(eng, arr, dev):
    """Host-decoded control data (masks, index arrays) → device, with
    the module's accounting policy: payload-derived host-materialized
    bytes count as bounce (on CPU ``host_to_device``'s protective copy
    counts the same buffer, so only non-CPU adds it here)."""
    from nvme_strom_tpu.ops.bridge import host_to_device
    if dev.platform != "cpu":
        eng.stats.add(bounce_bytes=int(arr.nbytes))
    return host_to_device(eng, arr, dev)


def _scatter_masked(vals_dev, mask_np, eng, dev):
    """Dense non-null values → full-length page output, ON DEVICE.

    positions = cumsum(mask)-1 maps each output slot to its dense
    source index; null slots read a garbage lane and are zeroed by the
    where().  Returns (full_values, device_mask)."""
    import jax.numpy as jnp
    m = _put_control(eng, mask_np, dev)
    pos = jnp.cumsum(m) - 1
    pad = mask_np.shape[0] - vals_dev.shape[0]
    vp = jnp.pad(vals_dev, (0, pad)) if pad > 0 else vals_dev
    return jnp.where(m, vp[jnp.clip(pos, 0)], 0), m


def _inline_levels(body, p: PagePart):
    """Parse a compressed v1 page's level block from its decompressed
    body → (values_view, mask|None, n_valid).  ``<u32 len><RLE def
    levels>``; all-valid masks collapse to None (stats may have proved
    it, or the writer padded an optional column with zero nulls)."""
    import numpy as np
    (n,) = struct.unpack_from("<I", body, 0)
    if 4 + n > len(body):
        raise ValueError("level block overruns decompressed page body")
    lev = decode_rle_hybrid(bytes(body[4:4 + n]),
                            p.max_def.bit_length(), p.num_values)
    mask = np.asarray(lev == p.max_def)
    vals = body[4 + n:]
    if mask.all():
        return vals, None, p.num_values
    return vals, mask, int(mask.sum())


def _decode_special_part(scanner, ds, fh, p: PagePart, plan, dict_dev,
                         dev):
    """One non-raw page (codec and/or mask) → (device values, mask).

    Compressed bytes ride the O_DIRECT engine, decompress on host
    (counted — see module docstring), and decode on device; raw-but-
    masked pages keep the zero-copy value stream and only the mask is
    host-decoded.  Returns full-page-length values when masked."""
    import numpy as np
    import jax.numpy as jnp
    eng = scanner.engine
    width = _WIDTHS[plan.physical_type]
    np_dtype = np.dtype(_NP_DTYPES[plan.physical_type])
    mask, n_valid = p.mask, p.valid_count

    if p.codec is not None:
        raw = _read_span_bytes(eng, fh, *p.span)
        body = _decompress(p.codec, raw, p.uncompressed_len)
        if dev.platform != "cpu":
            eng.stats.add(bounce_bytes=p.uncompressed_len)
        if p.inline_levels:
            body, mask, n_valid = _inline_levels(body, p)
        if p.kind == "dict":
            idx = _index_from_body(body, n_valid)
            _check_index_range(idx, plan.dict_count)
            vals = jnp.take(dict_dev, _put_control(eng, idx, dev))
        elif p.kind == "bss":
            u8 = _put_control(eng, np.frombuffer(body, np.uint8,
                                                 n_valid * width), dev)
            vals = (u8.reshape(width, n_valid).T.reshape(-1)
                    .view(np_dtype))
        else:
            arr = np.frombuffer(body, np_dtype, n_valid)
            from nvme_strom_tpu.ops.bridge import host_to_device
            # decompressed bytes were already counted above; the CPU
            # protective copy inside host_to_device re-counts there
            vals = host_to_device(eng, arr, dev)
    else:
        # raw values, masked: payload still streams zero-copy
        if p.kind == "dict":
            buf = _read_span_bytes(eng, fh, *p.span)
            idx = decode_rle_hybrid(buf, p.bit_width, n_valid)
            _check_index_range(idx, plan.dict_count)
            vals = jnp.take(dict_dev, _put_control(eng, idx, dev))
        elif p.kind == "bss":
            (raw,) = _stream_raw_groups(scanner, ds, fh, [p.span])
            vals = (raw.reshape(width, n_valid).T.reshape(-1)
                    .view(np_dtype))
        else:
            vals = _stream_spans(scanner, ds, fh, [p.span],
                                 plan.physical_type)
    if mask is not None:
        return _scatter_masked(vals, mask, eng, dev)
    return vals, None


def _check_index_range(idx, dict_count: int) -> None:
    if idx.size:
        lo, hi = int(idx.min()), int(idx.max())
        if lo < 0 or hi >= dict_count:
            raise ValueError(
                f"dictionary index {lo if lo < 0 else hi} out of range "
                f"[0, {dict_count})")


def _raw_dict_only(plans: Sequence[ColumnPlan]) -> bool:
    """Every row group a raw (uncompressed, null-free) dictionary-
    encoded chunk with a raw PLAIN dictionary page — the shape the
    whole-column batched path handles."""
    return all(
        plan.parts and plan.dict_span is not None
        and plan.dict_codec is None
        and all(p.kind == "dict" and p.is_raw for p in plan.parts)
        for plan in plans)


@functools.lru_cache(maxsize=1)
def _dict_combine_fn():
    """Jitted whole-column dict materialization: (concatenated dicts,
    concatenated per-chunk indices, per-chunk dict bases/sizes,
    per-chunk row counts) → (values, any-index-out-of-range).

    ONE program per (shape set): the per-chunk dictionary-base offset
    and the validity bound broadcast to rows via ``jnp.repeat`` with a
    static total, the gather reads the big dictionary once, and the
    range check collapses to a single boolean — so the whole column
    costs one decode + one combine + ONE host sync, independent of row
    group count."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def combine(big_dict, idx, bases, counts, rows_per_chunk):
        n = idx.shape[0]
        off = jnp.repeat(bases, rows_per_chunk, total_repeat_length=n)
        cnt = jnp.repeat(counts, rows_per_chunk, total_repeat_length=n)
        bad = ((idx < 0) | (idx >= cnt)).any()
        return jnp.take(big_dict, idx + off), bad

    return combine


def _read_dict_column_batched(scanner, ds, fh,
                              plans: Sequence[ColumnPlan], dev):
    """ALL row groups of a raw dictionary-encoded column as one device
    program set.  When the batched device decode declines, the SAME
    already-read buffers host-expand (counted as bounce, read once)
    and feed the identical combine — the per-chunk `_assemble_chunk`
    walk remains only as the caller's safety net.

    The per-chunk path costs, PER ROW GROUP: a dictionary put, a
    3-op batched index decode, a gather, and a BLOCKING min/max
    range-check sync — the window-9 suite_13 row spent 179 s mostly in
    those per-row-group dispatches on a ~20 ms/dispatch tunnel (the
    same dispatch-window disease config 5's ``sql_window_bytes`` lever
    fixed for the groupby scan).  Here the whole column is: one
    pipelined stream of every chunk's dictionary page (device concat),
    ONE batched RLE/bit-packed decode across every chunk's index runs,
    and one jitted combine that adds each chunk's dictionary base
    offset, range-checks, and gathers — one sync per COLUMN, not per
    row group."""
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bitunpack import rle_hybrid_batch_to_device

    eng = scanner.engine
    raw_parts = []
    rows_per_chunk = []
    for plan in plans:
        raw_parts.extend(
            (_read_span_bytes(eng, fh, *p.span), p.bit_width,
             p.valid_count) for p in plan.parts)
        rows_per_chunk.append(sum(p.valid_count for p in plan.parts))
    idx = rle_hybrid_batch_to_device(raw_parts, dev, engine=eng)
    if idx is None:
        # whole-batch decode declined (one bw>24 part, the int32
        # bit-offset cap on the concatenated stream, or the shared
        # segment budget — all scale with COLUMN size once batched):
        # retry per CHUNK with the same already-read buffers.  Each
        # chunk gets a fresh budget and its own device decode, and
        # only chunks that individually decline host-expand — the
        # per-chunk walk's behavior, minus the re-read (returning None
        # to the caller would re-read every index stream and double
        # the bounce claim suite_13 exists to verify).
        pieces, base = [], 0
        for plan in plans:
            chunk_parts = raw_parts[base:base + len(plan.parts)]
            base += len(plan.parts)
            d = rle_hybrid_batch_to_device(chunk_parts, dev, engine=eng)
            if d is None:
                host = [decode_rle_hybrid(b, bw, c)
                        for b, bw, c in chunk_parts]
                d = _put_control(
                    eng,
                    host[0] if len(host) == 1 else np.concatenate(host),
                    dev)
            pieces.append(d)
        idx = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
    # every chunk's dictionary values in one pipelined stream (device
    # concat inside _stream_spans); per-chunk bases index into it
    big_dict = _stream_spans(scanner, ds, fh,
                             [plan.dict_span for plan in plans],
                             plans[0].physical_type)
    counts = np.fromiter((plan.dict_count for plan in plans), np.int64)
    bases = np.zeros_like(counts)
    np.cumsum(counts[:-1], out=bases[1:])
    vals, bad = _dict_combine_fn()(
        big_dict, idx, jnp.asarray(bases, jnp.int32),
        jnp.asarray(counts, jnp.int32),
        jnp.asarray(np.asarray(rows_per_chunk, np.int64), jnp.int32))
    if bool(bad):              # the column's ONE host sync
        raise ValueError(
            f"dictionary index out of range (column of "
            f"{len(plans)} row groups)")
    return vals


def _assemble_chunk(scanner, ds, fh, plan: ColumnPlan, dev):
    """One column chunk → (device array, device mask | None), pages
    assembled in order.

    Raw plain pages stream O_DIRECT→device and bitcast there.  Raw
    dict-encoded pages: the dictionary's PLAIN values stream the same
    zero-copy path, index streams are host-expanded
    (:func:`decode_rle_hybrid`) and the decode is an on-device ``take``
    — values never materialize on host; adjacent dict pages share one
    gather.  Compressed and/or null-masked pages go through
    :func:`_decode_special_part` (host decompress / mask scatter).  The
    mask is None when every value in the chunk is present.
    """
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bridge import host_to_device

    eng = scanner.engine
    dict_dev = None
    if any(p.kind == "dict" for p in plan.parts):
        if plan.dict_codec is not None:
            raw = _read_span_bytes(eng, fh, *plan.dict_span)
            body = _decompress(plan.dict_codec, raw,
                               plan.dict_uncompressed_len)
            if dev.platform != "cpu":
                eng.stats.add(bounce_bytes=plan.dict_uncompressed_len)
            arr = np.frombuffer(body,
                                np.dtype(_NP_DTYPES[plan.physical_type]),
                                plan.dict_count)
            dict_dev = host_to_device(eng, arr, dev)
        else:
            dict_dev = _stream_spans(scanner, ds, fh, [plan.dict_span],
                                     plan.physical_type)
    segs = []            # (device array, mask | None) in page order
    pending_dict = []    # adjacent RAW dict pages' index-stream parts
    pending_plain = []   # value spans of adjacent RAW plain pages
    pending_bss = []     # value spans of adjacent RAW bss pages

    def flush_dict():
        if pending_dict:
            idx = _indices_to_device(eng, fh, pending_dict,
                                     plan.dict_count, dev)
            segs.append((jnp.take(dict_dev, idx), None))
            pending_dict.clear()

    def flush_plain():
        if pending_plain:
            # one pipelined stream over the adjacent spans — per-page
            # calls would collapse the queue to depth 1
            segs.append((_stream_spans(scanner, ds, fh,
                                       list(pending_plain),
                                       plan.physical_type), None))
            pending_plain.clear()

    def flush_bss():
        if pending_bss:
            width = _WIDTHS[plan.physical_type]
            np_dtype = np.dtype(_NP_DTYPES[plan.physical_type])
            for raw in _stream_raw_groups(scanner, ds, fh,
                                          list(pending_bss)):
                # BYTE_STREAM_SPLIT: page bytes are transposed
                # (width, n) — undo ON DEVICE, then bitcast
                n = raw.shape[0] // width
                segs.append((raw.reshape(width, n).T.reshape(-1)
                             .view(np_dtype), None))
            pending_bss.clear()

    def flush_all():
        flush_dict()
        flush_plain()
        flush_bss()

    flushes = {"plain": (flush_dict, flush_bss),
               "dict": (flush_plain, flush_bss),
               "bss": (flush_dict, flush_plain)}
    for p in plan.parts:
        if not p.is_raw:
            flush_all()          # page order is the output order
            segs.append(_decode_special_part(scanner, ds, fh, p, plan,
                                             dict_dev, dev))
            continue
        for fl in flushes[p.kind]:   # close the other kinds' runs
            fl()
        if p.kind == "plain":
            pending_plain.append(p.span)
        elif p.kind == "bss":
            pending_bss.append(p.span)
        else:
            pending_dict.append(p)
    flush_all()
    np_dtype = np.dtype(_NP_DTYPES[plan.physical_type])
    if not segs:     # zero-row chunk
        return jnp.zeros((0,), dtype=np_dtype), None
    vals = (segs[0][0] if len(segs) == 1
            else jnp.concatenate([s[0] for s in segs]))
    if all(m is None for _, m in segs):
        return vals, None
    mask = jnp.concatenate([
        m if m is not None else jnp.ones((a.shape[0],), bool)
        for a, m in segs])
    return vals, mask


def _plain_only(plans: Sequence[ColumnPlan]) -> bool:
    return all(p.kind == "plain" and p.is_raw
               for plan in plans for p in plan.parts)


def try_plan(scanner, columns: Sequence[str], allow_nulls: bool = False):
    """plan_columns, or None when the scanner/file isn't direct-eligible
    — THE fallback rule, shared by every consumer that degrades to the
    pyarrow path (groupby's iter_device_columns, topk) so the two can
    never diverge on the same scanner."""
    if not hasattr(scanner, "direct_reasons"):
        return None
    try:
        return plan_columns(scanner, columns, allow_nulls=allow_nulls)
    except ValueError:
        return None


def _compressed_plain_only(plans: Sequence[ColumnPlan]) -> bool:
    """Every page a codec-tagged null-free PLAIN body — the shape a
    zstd/snappy analytics table presents."""
    return all(p.kind == "plain" and p.codec is not None
               and p.mask is None
               for plan in plans for p in plan.parts)


#: phase breakdown of the most recent _read_compressed_plain_pipelined
#: call — read_stall (blocked in engine waits), decompress, device put —
#: so the bench row can ATTRIBUTE a compressed scan instead of shipping
#: one opaque number (round-3 verdict #5)
LAST_COMPRESSED_PHASES: Dict[str, float] = {}


def _iter_span_bytes_pipelined(eng, fh, spans, stall_box):
    """Yield ``bytes`` per span with the engine queue kept full ACROSS
    spans: sub-chunk splits of every span are submitted ahead (up to
    the configured queue depth) while earlier spans decompress on the
    host.  The round-3 compressed path read each page span with a
    blocking ``engine.read`` — one stop-and-wait round trip per page,
    which is what lost config 12 to pyarrow on the tunneled device
    (0.24x, ledger L24/L45).  ``stall_box[0]`` accumulates the time
    actually blocked in waits — the read-stall phase of the breakdown."""
    from collections import deque
    from nvme_strom_tpu.ops.bridge import split_ranges
    flat, n_chunks = split_ranges(spans, eng.config.chunk_bytes)
    span_of = [i for i, n in enumerate(n_chunks) for _ in range(n)]
    pend = deque()                  # (span_idx, PendingRead)
    parts: Dict[int, list] = {}
    emit_next = 0

    def drain_one():
        i, pr = pend.popleft()
        t0 = time.monotonic()
        view = pr.wait()
        stall_box[0] += time.monotonic() - t0
        b = bytes(view)             # copy out of recycled staging
        eng.stats.add(bounce_bytes=len(b))   # host-touched payload,
        pr.release()                         # same rule as engine.read
        parts.setdefault(i, []).append(b)

    try:
        for si, (off, n) in zip(span_of, flat):
            pend.append((si, eng.submit_read(fh, off, n,
                                             klass=SCAN_CLASS)))
            while len(pend) > eng.config.queue_depth:
                drain_one()
            # FIFO completion: span k's chunks all land before k+1's
            while (emit_next < len(spans)
                   and len(parts.get(emit_next, ())) ==
                   n_chunks[emit_next]):
                chunks = parts.pop(emit_next, [])
                yield (chunks[0] if len(chunks) == 1
                       else b"".join(chunks))
                emit_next += 1
        while pend:
            drain_one()
        while emit_next < len(spans):
            chunks = parts.pop(emit_next, [])
            yield (chunks[0] if len(chunks) == 1 else b"".join(chunks))
            emit_next += 1
    finally:
        for _, pr in pend:
            try:
                pr.wait()
            except OSError:
                pass
            pr.release()


def _read_compressed_plain_pipelined(scanner, fh, columns, plans, dev):
    """All-compressed-PLAIN scan: pipelined O_DIRECT page reads, host
    decompression overlapped with the in-flight reads, and one bulk
    device transfer per (column, row group).

    Contrast with the page-at-a-time path (`_decode_special_part`):
    that pays a blocking engine read AND a small ``device_put`` per
    page — ~2 round trips x pages, which on a high-latency link
    dominates everything (the 0.24x-of-pyarrow ledger rows).  Here the
    engine queue stays full across pages and the link sees a few
    column-sized transfers — the same shape pyarrow's fallback enjoys,
    so the comparison becomes an honest read+decode race."""
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bridge import host_to_device

    eng = scanner.engine
    # (column, row-group ordinal, part): host memory is bounded at one
    # row group's decompressed pages — each (c, rg)'s bodies join and
    # ship to device the moment its last page lands, so a table larger
    # than host RAM still scans (the whole-table join this replaced
    # peaked at ~2x decompressed size)
    work = [(c, gi, p) for c in columns
            for gi, plan in enumerate(plans[c])
            for p in plan.parts]
    widths = {c: _WIDTHS[plans[c][0].physical_type] for c in columns}
    stall = [0.0]
    t_decomp = 0.0
    t_put = 0.0
    comp_bytes = 0
    decomp_bytes = 0
    dev_parts: Dict[str, list] = {c: [] for c in columns}
    group_bodies: list = []

    def flush_group(c):
        nonlocal t_put, decomp_bytes
        if not group_bodies:
            return
        joined = (group_bodies[0] if len(group_bodies) == 1
                  else b"".join(group_bodies))
        group_bodies.clear()
        arr = np.frombuffer(joined, np.dtype(_NP_DTYPES[
            plans[c][0].physical_type]))
        decomp_bytes += arr.nbytes
        t0 = time.monotonic()
        dev_parts[c].append(host_to_device(eng, arr, dev))
        t_put += time.monotonic() - t0

    it = _iter_span_bytes_pipelined(eng, fh,
                                    [p.span for _, _, p in work], stall)
    prev = None                     # (column, row-group) being filled
    for (c, gi, p), raw in zip(work, it):
        if prev is not None and prev != (c, gi):
            flush_group(prev[0])
        prev = (c, gi)
        comp_bytes += len(raw)
        t0 = time.monotonic()
        body = _decompress(p.codec, raw, p.uncompressed_len)
        t_decomp += time.monotonic() - t0
        if dev.platform != "cpu":
            eng.stats.add(bounce_bytes=p.uncompressed_len)
        n_valid = p.valid_count
        if p.inline_levels:
            body, mask, n_valid = _inline_levels(body, p)
            if mask is not None:
                raise ValueError(
                    "unexpected nulls in a chunk planned null-free")
        group_bodies.append(bytes(body[:n_valid * widths[c]]))
    if prev is not None:
        flush_group(prev[0])
    out = {}
    for c in columns:
        parts = dev_parts[c]
        if not parts:
            out[c] = jnp.zeros((0,), dtype=np.dtype(_NP_DTYPES[
                plans[c][0].physical_type]))
        else:
            out[c] = (parts[0] if len(parts) == 1
                      else jnp.concatenate(parts))
    LAST_COMPRESSED_PHASES.clear()
    LAST_COMPRESSED_PHASES.update(
        read_stall_s=round(stall[0], 4), decomp_s=round(t_decomp, 4),
        put_s=round(t_put, 4), compressed_bytes=comp_bytes,
        decompressed_bytes=decomp_bytes, pages=len(work))
    return out


def _join_chunks(chunks, nulls: str, column: str):
    """[(values, mask|None)] per row group → column output per the
    ``nulls`` policy: "forbid" raises on any real mask (statistics lied
    or the caller forgot to opt in), "mask" returns (values, mask) with
    all-valid chunks contributing ones."""
    import jax.numpy as jnp
    vals = (chunks[0][0] if len(chunks) == 1
            else jnp.concatenate([c[0] for c in chunks]))
    if nulls == "forbid":
        if any(m is not None for _, m in chunks):
            raise ValueError(
                f"column {column!r} has nulls; pass nulls='mask'")
        return vals
    mask = (jnp.ones((vals.shape[0],), bool)
            if all(m is None for _, m in chunks)
            else jnp.concatenate([
                m if m is not None else jnp.ones((a.shape[0],), bool)
                for a, m in chunks]))
    return vals, mask


def read_plain_columns_to_device(scanner, columns: Sequence[str],
                                 device=None, plans=None,
                                 nulls: str = "forbid"
                                 ) -> Dict[str, "object"]:
    """Direct scan of the whole file: {name: device array}, row groups
    concatenated ON DEVICE.  Payload bytes (PLAIN values and dictionary
    values) ride O_DIRECT → staging → device; the host reads only
    headers, dict index streams, level blocks, and — for compressed
    chunks — the page bodies it must decompress (counted as bounce).
    ``plans`` lets callers reuse a prior :func:`plan_columns` walk.

    ``nulls``: "forbid" (default) raises if any chunk holds nulls;
    "mask" returns ``(values, valid_mask)`` pairs — null slots are
    zero-filled, the mask is the truth."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from nvme_strom_tpu.ops.bridge import DeviceStream
    from nvme_strom_tpu.utils.tuning import tuned_stream_params

    if nulls not in ("forbid", "mask"):
        raise ValueError(f"bad nulls={nulls!r}")
    dev = device or jax.local_devices()[0]
    plans = plans or plan_columns(scanner, columns,
                                  allow_nulls=nulls == "mask")
    depth, drain = tuned_stream_params(scanner.engine)
    ds = DeviceStream(scanner.engine, device=dev, depth=depth,
                      klass=SCAN_CLASS,
                      drain=drain)
    out = {}
    meta = scanner.metadata
    name_to_ci = {meta.schema.column(i).name: i
                  for i in range(meta.num_columns)}
    fh = scanner.engine.open(scanner.path)
    try:
        if (nulls == "forbid" and columns
                and all(plans[c] and _plain_only(plans[c])
                        for c in columns)):
            # the whole read is ONE pipelined range sequence across
            # every (row group, column) chunk — no boundary drains
            # (same rationale as iter_plain_row_groups_to_device)
            per_col = {c: [] for c in columns}
            for rg_out in _iter_plain_pipelined(
                    scanner, ds, fh, columns, plans,
                    range(meta.num_row_groups)):
                for c, v in rg_out.items():
                    per_col[c].append(v)
            return {c: (parts[0] if len(parts) == 1
                        else jnp.concatenate(parts))
                    for c, parts in per_col.items()}
        if (nulls == "forbid" and columns
                and all(plans[c] and _compressed_plain_only(plans[c])
                        for c in columns)):
            return _read_compressed_plain_pipelined(scanner, fh,
                                                    columns, plans, dev)
        for c in columns:
            if not plans[c]:   # zero row groups: empty typed column
                pt = meta.schema.column(name_to_ci[c]).physical_type
                empty = jnp.zeros((0,), dtype=np.dtype(_NP_DTYPES[pt]))
                out[c] = (empty if nulls == "forbid"
                          else (empty, jnp.zeros((0,), bool)))
            elif _plain_only(plans[c]) and nulls == "forbid":
                # one pipelined stream across every row group's spans
                out[c] = _stream_spans(
                    scanner, ds, fh,
                    (s for p in plans[c] for s in p.spans),
                    plans[c][0].physical_type)
            else:
                v = None
                if nulls == "forbid" and _raw_dict_only(plans[c]):
                    # whole-column batched dict path: one decode + one
                    # combine + one sync for ALL row groups.  It always
                    # returns the column (a declined device decode is
                    # retried per-chunk and then host-expanded INSIDE),
                    # so the per-chunk walk below runs only for columns
                    # that failed the _raw_dict_only gate above.
                    v = _read_dict_column_batched(scanner, ds, fh,
                                                  plans[c], dev)
                if v is None:
                    chunks = [_assemble_chunk(scanner, ds, fh, plan,
                                              dev)
                              for plan in plans[c]]
                    v = _join_chunks(chunks, nulls, c)
                out[c] = v
    finally:
        scanner.engine.close(fh)
    return out


# ---------------------------------------------------------------------------
# dictionary-code scans of BYTE_ARRAY (string) columns
#
# PG-Strom's trick for GROUP BY over strings: never materialize the
# strings on the accelerator — group by the dictionary CODE (an int32)
# and map codes back to labels on the host, where the dictionary page
# (tiny, one per chunk) already lives.  Payload economics: the device
# sees 4 bytes per row regardless of string length.


@dataclass(frozen=True)
class DictCodeChunk:
    """One chunk of a dictionary-coded BYTE_ARRAY column."""
    parts: Tuple[PagePart, ...]            # all kind "dict"
    num_values: int
    dict_span: Tuple[int, int]             # raw dictionary page body
    dict_count: int
    dict_codec: Optional[str] = None
    dict_uncompressed_len: int = 0


def dict_code_eligible(meta, rg: int, ci: int) -> Optional[str]:
    """None if the chunk can scan as dictionary codes, else the reason.

    A footer-level check only — a chunk whose writer overflowed to
    PLAIN BYTE_ARRAY data pages (undetectable from the footer) fails
    later in :func:`plan_dict_code_chunk`."""
    col = meta.row_group(rg).column(ci)
    sc = meta.schema.column(ci)
    if col.physical_type != "BYTE_ARRAY":
        return f"physical type {col.physical_type} (need BYTE_ARRAY)"
    codec = _codec_of(col)
    if codec is not None and not _codec_available(codec):
        return f"compression {col.compression}"
    encs = set(col.encodings)
    if not encs <= {"PLAIN", "RLE", "PLAIN_DICTIONARY", "RLE_DICTIONARY"}:
        return f"encodings {sorted(encs)}"
    if (col.dictionary_page_offset or 0) <= 0:
        return "no dictionary page"
    if sc.max_repetition_level != 0:
        return "repeated field"
    if sc.max_definition_level > 0:
        st = col.statistics
        if st is None or st.null_count is None:
            return "no null statistics"
        if st.null_count != 0:
            return f"{st.null_count} nulls"
    return None


def plan_dict_code_chunk(meta, rg: int, ci: int, raw_read) -> DictCodeChunk:
    """Page-walk a BYTE_ARRAY chunk: dictionary page body span + index
    stream spans (codec-tagged when the chunk is compressed).  Raises
    ValueError on any PLAIN data page (dictionary overflow) — string
    bytes cannot decode on device."""
    col = meta.row_group(rg).column(ci)
    sc = meta.schema.column(ci)
    max_def = sc.max_definition_level
    codec = _codec_of(col)
    parts: List[PagePart] = []
    dict_span = None
    dict_count = 0
    dict_codec: Optional[str] = None
    dict_ulen = 0
    for pos, ph in _walk_pages(col, raw_read):
        if ph.type in (_PAGE_DATA, _PAGE_DATA_V2):
            if ph.encoding not in _DICT_ENCODINGS:
                raise ValueError(
                    f"page at {pos}: encoding {ph.encoding} — string "
                    f"chunk fell back from dictionary (overflow?)")
            if dict_span is None:
                raise ValueError(
                    f"page at {pos}: dict-encoded data page before "
                    f"any dictionary page")
            v2 = ph.type == _PAGE_DATA_V2
            page_codec = codec
            if v2 and not ph.v2_is_compressed:
                page_codec = None
            if page_codec is not None and not v2:
                # v1: levels+values in one compressed body
                parts.append(PagePart(
                    "dict", (pos + ph.header_len, ph.compressed_size),
                    ph.num_values, bit_width=-1, codec=page_codec,
                    uncompressed_len=ph.uncompressed_size,
                    inline_levels=max_def > 0, max_def=max_def))
                continue
            # eligibility proved the chunk null-free → no masks
            lb, _ = _plan_levels(pos, ph, max_def, raw_read, False)
            if page_codec is not None:      # compressed v2 values
                parts.append(PagePart(
                    "dict",
                    (pos + ph.header_len + lb, ph.compressed_size - lb),
                    ph.num_values, bit_width=-1, codec=page_codec,
                    uncompressed_len=ph.uncompressed_size - lb,
                    max_def=max_def))
            else:
                parts.append(_index_stream_part(pos, ph, lb, raw_read,
                                                max_def=max_def))
        elif ph.type == _PAGE_DICTIONARY:
            _check_dict_page(pos, ph, dict_span is not None)
            # var-len strings: the span is the whole page body; entry
            # lengths are parsed from it host-side
            dict_span = (pos + ph.header_len, ph.compressed_size)
            dict_count = ph.num_values
            if codec is not None:
                dict_codec = codec
                dict_ulen = ph.uncompressed_size
    if dict_span is None:
        raise ValueError(f"rg{rg} col{ci}: no dictionary page")
    return DictCodeChunk(tuple(parts), col.num_values, dict_span,
                         dict_count, dict_codec=dict_codec,
                         dict_uncompressed_len=dict_ulen)


def parse_byte_array_dict(buf: bytes, count: int) -> List[bytes]:
    """PLAIN BYTE_ARRAY dictionary page body → label list
    (``<u32 len><bytes>`` repeated ``count`` times)."""
    out: List[bytes] = []
    pos = 0
    for _ in range(count):
        if pos + 4 > len(buf):
            raise ValueError("truncated dictionary page (length prefix)")
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        if pos + n > len(buf):
            raise ValueError("truncated dictionary page (entry bytes)")
        out.append(bytes(buf[pos:pos + n]))
        pos += n
    return out


def read_dict_key_column(scanner, column: str, device=None,
                         row_groups=None):
    """Prepare a BYTE_ARRAY column for on-device GROUP BY by code.

    Returns ``(labels, iter_codes)``: ``labels`` is the GLOBAL label
    list (union of EVERY row group's dictionary, first-seen order;
    bytes objects — stable across pruned and unpruned queries),
    ``iter_codes()`` yields one int32 device array of global codes per
    row group in ``row_groups`` (default: all).

    Two-pass: dictionary pages are read first (through the engine,
    host-touched by design → counted as bounce) so the global label
    space is known before any data streams — per-row-group dictionaries
    are remapped to global codes ON DEVICE via a gather.
    """
    import jax
    from nvme_strom_tpu.ops.bridge import host_to_device

    meta = scanner.metadata
    name_to_ci = {meta.schema.column(i).name: i
                  for i in range(meta.num_columns)}
    if column not in name_to_ci:
        raise KeyError(f"column {column!r} not in schema")
    ci = name_to_ci[column]
    import os
    with open(scanner.path, "rb") as f:
        def raw_read(off: int, ln: int) -> bytes:
            return os.pread(f.fileno(), ln, off)

        chunks = []
        for rg in range(meta.num_row_groups):
            why = dict_code_eligible(meta, rg, ci)
            if why is not None:
                raise ValueError(
                    f"rg{rg}.{column} not dict-code-eligible: {why}")
            chunks.append(plan_dict_code_chunk(meta, rg, ci, raw_read))

    dev = device or jax.local_devices()[0]
    eng = scanner.engine
    labels: List[bytes] = []
    gid: Dict[bytes, int] = {}
    remaps: List["object"] = []       # per-rg int32 device remap arrays
    import numpy as np
    fh = eng.open(scanner.path)
    try:
        for ch in chunks:
            body = _read_span_bytes(eng, fh, *ch.dict_span)
            if ch.dict_codec is not None:
                body = _decompress(ch.dict_codec, body,
                                   ch.dict_uncompressed_len)
                eng.stats.add(bounce_bytes=ch.dict_uncompressed_len)
            local = parse_byte_array_dict(body, ch.dict_count)
            remap = np.empty(max(ch.dict_count, 1), np.int32)
            for i, lab in enumerate(local):
                if lab not in gid:
                    gid[lab] = len(labels)
                    labels.append(lab)
                remap[i] = gid[lab]
            remaps.append(host_to_device(eng, remap, dev))
    finally:
        eng.close(fh)

    selected = (range(len(chunks)) if row_groups is None
                else list(row_groups))

    def iter_codes():
        import jax.numpy as jnp
        fh = eng.open(scanner.path)
        try:
            for rg in selected:
                ch, remap_dev = chunks[rg], remaps[rg]
                idx = _indices_to_device(eng, fh, ch.parts,
                                         ch.dict_count, dev)
                # local code → global code, on device
                yield jnp.take(remap_dev, idx)
        finally:
            eng.close(fh)

    return labels, iter_codes


def iter_plain_row_groups_to_device(scanner, columns: Sequence[str],
                                    device=None, plans=None,
                                    row_groups=None,
                                    nulls: str = "forbid",
                                    window_bytes: int | None = None):
    """Yield {name: device array} per (selected) row group — the
    incremental form sql_groupby folds over, so device memory holds one
    row group of columns at a time regardless of table size.  ``plans``
    lets callers reuse a prior :func:`plan_columns` walk;
    ``row_groups`` restricts to a pruned subset (statistics-based scan
    elimination — skipped chunks never leave the SSD).  ``nulls`` as in
    :func:`read_plain_columns_to_device` ("mask" yields (values, mask)
    pairs per column).

    ``window_bytes`` batches consecutive row groups into one yielded
    dict holding ~that many payload bytes (all-PLAIN ``forbid`` path
    only).  For FOLD consumers exclusively: on a high-latency link the
    per-row-group consumer ops (concat/view/fold dispatches) price the
    scan, not bandwidth — the 2026-07-31T18:04 on-silicon row ledgered
    the config-5 stream at 0.186 GiB/s under a 1.35 GiB/s link, ~20 ms
    per dispatch across ~70 of them.  Windowing divides the dispatch
    count by the window's group count.  Default None = one yield per
    row group — POSITIONAL consumers (topk zips yields against row-
    group ids; LIMIT scans early-exit per group) must keep that.

    When every selected chunk is raw-PLAIN (the common analytics case),
    the WHOLE scan is one pipelined range sequence — row-group
    boundaries are just chunk counts on the consumer side.  The per-
    row-group form (one drained ``stream_ranges`` call per column per
    group) collapsed the engine queue at every boundary: each drain is
    a ``block_until_ready`` round-trip with the device link idle, and a
    64-group × 2-column scan paid ~128 of them — the round-3 on-silicon
    ledger showed config 5 at 0.11× of a ceiling bench.py's single
    pipelined stream hits at 0.9× through the same tunnel."""
    import jax
    from nvme_strom_tpu.ops.bridge import DeviceStream
    from nvme_strom_tpu.utils.tuning import tuned_stream_params

    if nulls not in ("forbid", "mask"):
        raise ValueError(f"bad nulls={nulls!r}")
    dev = device or jax.local_devices()[0]
    plans = plans or plan_columns(scanner, columns,
                                  allow_nulls=nulls == "mask")
    # probe-tuned operating point, same as bench.py's headline stream:
    # the raw engine default (depth=queue_depth=16, ready) ledgered
    # 0.37 of ceiling in the window-7 sweep while depth 4-8 rode the
    # identical link at 0.88-0.91
    depth, drain = tuned_stream_params(scanner.engine)
    ds = DeviceStream(scanner.engine, device=dev, depth=depth,
                      klass=SCAN_CLASS,
                      drain=drain)
    fh = scanner.engine.open(scanner.path)
    try:
        groups = (range(scanner.metadata.num_row_groups)
                  if row_groups is None else row_groups)
        groups = list(groups)
        if nulls == "forbid" and all(
                _plain_only([plans[c][rg]])
                for rg in groups for c in columns):
            yield from _iter_plain_pipelined(scanner, ds, fh, columns,
                                             plans, groups,
                                             window_bytes=window_bytes)
            return
        for rg in groups:
            out = {}
            for c in columns:
                plan = plans[c][rg]
                if _plain_only([plan]) and nulls == "forbid":
                    out[c] = _stream_spans(scanner, ds, fh, plan.spans,
                                           plan.physical_type)
                else:
                    out[c] = _join_chunks(
                        [_assemble_chunk(scanner, ds, fh, plan, dev)],
                        nulls, c)
            yield out
    finally:
        scanner.engine.close(fh)


def _iter_plain_pipelined(scanner, ds, fh, columns, plans, groups,
                          window_bytes: int | None = None):
    """All-raw-PLAIN scan as ONE pipelined range sequence.

    Every (row group, column) chunk's spans are flattened into a single
    ``stream_ranges`` submission — the engine keeps ``depth`` reads in
    flight across row-group boundaries, and the only blocking wait is
    backpressure (pipe full), never a boundary drain.  The consumer
    side reassembles boundaries from chunk counts: submission order is
    yield order.  The fold's device compute overlaps the stream for
    free — JAX dispatch is async, so by the time the consumer asks for
    the next group's chunks, its aggregation is already queued behind
    the transfers.

    ``window_bytes`` (see :func:`iter_plain_row_groups_to_device`)
    coalesces consecutive row groups into one yield of ~that size, so
    each consumer-side concat/view/fold dispatch covers a window of
    payload instead of one group — the dispatch-latency lever.

    Transfer-side coalescing: PLAIN value spans are PER PAGE (~1 MiB
    each — page headers interleave them), so submitting them verbatim
    costs ~8x more device puts per byte than the north-star stream's
    8 MiB chunks; the same-minute window-7 ledger showed the scan's
    put path at 0.20 GiB/s while bench rode the identical link at
    1.15 (ratio 0.953).  When a column chunk's header gap is small,
    the ENCLOSING byte range streams as chunk-sized reads
    (header bytes ride along) and one jitted static-slice program per
    (window, column) drops the gaps ON DEVICE — one put per 8 MiB and
    ~3 device dispatches per window-column, independent of page
    count."""
    flat, counts, windows = [], [], _split_windows(columns, plans,
                                                   groups, window_bytes)
    for w in windows:
        f, cn = _plan_window_ranges(scanner, columns, plans, w)
        flat.extend(f)
        counts.extend(cn)
    it = ds.stream_ranges(fh, flat)
    ci = iter(counts)
    try:
        for w in windows:
            yield _assemble_window(columns, plans, w, ci, it)
    finally:
        it.close()                 # abandoned scan: release staging now


def _split_windows(columns, plans, groups,
                   window_bytes: int | None) -> list:
    """Row-group ids → consecutive windows of ~``window_bytes`` payload
    each (one group per window when None/0).  The ONE windowing rule
    shared by the serial pipelined scan above and the partition-parallel
    scan (sql/scan_plan.py) — identical windows are what make the
    parallel merge bit-identical to the serial stream."""
    if window_bytes:
        windows, cur, cur_b = [], [], 0
        for rg in groups:
            b = sum(ln for c in columns for _, ln in plans[c][rg].spans)
            if cur and cur_b + b > window_bytes:
                windows.append(cur)
                cur, cur_b = [], 0
            cur.append(rg)
            cur_b += b
        if cur:
            windows.append(cur)
        return windows
    return [[rg] for rg in groups]


def _plan_window_ranges(scanner, columns, plans, w):
    """One window's submission plan: ``(flat, counts)`` — every
    chunk-sized sub-range in submission order, plus the
    ``(rg, column, n_chunks, spec)`` reassembly records
    :func:`_assemble_window` consumes.  Pure function of the window:
    the serial path streams all windows' ranges as one sequence, the
    parallel path streams each worker's windows independently, and
    both assemble the same per-window buffers."""
    from nvme_strom_tpu.ops.bridge import split_ranges

    chunk_bytes = scanner.engine.config.chunk_bytes
    flat = []                      # every sub-range, submission order
    counts = []                    # (rg, column, n_chunks, spec)
    # merge decision per (window, column): the degap program holds
    # one lax.slice per value span ACROSS the window, so a
    # small-page layout (4 KiB pages → thousands of spans per
    # 64 MiB window) would compile a pathological program — cap
    # the slice count and fall back to exact per-span reads
    allow = {c: sum(len([s for s in plans[c][rg].spans if s[1]])
                    for rg in w) <= _COALESCE_MAX_SLICES
             for c in columns}
    for rg in w:
        for c in columns:
            spans = plans[c][rg].spans
            merged = _coalesce_spans(spans) if allow[c] else None
            if merged is not None:
                ranges, _ = split_ranges([merged], chunk_bytes)
                # value spans relative to the merged buffer: the
                # on-device degap spec
                spec = tuple((off - merged[0], ln)
                             for off, ln in spans if ln)
            else:
                ranges, _ = split_ranges(spans, chunk_bytes)
                spec = None
            flat.extend(ranges)
            counts.append((rg, c, len(ranges), spec))
    return flat, counts


def _assemble_window(columns, plans, w, ci, it):
    """Reassemble one window's {column: device array} dict from its
    ``counts`` records (``ci``) and streamed buffers (``it``) — the
    consumer half of :func:`_plan_window_ranges`, shared by the serial
    and parallel scans."""
    import jax.numpy as jnp
    import numpy as np

    parts: dict = {c: [] for c in columns}
    specs: dict = {c: [] for c in columns}
    merged_any = {c: False for c in columns}
    sizes = {c: 0 for c in columns}     # buffer bytes so far
    for rg in w:
        for c in columns:
            _, _, n, spec = next(ci)
            got = [next(it) for _ in range(n)]
            base = sizes[c]
            if spec is not None:
                merged_any[c] = True
                specs[c].extend((base + o, ln)
                                for o, ln in spec)
            else:
                # unmerged chunks are pure value bytes: they
                # enter the buffer verbatim, and the spec keeps
                # them in case a SIBLING row group merged
                pos = 0
                for p in got:
                    specs[c].append((base + pos,
                                     int(p.shape[0])))
                    pos += int(p.shape[0])
            parts[c].extend(got)
            sizes[c] += sum(int(p.shape[0]) for p in got)
    out = {}
    for c in columns:
        np_dtype = np.dtype(
            _NP_DTYPES[plans[c][w[0]].physical_type])
        ps = parts[c]
        if not ps:         # zero-row window
            out[c] = jnp.zeros((0,), dtype=np_dtype)
            continue
        buf = ps[0] if len(ps) == 1 else jnp.concatenate(ps)
        if merged_any[c]:
            buf = _degap(tuple(specs[c]), int(buf.shape[0]))(buf)
        out[c] = buf.view(np_dtype)
    return out


#: tolerated header/gap overhead when streaming a column chunk's
#: enclosing range: page headers are ~30-60 B per ~1 MiB page (<0.01%),
#: so anything beyond a few percent means an unexpected layout — fall
#: back to exact per-span reads rather than wasting link on holes
_COALESCE_GAP_FRAC = 0.05

#: max lax.slice ops in one window-column degap program (compile cost
#: grows with operand count; 1 MiB default pages put a 64 MiB window at
#: ~64-128 slices, comfortably under; 4 KiB-page layouts blow past and
#: take the exact per-span path instead)
_COALESCE_MAX_SLICES = 256


def _coalesce_spans(spans):
    """Enclosing (offset, length) of the span list when the interior
    gaps (page headers) are a negligible fraction — else None."""
    spans = [s for s in spans if s[1]]
    if len(spans) < 2:
        return None
    lo = spans[0][0]
    hi = spans[-1][0] + spans[-1][1]
    payload = sum(ln for _, ln in spans)
    if hi - lo - payload > _COALESCE_GAP_FRAC * payload:
        return None
    # spans must be ascending and disjoint for the relative spec to be
    # meaningful (the page walk emits them in file order)
    pos = lo
    for off, ln in spans:
        if off < pos:
            return None
        pos = off + ln
    return (lo, hi - lo)


@functools.lru_cache(maxsize=256)
def _degap(spec: tuple, total: int):
    """Jitted static-slice compaction: uint8 buffer of ``total`` bytes
    → the concatenation of the ``spec`` (offset, length) value spans.
    Page layouts repeat across row groups and windows, so the lru
    cache (plus the persistent compile cache) makes this one compile
    per distinct layout, ONE device dispatch per application."""
    import jax
    import jax.numpy as jnp

    def f(a):
        pieces = [jax.lax.slice(a, (o,), (o + ln,)) for o, ln in spec]
        return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)

    return jax.jit(f)
