"""On-device star-schema join: fact rows → dimension attributes.

PG-Strom's Direct SQL path is scan/JOIN/aggregate on the accelerator
(SURVEY.md §3.5); :mod:`.groupby` covers scan+aggregate, this module adds
the join.  The supported shape is the warehouse workhorse: a large fact
table joined to a dimension table on the dimension's UNIQUE key
(primary-key equi-join), then grouped by a dimension attribute:

    SELECT d.attr, AGG(f.value)
    FROM fact f JOIN dim d ON f.key = d.key
    GROUP BY d.attr

TPU-first formulation: a hash table is a pointer-chasing structure the
accelerator hates; with a unique build side the join is a SORT + binary
search — ``argsort`` the dimension keys once, ``searchsorted`` every
fact key into them (both XLA-native, O(n log n) with static shapes),
gather the attribute.  Unmatched fact rows carry ``found=False`` and
flow into :func:`groupby_aggregate`'s mask (its WHERE-pushdown path), so
inner-join semantics cost nothing extra.  Fact row groups stream through
the engine one at a time (pq_direct when eligible); only the small
dimension table is device-resident for the query's lifetime.

General M:N joins (non-unique build keys) produce data-dependent output
cardinality — fundamentally at odds with XLA's static shapes — and are
out of scope; the host/pyarrow path remains the fallback for those.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Sequence

import jax
import jax.numpy as jnp


@jax.jit
def lookup_unique(build_keys: jax.Array, probe_keys: jax.Array):
    """For each probe key, the index of the matching build row.

    build_keys (M,) UNIQUE integers; probe_keys (N,) integers →
    (idx (N,) int32 into build rows, found (N,) bool).  Rows with
    ``found=False`` have an arbitrary (clipped) idx — mask before use.
    Uniqueness of build_keys is the caller's contract
    (:func:`check_unique` validates it eagerly on host-sized tables).
    """
    order = jnp.argsort(build_keys)
    skeys = build_keys[order]
    pos = jnp.searchsorted(skeys, probe_keys)
    pos = jnp.clip(pos, 0, skeys.shape[0] - 1)
    found = skeys[pos] == probe_keys
    return order[pos].astype(jnp.int32), found


def check_unique(keys) -> None:
    """Raise if the build-side keys are empty or not unique (an M:N join
    the static-shape device path cannot represent; an empty build side
    would make the clipped gather in lookup_unique undefined)."""
    import numpy as np
    k = np.asarray(keys)
    if k.shape[0] == 0:
        raise ValueError("join build side (dimension table) is empty")
    if len(np.unique(k)) != k.shape[0]:
        raise ValueError(
            "join build side has duplicate keys — M:N joins are not "
            "supported on the device path (use the pyarrow fallback)")


def star_join_groupby(fact_scanner, fact_key: str, fact_value: str,
                      dim_scanner, dim_key: str, dim_attr: str,
                      num_groups: int,
                      aggs: Sequence[str] = ("count", "sum", "mean"),
                      method: str = "matmul", device=None,
                      where=None, where_columns: Sequence[str] = ()
                      ) -> Dict[str, jax.Array]:
    """The star query above, end to end on device.

    ``dim_attr`` must be an integer column in [0, num_groups) — the GROUP
    BY key after the join.  ``where`` (optional) receives the fact
    columns dict ({fact_key, fact_value, *where_columns}, device arrays)
    and returns a row mask, composed with the join's found-mask.
    Returns {agg: (num_groups,)} like :func:`.groupby.sql_groupby`.
    """
    from nvme_strom_tpu.sql.groupby import (
        _fold, _norm_aggs, finalize_folds, iter_device_columns,
        sql_window_bytes)

    dev = device or jax.local_devices()[0]

    # Dimension side: small, loaded once, device-resident.
    dcols = dim_scanner.read_columns_to_device([dim_key, dim_attr],
                                               device=dev)
    for c in (dim_key, dim_attr):
        if not jnp.issubdtype(dcols[c].dtype, jnp.integer):
            # astype below would TRUNCATE floats — [1.0, 1.5, 2.0] would
            # pass check_unique then collapse to duplicate keys
            raise TypeError(f"dimension column {c} must be integer, "
                            f"got {dcols[c].dtype}")
    check_unique(dcols[dim_key])
    # widest available int for key comparison (int64 needs jax x64 mode;
    # without it int32 is both sides' storage dtype anyway)
    kdt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    dkeys = dcols[dim_key].astype(kdt)
    dattr = dcols[dim_attr].astype(jnp.int32)

    part_aggs = _norm_aggs(aggs)   # ONE foldable-set rule (var/std
                                   # fold via sum2, mean via sum/count)
    cols_needed = list(dict.fromkeys(
        [fact_key, fact_value, *where_columns]))
    folds = None
    for cols in iter_device_columns(fact_scanner, cols_needed, dev,
                                    require_int=(fact_key,),
                                    window_bytes=sql_window_bytes()):
        mask = where(cols) if where is not None else None
        part = _join_part(dkeys, dattr, cols[fact_key],
                          cols[fact_value], mask,
                          num_groups=num_groups, aggs=part_aggs,
                          method=method)
        folds = part if folds is None else _fold(folds, part)
    if folds is None:
        raise ValueError("empty fact table")
    return finalize_folds(folds, aggs)


@partial(jax.jit, static_argnames=("num_groups", "aggs", "method"))
def _join_part(dkeys, dattr, fkeys, fvals, mask, *, num_groups, aggs,
               method):
    """One fact row group: join → masked partial aggregates.  dkeys and
    dattr are traced ARGUMENTS (not closure constants), so repeated
    queries — even against different dimension tables of the same shape
    — reuse one compilation."""
    from nvme_strom_tpu.sql.groupby import groupby_aggregate
    idx, found = lookup_unique(dkeys, fkeys.astype(dkeys.dtype))
    groups = dattr[idx]
    m = found if mask is None else (found & mask)
    return groupby_aggregate(groups, fvals, num_groups, aggs=aggs,
                             method=method, mask=m, empty_as_nan=False)
