"""Device-resident table cache: scan once, query from HBM.

PG-Strom pairs its Direct-SQL scan path with *GPU Cache* — a table
kept resident in GPU memory and queried repeatedly without touching
storage (SURVEY.md §3.5's consumer story, applied to the re-query
case).  This module is the TPU analogue: :class:`DeviceTable`
materializes selected Parquet columns into HBM through the same
windowed pq_direct streaming path the one-shot scan uses, then serves
GROUP BY / scalar aggregates / top-k / star joins as pure on-device
array programs — zero engine reads, zero host↔device payload traffic
per query.

Where the streaming scan's unit economics are "pay the NVMe read every
query", the cache's are "pay it once, then every query runs at HBM
speed" — on the round-4 on-silicon numbers that is the difference
between a 0.1-0.5 GiB/s link-priced scan and pure device compute.
The fit test is explicit: construction refuses tables beyond a byte
budget (``STROM_DEVICE_CACHE_BYTES``, default 4 GiB) instead of
OOM-ing mid-stream, because HBM is the serving/training budget too.

Columns are cached null-free (``nulls="forbid"`` semantics).  Nullable
queries belong on the streaming path — a cache of zero-filled values
would silently change aggregates.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp


def device_cache_budget() -> int:
    """Max bytes a DeviceTable may pin in HBM
    (``STROM_DEVICE_CACHE_BYTES`` overrides; 0 = unlimited)."""
    v = os.environ.get("STROM_DEVICE_CACHE_BYTES")
    return int(v) if v is not None else 4 << 30


class DeviceTable:
    """Selected columns of one Parquet table, resident on device.

    Construction streams every row group through
    :func:`groupby.iter_device_columns` (the pq_direct fast path when
    eligible, engine-backed pyarrow otherwise) in coalescing windows
    and concatenates per column ON DEVICE — the host never holds the
    table.  Queries then run against the resident arrays.
    """

    def __init__(self, scanner, columns: Sequence[str], device=None,
                 budget_bytes: Optional[int] = None):
        from nvme_strom_tpu.sql.groupby import (iter_device_columns,
                                                sql_window_bytes)
        columns = list(dict.fromkeys(columns))
        if not columns:
            raise ValueError("DeviceTable needs at least one column")
        self.device = device or jax.local_devices()[0]
        self.path = getattr(scanner, "path", None)
        budget = (device_cache_budget() if budget_bytes is None
                  else budget_bytes)
        est = _estimate_bytes(scanner, columns)
        if budget and est > budget:
            raise ValueError(
                f"table needs ~{est >> 20} MiB resident for "
                f"{columns}, over the {budget >> 20} MiB device-cache "
                f"budget (STROM_DEVICE_CACHE_BYTES) — use the "
                f"streaming scan instead")
        parts: Dict[str, list] = {c: [] for c in columns}
        for cols in iter_device_columns(scanner, columns, self.device,
                                        window_bytes=sql_window_bytes()):
            for c in columns:
                parts[c].append(cols[c])
        # concatenate one column at a time and drop its fragments
        # immediately: the transient over-residency is then one
        # column's payload, not the whole table's (a 2x whole-table
        # peak would defeat the budget guard above)
        self.columns: Dict[str, jax.Array] = {}
        for c in columns:
            frags = parts.pop(c)
            self.columns[c] = (frags[0] if len(frags) == 1
                               else jnp.concatenate(frags))
            frags.clear()
        n = {int(v.shape[0]) for v in self.columns.values()}
        if len(n) != 1:
            raise AssertionError(f"ragged cached columns: {n}")
        self.num_rows = n.pop()

    def nbytes(self) -> int:
        """Resident HBM payload of the cached columns."""
        return sum(int(v.nbytes) for v in self.columns.values())

    def column(self, name: str) -> jax.Array:
        try:
            return self.columns[name]
        except KeyError:
            raise KeyError(
                f"column {name!r} not cached (have "
                f"{sorted(self.columns)}) — list it at construction")

    # ---------------- queries (pure device programs) ----------------

    def _mask(self, where, where_ranges):
        from nvme_strom_tpu.sql.groupby import _range_mask
        if not where_ranges and where is None:
            return None
        where_ranges = list(where_ranges)
        for c, _, _ in where_ranges:    # actionable error, not KeyError
            self.column(c)
        return _range_mask(self.columns, where_ranges, where)

    def groupby(self, key_column: str, value_column,
                num_groups: int,
                aggs: Sequence[str] = ("count", "sum", "mean"),
                method: str = "matmul", where=None,
                where_ranges: Sequence[tuple] = ()
                ) -> Dict[str, jax.Array]:
        """``SELECT key, AGG(value) ... GROUP BY key`` over the cached
        columns — one ``groupby_aggregate`` call, no I/O.  Same
        aggregate set, WHERE predicate protocol and empty-group NaN
        semantics as :func:`groupby.sql_groupby`."""
        from nvme_strom_tpu.sql.groupby import (_norm_aggs,
                                                _stack_values,
                                                _value_cols,
                                                finalize_folds,
                                                groupby_aggregate)
        keys = self.column(key_column)
        if not jnp.issubdtype(keys.dtype, jnp.integer):
            raise TypeError(f"key column {key_column} must be integer")
        vcols, single = _value_cols(value_column)
        values = _stack_values(self.columns, vcols, single)
        part = groupby_aggregate(
            keys.astype(jnp.int32), values, num_groups,
            aggs=_norm_aggs(aggs), method=method,
            mask=self._mask(where, where_ranges), empty_as_nan=False)
        return finalize_folds(part, aggs)

    def scalar_agg(self, value_column,
                   aggs: Sequence[str] = ("count", "sum", "mean"),
                   where=None, where_ranges: Sequence[tuple] = ()
                   ) -> Dict[str, object]:
        """``SELECT AGG(v), ... `` (no GROUP BY): one global group."""
        from nvme_strom_tpu.sql.groupby import (_norm_aggs,
                                                _stack_values,
                                                _value_cols,
                                                finalize_folds,
                                                groupby_aggregate)
        vcols, single = _value_cols(value_column)
        values = _stack_values(self.columns, vcols, single)
        part = groupby_aggregate(
            jnp.zeros((self.num_rows,), jnp.int32), values, 1,
            aggs=_norm_aggs(aggs),
            mask=self._mask(where, where_ranges), empty_as_nan=False)
        res = finalize_folds(part, aggs)
        return {a: res[a][0] for a in res}

    def topk(self, by: str, columns: Sequence[str] = (), k: int = 10,
             descending: bool = True) -> Dict[str, object]:
        """``SELECT ... ORDER BY by LIMIT k`` over the cached table.

        Deterministic tie order like :func:`multi.multi_topk` (equal
        keys rank by ascending row in BOTH directions) — stricter than
        ``sql_topk``, whose streamed merge leaves ties unspecified.
        The key column is never negated (that would wrap unsigned
        dtypes and INT64_MIN — the same hazard multi_topk documents);
        descending order comes from reversing an ascending lexsort
        whose secondary keys are PRE-reversed.  NaN keys never surface,
        matching ``sql_topk``.  Returns host arrays with ``_row`` as
        global row ids."""
        import numpy as np
        if not 0 < k:
            raise ValueError("k must be positive")
        key = self.column(by)
        rows = jnp.arange(self.num_rows, dtype=jnp.int32)
        if jnp.issubdtype(key.dtype, jnp.floating):
            valid = ~jnp.isnan(key)
            kf = jnp.where(valid, key,
                           -jnp.inf if descending else jnp.inf)
        else:
            valid = jnp.ones((self.num_rows,), bool)
            kf = key
        if descending:
            # pre-reverse the tie-breakers: after [::-1], valid rows
            # precede invalid at equal keys and ties run row-ascending
            order = jnp.lexsort((-rows, valid, kf))[::-1]
        else:
            order = jnp.lexsort((rows, ~valid, kf))
        order = order[:k]
        # every valid row ranks before every invalid one (the fill is
        # the losing infinity, valid breaks the tie), so trimming the
        # invalid tail is a prefix slice
        nv = int(np.asarray(valid[order]).sum())
        order = order[:nv]
        out: Dict[str, object] = {
            c: np.asarray(self.column(c)[order])
            for c in (columns or [by])}
        out["_row"] = np.asarray(rows[order])
        return out

    def star_join_groupby(self, fact_key: str, fact_value: str,
                          dim_table: "DeviceTable", dim_key: str,
                          dim_attr: str, num_groups: int,
                          aggs: Sequence[str] = ("count", "sum",
                                                 "mean"),
                          method: str = "matmul", where=None
                          ) -> Dict[str, jax.Array]:
        """The :func:`join.star_join_groupby` query with BOTH sides
        cached: fact rows join the dimension's unique key, aggregate by
        the dimension attribute — no I/O on either side."""
        from nvme_strom_tpu.sql.groupby import (_norm_aggs,
                                                finalize_folds)
        from nvme_strom_tpu.sql.join import _join_part, check_unique
        fkeys = self.column(fact_key)
        dkeys = dim_table.column(dim_key)
        dattr = dim_table.column(dim_attr)
        # same truncation hazard on BOTH sides: astype would collapse
        # float keys (1.0/1.5 → 1) into silently wrong joins
        for name, arr in ((fact_key, fkeys), (dim_key, dkeys),
                          (dim_attr, dattr)):
            if not jnp.issubdtype(arr.dtype, jnp.integer):
                raise TypeError(f"join column {name} must be integer, "
                                f"got {arr.dtype}")
        check_unique(dkeys)
        kdt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        mask = where(self.columns) if where is not None else None
        # the streaming path's jitted join body — one fused, cached
        # compilation shared with star_join_groupby, not a per-query
        # op-by-op re-derivation
        part = _join_part(dkeys.astype(kdt), dattr.astype(jnp.int32),
                          self.column(fact_key),
                          self.column(fact_value), mask,
                          num_groups=num_groups,
                          aggs=_norm_aggs(aggs), method=method)
        return finalize_folds(part, aggs)


def _estimate_bytes(scanner, columns: Sequence[str]) -> int:
    """Uncompressed resident estimate from footer metadata (the cache
    stores decoded values, so total_uncompressed_size — not the on-disk
    compressed span — is what lands in HBM)."""
    md = scanner.metadata
    names = {md.schema.column(i).name: i
             for i in range(md.num_columns)}
    total = 0
    for c in columns:
        if c not in names:
            raise KeyError(f"column {c!r} not in the table schema")
        for rg in range(md.num_row_groups):
            total += md.row_group(rg).column(
                names[c]).total_uncompressed_size
    return total
