"""Parquet scan through the strom-io engine.

PG-Strom's Direct SQL pulls PostgreSQL table blocks through the reference's
DMA path into GPU scan kernels (SURVEY.md §3.5).  The TPU analogue scans
Parquet: row-group column chunks are read O_DIRECT through the engine and
decoded to columnar arrays that feed the on-device GROUP BY
(:mod:`nvme_strom_tpu.sql.groupby`) — benchmark config 5 (BASELINE.md).

``EngineFile`` adapts the engine to a file-like object, so pyarrow's parquet
reader performs *its own* range reads against O_DIRECT staging buffers —
every payload byte still flows through the engine (and its stats), while
all Parquet encodings/compressions keep working.  The handoff to pyarrow is
one host copy (counted as bounce — decompression/decoding is host compute
by nature; the reference's page-cache fallback pays the same copy).
"""

from __future__ import annotations

import io
from typing import Dict, List, Optional

from nvme_strom_tpu.formats.base import PlanEntry, ReadPlan
from nvme_strom_tpu.io.engine import StromEngine


class EngineFile(io.RawIOBase):
    """Read-only file-like view over an engine file handle.

    Serves ``read()`` from direct-engine reads (chunked if needed).  Each
    serviced byte is copied once into the returned bytes object; that copy
    is counted as a bounce.
    """

    def __init__(self, engine: StromEngine, path):
        super().__init__()
        self.engine = engine
        self.path = str(path)
        self._fh = engine.open(path)
        self._size = engine.file_size(self._fh)
        self._pos = 0

    # -- io protocol --

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = io.SEEK_SET) -> int:
        if whence == io.SEEK_SET:
            self._pos = offset
        elif whence == io.SEEK_CUR:
            self._pos += offset
        elif whence == io.SEEK_END:
            self._pos = self._size + offset
        else:
            raise ValueError(f"bad whence {whence}")
        return self._pos

    def tell(self) -> int:
        return self._pos

    def readinto(self, b) -> int:
        n = min(len(b), self._size - self._pos)
        if n <= 0:
            return 0
        eng = self.engine
        chunk = eng.config.chunk_bytes
        # pipelined chunked read of [pos, pos+n), tagged with the sql
        # scan class (footer/fallback reads are analytics traffic too —
        # per-class budgets and flight-recorder attribution see them)
        pend = [eng.submit_read(self._fh, self._pos + o,
                                min(chunk, n - o), klass="scan")
                for o in range(0, n, chunk)]
        pos = 0
        mv = memoryview(b)
        try:
            while pend:
                p = pend.pop(0)
                view = p.wait()
                mv[pos:pos + view.nbytes] = view  # single handoff copy
                pos += view.nbytes
                p.release()
        finally:
            for p in pend:  # mid-batch failure: free in-flight buffers
                p.release()
        eng.stats.add(bounce_bytes=pos)
        self._pos += pos
        return pos

    def close(self) -> None:
        if not self.closed and getattr(self, "_fh", None) is not None:
            self.engine.close(self._fh)
            self._fh = None
        super().close()

    @property
    def size(self) -> int:
        return self._size


class ParquetScanner:
    """Row-group scan planning + engine-backed decode."""

    def __init__(self, path, engine: StromEngine):
        import pyarrow.parquet as pq
        self.path = str(path)
        self.engine = engine
        # Metadata (footer) via buffered I/O — it is not payload.
        self.metadata = pq.read_metadata(self.path)
        self.schema = self.metadata.schema.to_arrow_schema()

    @property
    def num_row_groups(self) -> int:
        return self.metadata.num_row_groups

    @property
    def num_rows(self) -> int:
        return self.metadata.num_rows

    def plan(self, columns: Optional[List[str]] = None) -> ReadPlan:
        """Byte ranges of the selected column chunks, per row group —
        the scan's I/O footprint (what the direct engine will read)."""
        known = {self.metadata.schema.column(i).name
                 for i in range(self.metadata.num_columns)}
        names = columns or sorted(known)
        missing = set(names) - known
        if missing:
            raise KeyError(f"columns not in schema: {sorted(missing)}")
        entries = []
        for rg in range(self.metadata.num_row_groups):
            g = self.metadata.row_group(rg)
            for ci in range(g.num_columns):
                col = g.column(ci)
                name = col.path_in_schema
                if name not in names:
                    continue
                start = col.data_page_offset
                if (col.dictionary_page_offset is not None
                        and col.dictionary_page_offset > 0):
                    start = min(start, col.dictionary_page_offset)
                entries.append(PlanEntry(
                    key=f"rg{rg}.{name}", offset=start,
                    length=col.total_compressed_size,
                    meta={"row_group": rg, "column": name}))
        return ReadPlan(self.path, tuple(entries))

    def iter_row_groups(self, columns: Optional[List[str]] = None,
                        row_groups: Optional[List[int]] = None):
        """Yield pyarrow Tables, one per (selected) row group, decoded
        from engine-served reads."""
        import pyarrow.parquet as pq
        f = EngineFile(self.engine, self.path)
        try:
            # Reuse the already-parsed footer so metadata I/O stays
            # buffered-side and never pollutes the payload counters.
            pf = pq.ParquetFile(f, metadata=self.metadata, pre_buffer=False)
            groups = (range(pf.metadata.num_row_groups)
                      if row_groups is None else row_groups)
            for rg in groups:
                yield pf.read_row_group(rg, columns=columns)
        finally:
            f.close()

    def prune_row_groups(self, ranges) -> List[int]:
        """Row groups whose column statistics can satisfy every range.

        ``ranges``: iterable of (column, lo, hi) with None = unbounded.
        A row group survives unless some range PROVABLY excludes it
        (stats present and [min, max] disjoint from [lo, hi]) — the
        PG-Strom/Parquet scan-elimination move: entire chunks never
        leave the SSD.  Callers still apply the exact predicate on
        device; pruning is a correct-by-construction superset.
        """
        ranges = list(ranges)   # re-iterated per row group
        name_to_ci = {self.metadata.schema.column(i).name: i
                      for i in range(self.metadata.num_columns)}
        keep: List[int] = []
        for rg in range(self.metadata.num_row_groups):
            g = self.metadata.row_group(rg)
            alive = True
            for col, lo, hi in ranges:
                if col not in name_to_ci:
                    raise KeyError(f"column {col!r} not in schema")
                st = g.column(name_to_ci[col]).statistics
                if st is None or st.min is None or st.max is None:
                    continue          # no stats → cannot exclude
                if ((lo is not None and st.max < lo)
                        or (hi is not None and st.min > hi)):
                    alive = False
                    break
            if alive:
                keep.append(rg)
        return keep

    def direct_reasons(self, columns: List[str]) -> Dict[str, Optional[str]]:
        """Per column: None if EVERY row-group chunk can decode on device
        (pq_direct fast path), else the first blocking reason."""
        from nvme_strom_tpu.sql import pq_direct
        name_to_ci = {self.metadata.schema.column(i).name: i
                      for i in range(self.metadata.num_columns)}
        out: Dict[str, Optional[str]] = {}
        for c in columns:
            out[c] = None
            for rg in range(self.metadata.num_row_groups):
                why = pq_direct.eligible_chunk(self.metadata, rg,
                                               name_to_ci[c])
                if why is not None:
                    out[c] = f"rg{rg}: {why}"
                    break
        return out

    def read_columns_to_device(self, columns: List[str], device=None,
                               dtype_map: Optional[Dict] = None,
                               direct: str = "auto",
                               nulls: str = "forbid"):
        """Scan → device-resident columns (on-device concat of row groups).

        ``direct``: "auto" takes the pq_direct page-span path (payload
        bytes never touched by host except page decompression, decode =
        on-device bitcast/gather) whenever every selected column is
        eligible, else pyarrow; "always" raises on ineligible columns;
        "never" forces pyarrow.

        ``nulls``: "forbid" (default) raises on columns with nulls;
        "mask" returns ``(values, valid_mask)`` per column — null slots
        zero-filled, the mask is the truth (both paths agree on this
        contract).
        """
        import jax
        import jax.numpy as jnp
        from nvme_strom_tpu.ops.bridge import host_to_device
        from nvme_strom_tpu.sql import pq_direct
        dev = device or jax.local_devices()[0]

        if direct not in ("auto", "always", "never"):
            raise ValueError(f"bad direct={direct!r}")
        if nulls not in ("forbid", "mask"):
            raise ValueError(f"bad nulls={nulls!r}")
        if direct != "never":
            # One metadata walk: plan_columns both validates eligibility
            # and computes the page spans (a plan failure IS the
            # fallback signal — e.g. an encoding the footer can't rule
            # out, like a non-PLAIN page discovered mid-walk).
            try:
                plans = pq_direct.plan_columns(
                    self, columns, allow_nulls=nulls == "mask")
            except ValueError:
                if direct == "always":
                    raise
                plans = None
            if plans is not None:
                cols = pq_direct.read_plain_columns_to_device(
                    self, columns, device=dev, plans=plans, nulls=nulls)
                if dtype_map:
                    def cast(c, v):
                        if c not in dtype_map:
                            return v
                        if isinstance(v, tuple):
                            return v[0].astype(dtype_map[c]), v[1]
                        return v.astype(dtype_map[c])
                    cols = {c: cast(c, v) for c, v in cols.items()}
                return cols

        parts: Dict[str, list] = {c: [] for c in columns}
        masks: Dict[str, list] = {c: [] for c in columns}
        for tbl in self.iter_row_groups(columns):
            for c in columns:
                col = tbl.column(c).combine_chunks()
                if col.null_count and nulls == "forbid":
                    raise ValueError(
                        f"column {c} has nulls; pass nulls='mask'")
                if nulls == "mask":
                    masks[c].append(host_to_device(
                        self.engine,
                        col.is_valid().to_numpy(zero_copy_only=False),
                        dev))
                    col = col.fill_null(0)
                arr = col.to_numpy(zero_copy_only=False)
                if dtype_map and c in dtype_map:
                    arr = arr.astype(dtype_map[c])
                parts[c].append(host_to_device(self.engine, arr, dev))
        cat = lambda v: v[0] if len(v) == 1 else jnp.concatenate(v)  # noqa: E731
        if nulls == "mask":
            return {c: (cat(parts[c]), cat(masks[c])) for c in columns}
        return {c: cat(v) for c, v in parts.items()}
