"""ORDER BY <col> [DESC|ASC] LIMIT k over a streamed Parquet scan, on TPU.

The missing third of the PG-Strom consumer triad (SURVEY.md §3.5): scan
(parquet/pq_direct), aggregate/join (groupby/join), and now ORDER BY +
LIMIT pushdown.  PG-Strom sorts/limits on the GPU so only k result rows
return to host; the TPU formulation is a *streaming top-k merge*:

  - each row group's columns land on device via the usual direct path;
  - a jitted merge keeps the current best-k rows ON DEVICE — concat the
    carried k candidates with the group's N rows, ``argsort`` (stable,
    native dtype: no float-rank precision loss on integer keys), slice
    k.  Device memory holds one row group + k rows, never the table;
  - only the final k rows cross back to host.

LIMIT pushdown with scan elimination: row groups are visited in order of
their footer statistic bound (max for DESC, min for ASC; missing stats
sort first so they are never skipped), and once k valid rows are held,
any remaining group whose bound provably cannot beat the current k-th
row is skipped — its payload never leaves the SSD, the same
statistics-driven elimination ``prune_row_groups`` does for WHERE.

Ordering semantics: ties beyond position k are unspecified (as in SQL);
NaN keys and (with ``nulls="skip"``) NULL rows never surface.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from nvme_strom_tpu.sql.groupby import _range_mask, iter_device_columns


def _sentinel(dtype, descending: bool):
    """The key value an invalid row is given so it always loses."""
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf if descending else jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.min if descending else info.max, dtype)


@partial(jax.jit, static_argnames=("k", "descending"))
def _merge_topk(key, vals, row, valid, k: int, descending: bool):
    """Best-k rows of (key, vals, row, valid) by key.  ``k`` ≤ len(key)
    is static; callers pass the concatenation of the carried candidates
    and one row group, so one compiled merge serves the whole stream
    (per distinct row-group length)."""
    if jnp.issubdtype(key.dtype, jnp.floating):
        valid = valid & ~jnp.isnan(key)
    skey = jnp.where(valid, key, _sentinel(key.dtype, descending))
    # lexsort, validity secondary: a VALID row whose key *equals* the
    # sentinel (a real -inf/iinfo-min value) must still beat invalid
    # rows, or WHERE-filtered rows would displace it from the carry
    if descending:
        # ascending sort, invalid first among ties → after the reversal
        # valid rows precede invalid ones
        order = jnp.lexsort((valid, skey))
        idx = order[::-1][:k]
    else:
        # ascending, valid first among ties
        order = jnp.lexsort((~valid, skey))
        idx = order[:k]
    return (key[idx], {c: v[idx] for c, v in vals.items()},
            row[idx], valid[idx])


def _rg_bound(scanner, rg: int, ci: int, descending: bool):
    """The best key value row group ``rg`` could possibly contain, per
    footer statistics — or None when stats are absent (no claim)."""
    st = scanner.metadata.row_group(rg).column(ci).statistics
    if st is None or st.min is None or st.max is None:
        return None
    return st.max if descending else st.min


def _beats(bound, worst, descending: bool) -> bool:
    """Could a row at ``bound`` displace the current k-th row ``worst``?
    Strict comparison: a tie cannot improve the top-k multiset."""
    return bound > worst if descending else bound < worst


def sql_topk(scanner, by: str, columns: Sequence[str] = (),
             k: int = 10, descending: bool = True, device=None,
             where=None, where_columns: Sequence[str] = (),
             where_ranges: Sequence[tuple] = (),
             nulls: str = "forbid") -> Dict[str, np.ndarray]:
    """``SELECT by, columns... FROM parquet [WHERE ...] ORDER BY by
    [DESC] LIMIT k`` — streamed, merged on device, statistics-skipped.

    Returns {name: (m,) numpy} for ``by`` and every name in ``columns``,
    plus ``"_row"`` (int32 global row index — result provenance) and
    ``"_skipped_row_groups"`` (int: groups the LIMIT elimination proved
    irrelevant — their payload was never read), with m ≤ k (m < k only
    when fewer rows survive the WHERE/NULL masks), in result order.

    ``where``/``where_columns``/``where_ranges``: the same on-device
    WHERE pushdown + footer-statistics row-group pruning as
    ``sql_groupby``.  ``nulls="skip"`` drops rows where ANY referenced
    column is NULL (SQL three-valued logic); "forbid" raises on NULLs.
    """
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if nulls not in ("forbid", "skip"):
        raise ValueError(f"bad nulls={nulls!r}")
    where_ranges = list(where_ranges)
    dev = device or jax.local_devices()[0]
    out_cols = list(dict.fromkeys([by, *columns]))
    range_cols = [c for c, _, _ in where_ranges]
    cols_needed = list(dict.fromkeys(
        [*out_cols, *where_columns, *range_cols]))
    full_where = ((lambda cols: _range_mask(cols, where_ranges, where))
                  if (where_ranges or where is not None) else None)

    # row groups the WHERE ranges allow, ordered by how good their best
    # possible key is — the LIMIT-elimination visit order
    rgs = (scanner.prune_row_groups(where_ranges) if where_ranges
           else list(range(scanner.num_row_groups)))
    name_to_ci = {scanner.metadata.schema.column(i).name: i
                  for i in range(scanner.metadata.num_columns)}
    if by not in name_to_ci:
        raise KeyError(f"column {by!r} not in schema")
    ci = name_to_ci[by]
    bounds = {rg: _rg_bound(scanner, rg, ci, descending) for rg in rgs}
    # missing stats order FIRST (best-possible bound ⇒ never skipped);
    # bounded groups sort on the EXACT stat value (no float() cast —
    # int64 bounds above 2^53 must order consistently with _beats, or
    # the elimination break could skip a group that still wins)
    unbounded = [rg for rg in rgs if bounds[rg] is None]
    bounded = sorted((rg for rg in rgs if bounds[rg] is not None),
                     key=lambda rg: bounds[rg], reverse=descending)
    rgs = unbounded + bounded
    # global row offset of each row group, for the _row provenance
    row_base, acc = {}, 0
    for rg in range(scanner.num_row_groups):
        row_base[rg] = acc
        acc += scanner.metadata.row_group(rg).num_rows

    carry = None          # (key (k,), vals {c: (k,)}, row (k,), valid (k,))
    skipped_rgs = 0

    def fold(rg_index: int, cols, base_mask):
        nonlocal carry
        key = cols[by]
        n = key.shape[0]
        row = jnp.arange(n, dtype=jnp.int32) + np.int32(row_base[rg_index])
        valid = jnp.ones((n,), bool)
        if full_where is not None:
            valid = valid & full_where(cols)
        if base_mask is not None:
            valid = valid & base_mask
        vals = {c: cols[c] for c in out_cols}
        if carry is not None:
            ckey, cvals, crow, cvalid = carry
            key = jnp.concatenate([ckey, key])
            row = jnp.concatenate([crow, row])
            valid = jnp.concatenate([cvalid, valid])
            vals = {c: jnp.concatenate([cvals[c], vals[c]])
                    for c in out_cols}
        kk = min(k, int(key.shape[0]))
        carry = _merge_topk(key, vals, row, valid, kk, descending)

    # one page walk for the whole query; each elimination window below
    # reuses it instead of re-walking every page per window
    from nvme_strom_tpu.sql import pq_direct
    plans = pq_direct.try_plan(scanner, cols_needed,
                               allow_nulls=nulls == "skip")

    def group_stream(batch):
        if nulls == "skip":
            for cols, masks in iter_device_columns(
                    scanner, cols_needed, dev, row_groups=batch,
                    nulls="mask", plans=plans):
                base = None
                for c in cols_needed:
                    base = masks[c] if base is None else base & masks[c]
                yield cols, base
        else:
            for cols in iter_device_columns(scanner, cols_needed, dev,
                                            row_groups=batch,
                                            plans=plans):
                yield cols, None

    # Windowed streaming with exact elimination accounting: groups are
    # pulled in exponentially growing windows (1, 2, 4, 8, 8, ...), and
    # the LIMIT-elimination check runs once per window BEFORE its reads
    # are submitted — since bounded groups are visited best-bound-first,
    # the first remaining group's bound failing to beat the carried
    # k-th row proves every later group irrelevant.  Why windows rather
    # than the round-3 per-group loop: the per-group check cost two
    # device→host syncs per row group (a stop-and-wait round-trip each
    # on a high-latency link — the ledgered 3.5s/22M-row scans), while
    # each window streams as ONE pipelined range sequence; the ramp
    # bounds over-read at <2x of perfectly-lazy while the sorted-column
    # query still reads exactly one group.  `_skipped_row_groups` stays
    # exact: a skipped group's reads were never submitted.
    pos = 0
    window = 1
    while pos < len(rgs):
        if carry is not None and carry[0].shape[0] == k:
            if np.asarray(carry[3]).all():
                worst = np.asarray(carry[0])[-1]
                b = bounds[rgs[pos]]
                if b is not None and not _beats(b, worst, descending):
                    skipped_rgs = len(rgs) - pos
                    break
        batch = rgs[pos:pos + window]
        for rg, (cols, base) in zip(batch, group_stream(batch)):
            fold(rg, cols, base)
        # warm the next check's host copy while the link is still busy
        # with this window — the sync above then finds the bytes ready
        # instead of paying a fresh round-trip
        for a in (carry[0], carry[3]):
            if hasattr(a, "copy_to_host_async"):
                a.copy_to_host_async()
        pos += len(batch)
        window = min(window * 2, 8)

    if carry is None:
        raise ValueError("empty table (no row groups survive pruning)")
    key, vals, row, valid = carry
    m = int(np.asarray(valid).sum())
    out = {c: np.asarray(vals[c])[:m] for c in out_cols}
    out["_row"] = np.asarray(row)[:m]
    out["_skipped_row_groups"] = skipped_rgs    # elimination evidence
    return out
