"""GROUP BY aggregation on TPU.

The compute half of the PG-Strom-style scan (SURVEY.md §3.5): filtered /
projected columns live on device, the aggregate runs there, and only the
(tiny) per-group results return to host — the whole point of pushing the
scan to the accelerator.

Two jit-friendly formulations, both with static ``num_groups``:

- ``method="matmul"``: segment-sum as ``one_hot(keys).T @ values`` — a
  (G×N)·(N,) matmul the XLA TPU backend tiles onto the MXU.  The idiomatic
  TPU answer for moderate G (≤ a few thousand): turns a scatter into dense
  FLOPs the systolic array eats for free.
- ``method="scatter"``: ``jax.ops.segment_*`` (scatter-add lowering) for
  large G where the one-hot would dominate memory.

Supported aggregates: count, sum, mean, min, max.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Sequence

import jax
import jax.numpy as jnp

_AGGS = ("count", "sum", "mean", "min", "max")


@partial(jax.jit, static_argnames=("num_groups", "aggs", "method"))
def groupby_aggregate(keys: jax.Array, values: jax.Array, num_groups: int,
                      aggs: Sequence[str] = ("count", "sum", "mean"),
                      method: str = "matmul") -> Dict[str, jax.Array]:
    """Aggregate ``values`` (N,) or (N, C) by integer ``keys`` (N,) in
    [0, num_groups). Returns {agg: (num_groups,) or (num_groups, C)}."""
    for a in aggs:
        if a not in _AGGS:
            raise ValueError(f"unknown aggregate {a!r}")
    if method not in ("matmul", "scatter"):
        raise ValueError(f"unknown method {method!r}")
    squeeze = values.ndim == 1
    vals = values[:, None] if squeeze else values
    vals_f = vals.astype(jnp.float32)

    if method == "matmul":
        # Segment-sum as a dense (N,G)x(N,C) contraction on the MXU.
        # one_hot entries are exact in any float dtype; values stay f32
        # so sums match the scatter path bit-for-bit-ish.
        onehot = jax.nn.one_hot(keys, num_groups, dtype=jnp.float32)
        ones = jnp.ones((vals_f.shape[0], 1), jnp.float32)
        summed = jnp.einsum("ng,nc->gc", onehot, vals_f,
                            preferred_element_type=jnp.float32)
        count = jnp.einsum("ng,nc->gc", onehot, ones,
                           preferred_element_type=jnp.float32)[:, 0]
    else:
        summed = jax.ops.segment_sum(vals_f, keys, num_groups)
        count = jax.ops.segment_sum(jnp.ones_like(keys, jnp.float32),
                                    keys, num_groups)

    out: Dict[str, jax.Array] = {}
    if "count" in aggs:
        out["count"] = count.astype(jnp.int32)
    if "sum" in aggs or "mean" in aggs:
        if "sum" in aggs:
            out["sum"] = summed[:, 0] if squeeze else summed
        if "mean" in aggs:
            mean = summed / jnp.maximum(count, 1.0)[:, None]
            mean = jnp.where(count[:, None] > 0, mean, jnp.nan)
            out["mean"] = mean[:, 0] if squeeze else mean
    if "min" in aggs:
        m = jax.ops.segment_min(vals_f, keys, num_groups)
        out["min"] = m[:, 0] if squeeze else m
    if "max" in aggs:
        m = jax.ops.segment_max(vals_f, keys, num_groups)
        out["max"] = m[:, 0] if squeeze else m
    return out


def sql_groupby(scanner, key_column: str, value_column: str,
                num_groups: int, aggs: Sequence[str] = ("count", "sum",
                                                        "mean"),
                method: str = "matmul", device=None) -> Dict[str, jax.Array]:
    """End-to-end config-5 query:

        SELECT key, AGG(value) FROM parquet GROUP BY key

    Row groups stream through the engine and are aggregated on device
    incrementally — partial sums/counts/min/max fold across row groups, so
    device memory holds one row group of columns at a time, not the table.
    """
    import numpy as np
    from nvme_strom_tpu.ops.bridge import host_to_device

    dev = device or jax.local_devices()[0]

    folds = None
    for tbl in scanner.iter_row_groups([key_column, value_column]):
        keys = tbl.column(key_column).to_numpy(zero_copy_only=False)
        vals = tbl.column(value_column).to_numpy(zero_copy_only=False)
        if not np.issubdtype(keys.dtype, np.integer):
            raise TypeError(f"key column {key_column} must be integer")
        kd = host_to_device(scanner.engine, keys.astype(np.int32), dev)
        vd = host_to_device(scanner.engine, vals, dev)
        part = groupby_aggregate(
            kd, vd, num_groups,
            aggs=tuple(sorted((set(aggs) | {"count", "sum"}) - {"mean"})),
            method=method)
        folds = part if folds is None else _fold(folds, part)

    if folds is None:
        raise ValueError("empty table")
    out: Dict[str, jax.Array] = {}
    count = folds["count"]
    if "count" in aggs:
        out["count"] = count
    if "sum" in aggs:
        out["sum"] = folds["sum"]
    if "mean" in aggs:
        cf = count.astype(jnp.float32)
        mean = folds["sum"] / jnp.maximum(cf, 1.0)
        out["mean"] = jnp.where(cf > 0, mean, jnp.nan)
    if "min" in aggs:
        out["min"] = folds["min"]
    if "max" in aggs:
        out["max"] = folds["max"]
    return out


@jax.jit
def _fold(a: Dict[str, jax.Array], b: Dict[str, jax.Array]):
    out = {}
    for k in a:
        if k == "count" or k == "sum":
            out[k] = a[k] + b[k]
        elif k == "min":
            out[k] = jnp.minimum(a[k], b[k])
        elif k == "max":
            out[k] = jnp.maximum(a[k], b[k])
        else:  # mean folds from sum/count at the end
            out[k] = a[k]
    return out
